"""Seeded chaos harness: randomized failpoint schedules vs. the standing
invariants.

Each schedule arms 1-3 deterministic failpoints (testing/failpoints.py)
from a seeded menu — torn frames, stuck connects, failing fsyncs,
mid-ingest faults — runs a semi-sync write workload against a 3-node
replication cluster (leader + 2 followers over real TCP loopback, the
test_replication Host shape) plus periodic SST bulk-ingests through the
real AdminHandler path, clears the faults, waits for recovery, and
checks the three standing invariants:

1. **hole-free WAL prefix** on every node — seq ranges tile with no gap;
2. **zero acked-write loss** — every write whose ack future resolved
   ``acked`` is readable on the leader AND both followers once the
   cluster reconverges;
3. **ingest atomicity / no partial meta** — a fault anywhere in the
   ingest pipeline leaves either no meta claim, or a meta claim with
   every ingested key readable; a clean retry then always completes.

Everything is derived from ``--seed``: the fault menu draws, the torn
offsets and probability rolls (per-site seeded RNGs), the jittered
retry backoffs (RSTPU_RETRY_SEED / RSTPU_PULL_RETRY_SEED). A violation
prints the reproducing command line and exits 1.

``--break-guard`` deliberately breaks a guard to prove the harness has
teeth (the acceptance demo):

- ``wal_hole``    — WalWriter.append claims a durability token for every
  5th record without writing it (an ack-without-WAL bug): invariant 1
  must catch the hole;
- ``meta_first``  — the ingest handler writes DBMetaData BEFORE the
  engine ingest (the crash-ordering bug the r8 seam exists to prevent):
  invariant 3 must catch meta-without-data.

With ``--expect-violation`` the run exits 0 iff a violation WAS caught.

``--failover`` switches to the COORDINATOR-BACKED schedule menu
(round 11): a durable coordinator primary + replicated standby, a
Controller, a Spectator publishing the shard map, and 3 participant
hosts running one replicas=3 semi-sync shard. Seeded schedules kill the
acting leader while it holds a full AckWindow (heartbeats wedged, data
plane alive — the classic deposed-but-running leader), expire
participant sessions mid-write via the ``coordinator.heartbeat`` seam,
kill the coordinator primary, torn-write the coordinator WAL
(``coordinator.wal.append``), and blip the
``participant.transition`` / ``controller.assign`` /
``shardmap.publish`` / ``coordinator.reap`` seams. After EVERY schedule
the harness holds the **fourth standing invariant**:

4. **failover under fault** — exactly one LEADER per shard (current
   states AND the published shard map), zero acked-write loss across
   the handoff (strict ledger: pre-fault + post-promotion acks; acks
   landing inside the visibility window are counted separately), zero
   stale acks (a deposed leader must not ack a single write after the
   new leader's epoch is visible — enforced by the end-to-end fencing
   epochs), and shard-map convergence within a bounded number of
   controller passes.

5. **bounded-staleness + lineage reads** (round 13) — after every
   healed schedule, reads with a ``max_lag`` bound are issued at every
   replica: ZERO served reads may violate the bound (checked exactly:
   the workload is quiesced, the read-info TTL slept out, and the
   leader's committed seq sampled BEFORE the reads — any served read
   must have ``applied_seq >= L0 - bound``) and ZERO reads may be
   served from a deposed lineage (the leader-crash schedule probes the
   fenced ex-leader directly: reads there must raise STALE_EPOCH, with
   and without the new epoch on the request). Bounces are always
   legal; wrong serves never are.

``--reshard`` (round 15) runs LIVE SHARD MOVES under fault: a 4-node /
3-replica coordinator-backed cluster where seeded schedules drive the
resumable move step machine (``cluster/shard_move.py``: snapshot →
bulk-ingest → WAL-tail catch-up → epoch-bumped pinned flip → retire)
with continuous write load riding through every phase, and kill every
actor at every seam — the move coordinator at each of its failpoint
phases (``move.record/snapshot/restore/catchup/flip/retire``), the
source and target participants mid-move, the coordinator primary
(kill + torn WAL during the flip), plus cluster-wide session expiry
mid-catch-up and data-plane faults riding a whole move. After EVERY
schedule the harness holds the **sixth standing invariant**:

6. **live moves under fault** — exactly ONE serving lineage per shard
   (current states, the published shard map, and the data plane agree
   on one unfenced leader; two coexisting unfenced leaders at any
   sampled instant is a violation), zero acked-write loss across the
   move (every acked key readable on every CURRENT host — the hosting
   set itself moved; plus the sharp probe: the instant a cutover
   claims completion, every already-acked write must be readable on
   the NEW leader), bounded convergence (controller-pass bound), and
   no stranded replicas (a non-host still holding the db is un-swept
   move garbage — aborts must sweep the target, retires the source).
   A killed mover must leave the move either cleanly aborted or
   resumable to completion — a move that can do neither is the
   half-flipped-map state and a violation by itself.

``--rebalance`` (round 20) runs the AUTONOMOUS REBALANCER: a 4-node /
2-hash-shard cluster where the harness only drives SKEWED write load —
it never names a source, target, or split key. The policy loop
(``cluster/rebalancer.py``) must sense the sustained hot shard from
real per-db rates (EWMA + hysteresis + consecutive-tick sustain),
plan, and dispatch the live move — or, past the split threshold, the
hot-shard RANGE SPLIT (``cluster/shard_split.py``: snapshot → hidden
observer → catch-up → paused-drain fenced cutover renaming the parent
into range children). Schedules blip every rebalancer seam
(``rebalance.decide/plan/dispatch`` — the tick's work re-derives from
durable ledgers on the next tick), kill a dispatched move mid-catch-up,
and kill the splitter AT ``split.cutover``; both must finish via
resume. After EVERY schedule the harness holds the **seventh standing
invariant**:

7. **policy-initiated placement** — every LEAF of the split forest
   converges (one unfenced leader per child in the current states, the
   published map — including its ``__splits__`` routing records — and
   the data plane), zero acked-write loss where each acked key is
   checked on the child OWNING its range (resolved through the split
   records exactly as the router resolves it), the split-retired
   parent lineage gone from every node, and bounded convergence. The
   sharp probe runs WAITLESS once converged: an acked tail lost at a
   split cutover can never heal and must be caught, not outwaited.

``--cdc`` (round 21) runs the CDC STREAMING INGEST deck (``cdc_burst``):
an embedded broker feeds the exactly-once consumer
(``kafka/ingestion.py``) applying into a 3-replica semi-sync group,
while seeded schedules kill the consumer at every registered seam
(``kafka.fetch`` / ``kafka.apply`` / ``kafka.checkpoint``) mid-batch,
run multi-kill bursts, and depose the leader mid-consume (the consumer
restarted against the promoted follower resumes from ITS replicated
watermark). After EVERY schedule the harness holds the **eighth
standing invariant**:

8. **CDC exactly-once** — applied records == the produced prefix,
   exactly once, per partition, on every replica of the serving
   lineage: the durable watermark equals the produced count, the
   applies-counter witness equals it too (record applies are idempotent
   upserts, so a re-apply is INVISIBLE to state-compare — only the
   counter riding the records batches can see a duplicate), and the
   readable state equals the fold of the produced log (catching drops,
   doubled deletes, and lost overwrites).

- ``fencing`` (``--failover`` only) — the leader IGNORES epochs
  (``ReplicatedDB._reject_stale_epoch`` patched to a no-op): the
  stale-frame probes in the leader-crash schedule must catch it acking
  writes after deposition (SPLIT BRAIN).
- ``move_flip`` (``--reshard`` only) — the naive cutover: no write
  pause, no tail drain, no two-phase demote — force-promote the
  target's data plane the moment catch-up is "close": the lineage
  probes must catch the two coexisting serving lineages / the acked
  tail missing on the new leader.
- ``split_cutover`` (``--rebalance`` only) — the naive SPLIT cutover:
  "the snapshot is good enough" — the hidden observer's WAL-tail pull
  severed, catch-up skipped, and the paused drain-to-exact-equality
  no-op'd before the rename. The REAL cutover refuses to flip a
  non-drained child; the naive one renames a frozen snapshot into the
  high child — keys at/above the split key acked after the snapshot
  are absent from the child that owns them FOREVER: the per-child
  acked-readability probe must catch the loss.
- ``cdc_dedup`` (``--cdc`` only) — the at-least-once consumer a naive
  port would ship: the offset checkpoint DECOUPLED from its apply batch
  (records commit first, the watermark follows in a separate write). A
  kill between the two leaves applied records above a stale watermark;
  resume re-applies them. The re-apply is invisible to state-compare
  (idempotent upserts) — the applies-counter witness must catch
  ``applies_total > produced`` at quiesce.
- ``mux_misroute`` (data-plane only; forces ``RSTPU_PULL_MUX=1``) — the
  session-demux bug class: the mux serve files one shard's updates
  under its sibling's section key, seqs restamped off the victim's
  cursor (the index-off-by-one a mux serve loop can ship). The batch is
  perfectly continuous, so the apply-side guard cannot reject it — the
  zero-acked-loss / reconvergence invariants over BOTH chaos shards
  must catch the cross-shard bleed.

Usage::

    python -m tools.chaos_soak --schedules 20 --seed 1          # soak
    python -m tools.chaos_soak --break-guard wal_hole \
        --expect-violation                                      # teeth
    python -m tools.chaos_soak --failover --schedules 15 --seed 1
    python -m tools.chaos_soak --failover --break-guard fencing \
        --expect-violation                                      # tooth
    python -m tools.chaos_soak --reshard --schedules 15 --seed 1
    python -m tools.chaos_soak --reshard --break-guard move_flip \
        --expect-violation                                      # tooth
    python -m tools.chaos_soak --rebalance --schedules 3 --seed 1
    python -m tools.chaos_soak --rebalance --break-guard split_cutover \
        --expect-violation                                      # tooth
    python -m tools.chaos_soak --cdc --schedules 5 --seed 1
    python -m tools.chaos_soak --cdc --break-guard cdc_dedup \
        --expect-violation                                      # tooth
    RSTPU_PULL_MUX=1 python -m tools.chaos_soak --schedules 6   # mux deck
    python -m tools.chaos_soak --break-guard mux_misroute \
        --expect-violation                                      # tooth
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import random
import shutil
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

from rocksplicator_tpu.replication import (  # noqa: E402
    ReplicaRole,
    ReplicationFlags,
    Replicator,
    StorageDbWrapper,
)
from rocksplicator_tpu.storage import DB, DBOptions, WriteBatch
from rocksplicator_tpu.storage import wal as wal_mod
from rocksplicator_tpu.storage.records import OpType, scan_batch_meta
from rocksplicator_tpu.storage.sst import SSTWriter
from rocksplicator_tpu.testing import failpoints as fp
from rocksplicator_tpu.utils.objectstore import LocalObjectStore

DB_NAME = "seg00001"
# sibling shard on the same 3 hosts (round 22): with RSTPU_PULL_MUX=1
# every follower's pull session to the leader carries BOTH shards'
# sections, so session-level faults and the mux_misroute tooth exercise
# real multi-shard demux — and the standing invariants cover
# cross-shard bleed
DB2_NAME = "seg00002"

# quick-recovery flags: chaos wants many fault→heal cycles per minute,
# not the reference's production 5-10s backoffs
FLAGS = ReplicationFlags(
    server_long_poll_ms=300,
    pull_error_delay_min_ms=30,
    pull_error_delay_max_ms=250,
    ack_timeout_ms=800,
    consecutive_timeouts_to_degrade=1000,
    empty_pulls_before_reset=1 << 30,
    write_window=32,
)

DB_OPTS = dict(
    memtable_bytes=32 * 1024,  # continuous flush/compaction churn
    background_compaction=True,
    level0_compaction_trigger=3,
)

# Make key-range subcompactions REACHABLE at chaos scale: the
# production threshold (32k entries per slice) would never slice the
# tiny chaos memtables, leaving the compact.subcompact seam unarmed in
# every schedule. The in-process chaos clusters inherit this.
from rocksplicator_tpu.storage import native_compaction as _nc  # noqa: E402

_nc.MIN_SLICE_ENTRIES = 256

# Same for the streaming bounded-memory merge (round 17): chaos-scale
# compactions are a few thousand entries, far under the auto threshold,
# so force streaming as the default full-compaction path with chunk
# windows small enough that every compaction crosses multiple
# compact.stream.chunk/refill seams. A stream fault mid-chunk sweeps
# the partial outputs and the engine falls back (or retries) — the
# ingest-atomicity invariant rides every schedule.
from rocksplicator_tpu.storage import stream_merge as _sm  # noqa: E402

_sm.STREAM_MODE_OVERRIDE = "always"
_sm.CHUNK_ENTRIES_OVERRIDE = 512


def _fault_menu(rng: random.Random) -> List[Tuple[str, str]]:
    """The schedule's candidate faults — every parameter drawn from the
    schedule RNG, every probabilistic policy pinned to a drawn seed."""
    s = rng.randrange(1 << 16)
    return [
        ("wal.fsync", f"delay_ms:{rng.randint(5, 40)}"),
        ("wal.append", f"torn:{rng.uniform(0.02, 0.15):.3f}@seed{s}"),
        ("sst.fsync", f"delay_ms:{rng.randint(5, 40)}"),
        ("manifest.persist", f"fail_nth:{rng.randint(1, 4)}"),
        ("manifest.persist", f"delay_ms:{rng.randint(5, 30)}"),
        ("rpc.frame.send", f"torn:{rng.uniform(0.01, 0.08):.3f}@seed{s}"),
        ("rpc.frame.send",
         f"fail_prob:{rng.uniform(0.01, 0.08):.3f}@seed{s}"),
        ("rpc.frame.recv",
         f"fail_prob:{rng.uniform(0.005, 0.04):.3f}@seed{s}"),
        ("rpc.connect", f"fail_first:{rng.randint(1, 3)}"),
        ("rpc.connect",
         f"delay_ms:{rng.randint(20, 120)}:{rng.uniform(0.1, 0.4):.2f}"
         f"@seed{s}"),
        ("repl.pull", f"fail_prob:{rng.uniform(0.02, 0.10):.3f}@seed{s}"),
        ("repl.apply", f"fail_nth:{rng.randint(1, 3)}"),
        ("ack.expire", f"delay_ms:{rng.randint(5, 50)}"),
        # round 16: the workload-adaptive compaction scheduler's seams —
        # the chaos DBs run background compaction with the scheduler
        # active, so pick faults (loop retries), subcompaction slice
        # faults (fall back to the unsliced/tuple merge), and IO-budget
        # yield delays all ride the standing data-plane invariants
        ("compact.pick", f"fail_prob:{rng.uniform(0.05, 0.25):.3f}@seed{s}"),
        ("compact.subcompact", f"fail_nth:{rng.randint(1, 3)}"),
        ("compact.yield", f"delay_ms:{rng.randint(5, 30)}"),
        # round 17: the streaming bounded-memory merge runs as the
        # default full-compaction path at chaos scale (see the
        # STREAM_MODE_OVERRIDE block above) — kill it mid-chunk and
        # mid-refill; outputs are swept, nothing installs, the
        # invariants must hold
        ("compact.stream.chunk", f"fail_nth:{rng.randint(1, 4)}"),
        ("compact.stream.refill",
         f"fail_prob:{rng.uniform(0.02, 0.15):.3f}@seed{s}"),
        # round 22: the mux session seams — a serve fault fails the
        # WHOLE multiplexed response (every section of the session
        # retries together, the torn-session shape), an apply fault
        # kills ONE section's client-side demux handoff. With
        # RSTPU_PULL_MUX=1 the decks cross them on every pull round;
        # with mux off they arm but the per-shard path never trips them
        ("repl.mux.serve",
         f"fail_prob:{rng.uniform(0.02, 0.10):.3f}@seed{s}"),
        ("repl.mux.apply", f"fail_nth:{rng.randint(1, 3)}"),
    ]


_INGEST_FAULTS = [
    None,
    ("admin.ingest.engine", "fail_nth:1"),
    ("admin.ingest.meta", "fail_nth:1"),
    ("engine.ingest", "fail_nth:1"),
    ("sst.ingest_footer", "fail_nth:1"),
    ("objectstore.get", "fail_first:1"),  # absorbed by the batch retry
    ("objectstore.get", "fail_first:6"),  # outlasts it — RPC must fail
]


class ChaosCluster:
    """Leader + 2 followers over TCP loopback, semi-sync (mode 1), two
    shards per host (DB_NAME + DB2_NAME) so muxed pull sessions carry
    multiple sections."""

    def __init__(self, root: str):
        self.root = root
        self.hosts: List[Replicator] = [
            Replicator(port=0, flags=FLAGS) for _ in range(3)]
        self.dbs: List[DB] = []
        self.dbs2: List[DB] = []
        self.rdbs = []
        self.rdbs2 = []
        leader_addr = ("127.0.0.1", self.hosts[0].port)
        for i, rep in enumerate(self.hosts):
            role = ReplicaRole.LEADER if i == 0 else ReplicaRole.FOLLOWER
            db = DB(os.path.join(root, f"n{i}", DB_NAME),
                    DBOptions(**DB_OPTS))
            self.dbs.append(db)
            self.rdbs.append(rep.add_db(
                DB_NAME, StorageDbWrapper(db), role,
                upstream_addr=None if i == 0 else leader_addr,
                replication_mode=1,
            ))
            db2 = DB(os.path.join(root, f"n{i}", DB2_NAME),
                     DBOptions(**DB_OPTS))
            self.dbs2.append(db2)
            self.rdbs2.append(rep.add_db(
                DB2_NAME, StorageDbWrapper(db2), role,
                upstream_addr=None if i == 0 else leader_addr,
                replication_mode=1,
            ))

    @property
    def leader(self):
        return self.rdbs[0]

    @property
    def leader2(self):
        return self.rdbs2[0]

    def converged(self) -> bool:
        for group in (self.dbs, self.dbs2):
            lat = group[0].latest_sequence_number_relaxed()
            if any(db.latest_sequence_number_relaxed() != lat
                   for db in group[1:]):
                return False
        return True

    def wait_converged(self, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.converged():
                return True
            time.sleep(0.05)
        return self.converged()

    def stop(self) -> None:
        for rep in self.hosts:
            rep.stop()
        for db in self.dbs + self.dbs2:
            db.close()


def check_wal_contiguous(db: DB) -> Optional[str]:
    """Invariant 1: the WAL's surviving records tile seq space with no
    hole (purge only ever trims a fully-persisted prefix)."""
    prev_end = None
    for start_seq, raw in wal_mod.iter_updates(
            os.path.join(db.path, "wal"), 0):
        count, _ts = scan_batch_meta(raw)
        if prev_end is not None and start_seq != prev_end + 1:
            return (f"WAL hole: record at seq {start_seq} follows "
                    f"seq {prev_end} (gap of {start_seq - prev_end - 1})")
        prev_end = start_seq + count - 1
    return None


class IngestFixture:
    """SST bulk-ingest through the real AdminHandler path, one fresh db
    per step, with one ingest-class fault armed per step."""

    def __init__(self, root: str, replicator: Replicator):
        from rocksplicator_tpu.admin.handler import AdminHandler

        self.bucket = os.path.join(root, "bucket")
        self.store = LocalObjectStore(self.bucket)
        self.handler = AdminHandler(
            os.path.join(root, "admin"), replicator)
        self.counter = 0

    def step(self, rng: random.Random, violations: List[str],
             tag: str) -> None:
        self.counter += 1
        db_name = f"ing{self.counter:05d}"
        prefix = f"set{self.counter:05d}"
        items = [
            (b"k%05d" % j, b"v%05d" % (j % 997))
            for j in range(rng.randint(40, 120))
        ]
        tmp_sst = os.path.join(self.bucket, "_mk.tsst")
        w = SSTWriter(tmp_sst)
        for k, v in items:
            w.add(k, 0, OpType.PUT, v)
        w.finish()
        self.store.put_object(tmp_sst, f"{prefix}/bulk.tsst")
        os.remove(tmp_sst)
        asyncio.run(self.handler.handle_add_db(
            db_name=db_name, role="NOOP"))
        fault = rng.choice(_INGEST_FAULTS)
        if fault is not None:
            fp.activate(*fault)
        ok, err = True, None
        try:
            asyncio.run(self.handler.handle_add_s3_sst_files_to_db(
                db_name=db_name, s3_bucket=self.bucket, s3_path=prefix,
                compact_db_after_load=rng.random() < 0.5))
        except Exception as e:
            ok, err = False, e
        finally:
            if fault is not None:
                fp.deactivate(fault[0])
        msg = self._check(db_name, prefix, items, must_claim=ok)
        if msg:
            violations.append(f"{tag}: ingest fault={fault}: {msg}")
            return
        if not ok:
            # faults cleared: one clean retry must complete the load
            try:
                asyncio.run(self.handler.handle_add_s3_sst_files_to_db(
                    db_name=db_name, s3_bucket=self.bucket,
                    s3_path=prefix))
            except Exception as e:
                violations.append(
                    f"{tag}: ingest retry after fault={fault} "
                    f"(first error {err!r}) failed: {e!r}")
                return
            msg = self._check(db_name, prefix, items, must_claim=True)
            if msg:
                violations.append(
                    f"{tag}: ingest fault={fault} post-retry: {msg}")

    def _check(self, db_name: str, prefix: str, items,
               must_claim: bool) -> Optional[str]:
        """Invariant 3: meta claims the set ⇒ every key is readable
        (never partial meta); a successful RPC ⇒ meta claims it."""
        meta = self.handler.get_meta_data(db_name)
        claims = (meta.s3_bucket == self.bucket
                  and meta.s3_path == prefix)
        if must_claim and not claims:
            return "ingest RPC succeeded but meta does not claim the set"
        if not claims:
            return None  # fully pre-ingest (data may exist un-claimed)
        app_db = self.handler.db_manager.get_db(db_name)
        for k, v in items:
            got = app_db.db.get(k)
            if got != v:
                return (f"meta claims {prefix} but key {k!r} reads "
                        f"{got!r} (want {v!r}) — partial meta")
        return None

    def close(self) -> None:
        self.handler.close()


# seams the remote-compaction fixture arms one-at-a-time (registration
# asserted by the registry pass like the ingest menu): leader-side
# faults must fall back to the local merge; worker-side faults must
# fail the job or look like a dead worker (reap → republish)
_REMOTE_COMPACT_FAULTS = [
    ("compact.remote.publish", "fail_nth:1"),
    ("compact.remote.claim", "fail_nth:1"),
    ("compact.remote.fetch", "fail_nth:1"),
    ("compact.remote.upload", "fail_nth:1"),
    ("compact.remote.install", "fail_nth:1"),
    ("compact.remote.heartbeat", "fail_nth:1"),
]


class RemoteCompactionFixture:
    """Disaggregated compaction tier (round 18) under chaos: one fresh
    db + leader-side manager per step, a persistent worker draining the
    job ledger. Every step runs ONE rotating scenario (a seam fault, a
    worker kill mid-job, or a leader kill mid-job) and then ALWAYS the
    deposition probe: a job whose epoch goes stale in flight must come
    back "fenced" with the file generation untouched — the invariant
    the ``remote_install`` break-guard demonstrably violates."""

    def __init__(self, root: str):
        from rocksplicator_tpu.cluster.coordinator import (
            CoordinatorClient, CoordinatorServer)
        from rocksplicator_tpu.compaction_remote import (
            CompactionWorker, RemoteDispatchPolicy)

        self.root = root
        self.server = CoordinatorServer(port=0, session_ttl=5.0)
        self._clients = []

        def client():
            c = CoordinatorClient("127.0.0.1", self.server.port)
            self._clients.append(c)
            return c

        self._client = client
        self.store_uri = f"local://{os.path.join(root, 'compact_store')}"
        self.policy = RemoteDispatchPolicy(
            enabled=True, size_floor_bytes=0, deadline_s=20.0,
            claim_wait_s=2.0, heartbeat_timeout_s=0.5)
        self._worker_stop = threading.Event()
        self.worker = CompactionWorker(
            client(), os.path.join(root, "compact_wk"),
            worker_id="chaos-worker", poll_interval=0.05)
        threading.Thread(target=self.worker.serve_forever,
                         args=(self._worker_stop,), daemon=True).start()
        self.counter = 0  # fresh-db namer
        self.steps = 0  # scenario rotation
        self.outcomes: Dict[str, int] = {}

    def _fresh_db(self, epoch_provider):
        from rocksplicator_tpu.compaction_remote import \
            RemoteCompactionManager

        self.counter += 1
        name = f"rc{self.counter:05d}"
        db = DB(os.path.join(self.root, "compact_dbs", name),
                DBOptions(memtable_bytes=4 * 1024,
                          level0_compaction_trigger=100,
                          background_compaction=False))
        for j in range(120):
            db.write(WriteBatch().put(b"c%05d" % j, b"v%05d" % (j % 97)))
            if j % 40 == 0:
                db.flush()
        for j in range(0, 120, 9):
            db.write(WriteBatch().delete(b"c%05d" % j))
        db.flush()
        mgr = RemoteCompactionManager(
            name, db, self._client(), self.store_uri,
            policy=self.policy, epoch_provider=epoch_provider)
        want = {b"c%05d" % j: db.get(b"c%05d" % j) for j in range(120)}
        return name, db, mgr, want

    class _Pick:
        kind, level, score, reason = "l0", 0, 2.0, "chaos"

    def _note(self, outcome: str) -> None:
        self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1

    def step(self, rng: random.Random, violations: List[str],
             tag: str) -> None:
        scenarios = ["clean", "worker_kill", "leader_kill"] + [
            f"seam:{site}" for site, _ in _REMOTE_COMPACT_FAULTS]
        scenario = scenarios[self.steps % len(scenarios)]
        self.steps += 1
        try:
            self._run_scenario(scenario, rng, violations, tag)
            # the standing probe, every step: a deposed leader's job
            # must fence, and fencing must leave the generation alone
            self._probe_deposition(violations, tag)
        except Exception as e:
            violations.append(
                f"{tag}: remote-compaction fixture crashed "
                f"({scenario}): {e!r}")

    def _run_scenario(self, scenario: str, rng: random.Random,
                      violations: List[str], tag: str) -> None:
        name, db, mgr, want = self._fresh_db(lambda: 1)
        fault = None
        if scenario.startswith("seam:"):
            fault = scenario.split(":", 1)[1]
            fp.activate(fault, "fail_nth:1")
        try:
            if scenario == "worker_kill":
                outcome = self._worker_kill(name, db, mgr)
            elif scenario == "leader_kill":
                outcome = self._leader_kill(name, db, mgr, want,
                                            violations, tag)
                self._note(f"{scenario}:{outcome}")
                return  # db already reopened+closed inside
            else:
                outcome = mgr.maybe_offload(self._Pick())
            self._note(f"{scenario}:{outcome}")
            if outcome == "fenced":
                violations.append(
                    f"{tag}: remote {scenario}: unexpected fence at "
                    f"stable epoch")
                return
            if outcome == "declined":
                # the automatic local fallback must be intact
                db.compact_range()
            got = {k: db.get(k) for k in want}
            if got != want:
                bad = next(k for k in want if got[k] != want[k])
                violations.append(
                    f"{tag}: remote {scenario} ({outcome}): data "
                    f"diverged at {bad!r}")
                return
            if fault:
                # retry after clear: the tier must work again
                fp.deactivate(fault)
                fault = None
                retry = mgr.maybe_offload(self._Pick())
                if retry not in ("installed", "declined"):
                    violations.append(
                        f"{tag}: remote {scenario}: retry after clear "
                        f"→ {retry}")
                got = {k: db.get(k) for k in want}
                if got != want:
                    violations.append(
                        f"{tag}: remote {scenario}: data diverged "
                        f"after clean retry")
        finally:
            if fault:
                fp.deactivate(fault)
            db.close()

    def _worker_kill(self, name: str, db, mgr) -> str:
        """A claimer that dies instantly: claims the job the moment it
        appears, never heartbeats, never merges. The leader must reap
        on heartbeat expiry and the live worker must finish the job."""
        from rocksplicator_tpu.compaction_remote import CompactionJobQueue

        dead_q = CompactionJobQueue(self._client())
        stop = threading.Event()

        def dead_claimer():
            while not stop.is_set():
                try:
                    open_dbs = dead_q.list_open_jobs()
                    if name in open_dbs:
                        dead_q.claim(name, "dead-chaos-worker")
                        return
                except Exception:
                    pass
                time.sleep(0.01)

        t = threading.Thread(target=dead_claimer, daemon=True)
        t.start()
        try:
            return mgr.maybe_offload(self._Pick())
        finally:
            stop.set()
            t.join(timeout=2.0)

    def _leader_kill(self, name: str, db, mgr, want,
                     violations: List[str], tag: str) -> str:
        """Leader killed between publish and install: reopen must be
        exactly pre-compaction, recover() sweeps the orphan, and the
        next pick completes clean."""
        files_before = sorted(
            n for level in db._levels for n in level)
        plan = db.plan_full_compaction()
        if plan is None:
            db.close()
            return "noplan"
        mgr._publish(plan, f"chaoskill{self.counter:05d}", 1)
        db.abort_full_compaction(plan)  # the crash drops the mutex
        db.close()

        db2 = DB(db.path, DBOptions(memtable_bytes=4 * 1024,
                                    level0_compaction_trigger=100,
                                    background_compaction=False))
        try:
            files_after = sorted(
                n for level in db2._levels for n in level)
            if files_after != files_before:
                violations.append(
                    f"{tag}: remote leader_kill: reopen NOT exactly "
                    f"pre-compaction ({files_before} → {files_after})")
                return "diverged"
            got = {k: db2.get(k) for k in want}
            if got != want:
                violations.append(
                    f"{tag}: remote leader_kill: reopened data "
                    f"diverged")
                return "diverged"
            mgr._db = db2
            mgr.recover()
            if mgr._queue.get_job(name) is not None:
                violations.append(
                    f"{tag}: remote leader_kill: recover() left the "
                    f"orphan job in the ledger")
                return "orphan"
            outcome = mgr.maybe_offload(self._Pick())
            if outcome == "declined":
                db2.compact_range()
            got = {k: db2.get(k) for k in want}
            if got != want:
                violations.append(
                    f"{tag}: remote leader_kill: post-recovery "
                    f"compaction diverged")
            return outcome
        finally:
            db2.close()

    def _probe_deposition(self, violations: List[str], tag: str) -> None:
        """Publish at epoch 1, mint epoch 2 mid-job: the install MUST
        fence, and the file generation must be byte-for-byte untouched.
        With --break-guard remote_install the epoch gate is patched
        out, the stale job installs, and THIS probe is what catches
        it. A transient "declined" (worker hiccup: the result never
        arrived, so there was nothing to fence) is retried once before
        judging."""
        for attempt in (0, 1):
            epoch = {"cur": 1}
            name, db, mgr, want = self._fresh_db(lambda: epoch["cur"])
            files_before = sorted(
                n for level in db._levels for n in level)
            orig_publish = mgr._queue.publish

            def publish_then_depose(job, _pub=orig_publish):
                _pub(job)
                epoch["cur"] = 2  # a new leader was elected mid-job

            mgr._queue.publish = publish_then_depose
            try:
                outcome = mgr.maybe_offload(self._Pick())
                self._note(f"deposed:{outcome}")
                files_after = sorted(
                    n for level in db._levels for n in level)
                if outcome == "installed" or (
                        outcome == "fenced"
                        and files_after != files_before):
                    violations.append(
                        f"{tag}: DEPOSED LEADER'S JOB INSTALLED: "
                        f"stale-epoch result came back {outcome!r}, "
                        f"generation {files_before} → {files_after} "
                        f"(epoch gate broken?)")
                    return
                if outcome == "fenced":
                    return  # the expected path: discarded, untouched
                # declined = the result never arrived to be fenced
                # (worker hiccup) — inconclusive, retry once
            finally:
                db.close()
        violations.append(
            f"{tag}: deposition probe inconclusive twice: no result "
            f"ever reached the epoch gate (worker wedged?)")

    def close(self) -> None:
        self._worker_stop.set()
        for c in self._clients:
            try:
                c.close()
            except Exception:
                pass
        self.server.stop()


# ---------------------------------------------------------------------------
# coordinator-backed failover chaos (the control-plane schedule menu)
# ---------------------------------------------------------------------------

# faults the failover schedules arm (registration asserted by tests the
# same way the data-plane menu is)
_FAILOVER_FAULT_SITES = [
    "coordinator.heartbeat", "coordinator.reap", "coordinator.wal.append",
    "participant.transition", "shardmap.publish", "controller.assign",
    "repl.pull",
    # round 19: the tail-armor shed/hedge seams the overload schedule arms
    "rpc.deadline.check", "admission.shed", "router.hedge.fire",
    "repl.read",
]

FAILOVER_SESSION_TTL = 1.0
FAILOVER_FLAGS = ReplicationFlags(
    server_long_poll_ms=300,
    pull_error_delay_min_ms=30,
    pull_error_delay_max_ms=200,
    ack_timeout_ms=800,
    consecutive_timeouts_to_degrade=1000,
    write_window=16,
    # bounded-staleness reads (round 13): small TTL so the read
    # invariant's quiesce-then-check window stays fast; a follower
    # whose estimate aged past this must prove its lag with an
    # upstream probe before serving — or bounce
    read_info_ttl_ms=300,
    read_probe_timeout_ms=500,
)
# "shard-map convergence within a bounded number of controller passes":
# the reconcile loop runs every 0.25 s, so this bound also caps heal time
FAILOVER_PASS_BOUND = 80
# reshard heals ride a longer window (deposed resync + drops + rejoin
# storms settle through MORE passes, at the same 0.25 s cadence): the
# bound scales with the 30 s heal timeout the reshard checks use
RESHARD_PASS_BOUND = 160
_LEADERLIKE = {"LEADER", "MASTER"}


class FailoverNode:
    """One 'host': replicator + admin service + participant."""

    def __init__(self, root: str, name: str, coord_port: int, cluster: str,
                 fallbacks, store_uri: str):
        from rocksplicator_tpu.admin.handler import AdminHandler
        from rocksplicator_tpu.cluster.model import InstanceInfo
        from rocksplicator_tpu.cluster.participant import Participant
        from rocksplicator_tpu.rpc.server import RpcServer

        self.name = name
        self.replicator = Replicator(port=0, flags=FAILOVER_FLAGS)
        self.handler = AdminHandler(
            os.path.join(root, "admin", name), self.replicator)
        self.server = RpcServer(port=0, ioloop=self.replicator.ioloop)
        self.server.add_handler(self.handler)
        self.server.start()
        self.instance = InstanceInfo(
            instance_id=f"127.0.0.1_{self.server.port}",
            host="127.0.0.1",
            admin_port=self.server.port,
            repl_port=self.replicator.port,
            az=f"az-{name}",
        )
        self.participant = Participant(
            "127.0.0.1", coord_port, cluster, self.instance,
            backup_store_uri=store_uri, catch_up_timeout=10.0,
            error_retry_backoff=0.2, coord_fallbacks=fallbacks,
            # chaos-scale 3-node-failure guard: the default 100k slack
            # is scale-blind at these workload sizes — a data-poor
            # candidate must refuse promotion past a checkpointed
            # lineage and rebuild first
            promotion_seq_slack=64,
        )
        # data-plane self-healing: followers can repoint from the pull
        # loop's forced-reset path without waiting on a controller write
        self.handler.set_leader_resolver(
            self.participant.make_leader_resolver())

    def state_of(self, partition: str):
        return self.participant.current_states.get(partition)

    def rdb(self, db_name: str):
        return self.replicator.get_db(db_name)

    def stop(self) -> None:
        try:
            self.participant.stop()
        except Exception:
            pass
        self.server.stop()
        self.handler.close()
        self.replicator.stop()


class FailoverCluster:
    """Coordinator primary + standby (durable, replicated), a Controller,
    a Spectator publishing the shard map, and N participant hosts running
    one replicas=3 LeaderFollower resource in semi-sync mode — the
    reference Helix topology in one process, chaos-sized. ``num_nodes``
    above the replica count leaves spare hosts for the reshard
    schedules' live shard moves (3 of 4 host the shard; moves relocate
    replicas onto the free node). ``num_shards`` above 1 gives the
    rebalance schedules a fleet MEAN to compare hot shards against."""

    def __init__(self, root: str, num_nodes: int = 3,
                 num_shards: int = 1):
        import itertools as _it

        from rocksplicator_tpu.cluster.controller import Controller
        from rocksplicator_tpu.cluster.coordinator import CoordinatorServer
        from rocksplicator_tpu.cluster.coordinator import CoordinatorClient
        from rocksplicator_tpu.cluster.model import ResourceDef
        from rocksplicator_tpu.cluster.publishers import CallbackPublisher
        from rocksplicator_tpu.cluster.spectator import Spectator
        from rocksplicator_tpu.rpc.client_pool import RpcClientPool
        from rocksplicator_tpu.rpc.ioloop import IoLoop
        from rocksplicator_tpu.utils.segment_utils import segment_to_db_name

        self.root = root
        self.cluster = "chaos"
        self.segment = "seg"
        self.num_shards = num_shards
        self.partitions = [f"{self.segment}_{s}"
                           for s in range(self.num_shards)]
        self.db_names = [segment_to_db_name(self.segment, s)
                         for s in range(self.num_shards)]
        self._coord_seq = _it.count()
        # the failover invariants are about SEMI-SYNC acks (mode 1): an
        # ack means a follower received the write. Participant-created
        # dbs take their mode from the per-segment config.
        from rocksplicator_tpu.utils.dbconfig import DBConfigManager

        mgr = DBConfigManager.get()
        self._saved_dbconfig = dict(mgr.config.raw)
        mgr.load_from_dict({self.segment: {"replication_mode": 1}})
        self.primary = CoordinatorServer(
            port=0, session_ttl=FAILOVER_SESSION_TTL,
            data_dir=self._coord_dir())
        self.standby = CoordinatorServer(
            port=0, session_ttl=FAILOVER_SESSION_TTL,
            data_dir=self._coord_dir(),
            replica_of=("127.0.0.1", self.primary.port))
        fallbacks = [("127.0.0.1", self.standby.port)]
        store_uri = os.path.join(root, "bucket")
        LocalObjectStore(store_uri)
        self.store_uri = store_uri
        self.nodes = [
            FailoverNode(root, f"n{i}", self.primary.port, self.cluster,
                         fallbacks, store_uri)
            for i in range(num_nodes)
        ]
        self.controller = Controller(
            "127.0.0.1", self.primary.port, self.cluster, "ctrl-1",
            reconcile_interval=0.25, coord_fallbacks=fallbacks)
        self.maps: List[Dict] = []
        self.spectator = Spectator(
            "127.0.0.1", self.primary.port, self.cluster,
            [CallbackPublisher(self.maps.append)],
            coord_fallbacks=fallbacks)
        self.client = CoordinatorClient("127.0.0.1", self.primary.port,
                                        fallbacks=fallbacks)
        self.controller.add_resource(
            ResourceDef(self.segment, num_shards=self.num_shards,
                        replicas=3))
        self._ioloop = IoLoop.default()
        self._pool = RpcClientPool()
        # the reshard schedules drive real AdminClient RPCs (the shard-
        # move step machine's snapshot/restore/pause calls)
        from rocksplicator_tpu.cluster.helix_utils import AdminClient

        self.admin = AdminClient()

    def _coord_dir(self) -> str:
        return os.path.join(self.root, f"coord{next(self._coord_seq)}")

    # -- RPC straight at a node's replication plane (the follower frame
    # -- a harness probe fakes rides the REAL wire path)
    def rpc(self, port: int, method: str, args: dict, timeout: float = 5.0,
            **kw):
        async def go():
            return await self._pool.call("127.0.0.1", port, method, args,
                                         timeout=timeout, **kw)

        return self._ioloop.run_sync(go(), timeout=timeout + 5)

    # -- views ------------------------------------------------------------

    def leader_node(self, partition: str,
                    exclude=()) -> Optional[FailoverNode]:
        for n in self.nodes:
            if n in exclude:
                continue
            if n.state_of(partition) in _LEADERLIKE:
                return n
        return None

    def states(self, partition: str) -> Dict[str, str]:
        return {n.name: n.state_of(partition) for n in self.nodes}

    def seqs(self, db_name: str) -> List[Optional[int]]:
        out = []
        for n in self.nodes:
            app = n.handler.db_manager.get_db(db_name)
            out.append(
                app.db.latest_sequence_number_relaxed()
                if app is not None else None)
        return out

    def wait(self, pred, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return True
            time.sleep(0.05)
        return pred()

    def wait_initial_convergence(self, timeout: float = 30.0) -> None:
        def ready():
            for partition in self.partitions:
                st = sorted(s for s in self.states(partition).values() if s)
                if st != ["FOLLOWER", "FOLLOWER", "LEADER"]:
                    return False
            return True

        if not self.wait(ready, timeout):
            raise RuntimeError(
                f"failover cluster never converged: "
                f"{[self.states(p) for p in self.partitions]}")

    # -- workload ---------------------------------------------------------

    def write_some(self, rng: random.Random, tag: str, n: int,
                   acked: List[Tuple[bytes, bytes]],
                   deadline_per_write: float = 3.0, exclude=()) -> int:
        """n writes through the current leader; waits the ack futures and
        appends acked (key, value) pairs. Returns how many writes errored
        (fenced / no leader / mid-handoff)."""
        errors = 0
        waiters = []
        for i in range(n):
            key = f"{tag}-k{i:04d}".encode()
            val = f"{tag}-v{i:04d}".encode()
            node = self.leader_node(self.partitions[0], exclude=exclude)
            if node is None:
                errors += 1
                continue
            app = node.handler.db_manager.get_db(self.db_names[0])
            if app is None:
                errors += 1
                continue
            try:
                waiters.append((key, val, app.write_async(
                    WriteBatch().put(key, val))))
            except Exception:
                errors += 1
        for key, val, w in waiters:
            try:
                w.future.result(deadline_per_write)
            except Exception:
                continue
            if w.acked:
                acked.append((key, val))
        return errors

    def stop(self) -> None:
        for closer in (self.spectator.stop, self.controller.stop,
                       self.client.close, self.admin.close):
            try:
                closer()
            except Exception:
                pass
        for n in self.nodes:
            n.stop()
        try:
            self._ioloop.run_sync(self._pool.close(), timeout=5)
        except Exception:
            pass
        for srv in (self.primary, self.standby):
            try:
                srv.stop()
            except Exception:
                pass
        from rocksplicator_tpu.utils.dbconfig import DBConfigManager

        DBConfigManager.get().load_from_dict(self._saved_dbconfig)


# ---------------------------------------------------------------------------
# deliberately-broken guards (harness-teeth demonstration)
# ---------------------------------------------------------------------------


def _break_guard(kind: str):
    """Returns an undo callable."""
    if kind == "wal_hole":
        from rocksplicator_tpu.storage.wal import WalWriter

        orig = WalWriter.append
        state = {"n": 0}

        def broken_append(self, start_seq, batch_bytes):
            state["n"] += 1
            if state["n"] % 5 == 0:
                # claim a durability token without writing the record —
                # the ack-before-durability bug class
                self._append_token += 1
                return self._append_token
            return orig(self, start_seq, batch_bytes)

        WalWriter.append = broken_append
        return lambda: setattr(WalWriter, "append", orig)
    if kind == "meta_first":
        from rocksplicator_tpu.admin.handler import AdminHandler

        orig_do = AdminHandler._do_ingest

        def broken_do(self, sp, db_name, store, s3_bucket, s3_path,
                      *args):
            self.write_meta_data(db_name, s3_bucket, s3_path)
            return orig_do(self, sp, db_name, store, s3_bucket, s3_path,
                           *args)

        AdminHandler._do_ingest = broken_do
        return lambda: setattr(AdminHandler, "_do_ingest", orig_do)
    if kind == "move_flip":
        # the naive shard-move cutover a lazy implementation would ship:
        # no write pause, no tail drain, no two-phase handoff — just
        # bump the ledger and force-promote the target's data plane the
        # moment catch-up is "close enough". This leaves TWO unfenced
        # serving lineages (the source still leads its follower set;
        # the target leads alone at a higher epoch, missing the acked
        # tail) — the sixth invariant's lineage probes must catch it.
        import json as _json

        from rocksplicator_tpu.cluster.model import cluster_path
        from rocksplicator_tpu.cluster.shard_move import ShardMove

        orig_cutover = ShardMove._phase_cutover

        def broken_cutover(self):
            rec = self.rec
            rec.moving_leader = True
            target = self._target_info()
            path = cluster_path(self.cluster, "epochs", rec.partition)
            raw = self.coord.get_or_none(path)
            cur = 0
            if raw:
                try:
                    cur = int(_json.loads(bytes(raw).decode())
                              .get("epoch", 0))
                except (ValueError, UnicodeDecodeError):
                    cur = 0
            self.coord.put(path, _json.dumps(
                {"epoch": cur + 1, "leader": rec.target}).encode())
            self.admin.change_db_role_and_upstream(
                self._admin_addr(target), rec.db_name, "LEADER",
                epoch=cur + 1)

        ShardMove._phase_cutover = broken_cutover
        return lambda: setattr(
            ShardMove, "_phase_cutover", orig_cutover)
    if kind == "split_cutover":
        # the naive split cutover: "the snapshot is good enough" — the
        # hidden observer's WAL-tail pull severed (self-upstream), the
        # catch-up wait skipped, the paused drain-to-exact-equality
        # no-op'd. The REAL cutover refuses to flip a non-drained child
        # (the drain polls lag==0 under the pause and times out); the
        # naive one renames a frozen snapshot into the high child, so
        # every key >= split_key acked after the snapshot seq is absent
        # from the child that now OWNS it — the rebalance harness's
        # per-child acked-readability probe must catch the loss.
        from rocksplicator_tpu.cluster.shard_split import ShardSplit

        orig_catchup = ShardSplit._phase_catchup
        orig_drain = ShardSplit._cutover_drain

        def naive_catchup(self):
            target = self._instances().get(self.rec.target_instance)
            if target is not None:
                self.admin.change_db_role_and_upstream(
                    self._admin_addr(target), self.parent_db, "OBSERVER",
                    upstream=(target.host, target.repl_port))

        ShardSplit._phase_catchup = naive_catchup
        ShardSplit._cutover_drain = lambda self, leader: None

        def undo():
            ShardSplit._phase_catchup = orig_catchup
            ShardSplit._cutover_drain = orig_drain

        return undo
    if kind == "remote_install":
        # a leader that installs a remote compaction result WITHOUT the
        # epoch gate: a deposed leader's in-flight job comes back and
        # swaps a generation into a db that a higher-epoch leader now
        # owns. The remote-compaction fixture's standing deposition
        # probe must catch the install that should have fenced.
        from rocksplicator_tpu.compaction_remote import install as rc_install

        orig_gate = rc_install._epoch_is_current
        rc_install._epoch_is_current = \
            lambda job_epoch, current_epoch: True
        return lambda: setattr(
            rc_install, "_epoch_is_current", orig_gate)
    if kind == "mux_misroute":
        # the session-demux bug class (round 22): the server drains the
        # right WALs but files one shard's updates under its SIBLING's
        # section key — cursor bookkeeping intact, seqs restamped off
        # the victim's cursor, which is exactly what an index-off-by-one
        # in the serve loop produces. The apply side sees a perfectly
        # CONTINUOUS batch of the wrong shard's bytes, so the
        # seq-continuity guard cannot reject it — only the standing
        # invariants can catch it: acked writes on the donor shard never
        # reach the followers (zero-acked-loss), and the victim shard
        # runs ahead of its leader (reconvergence never lands). Forces
        # RSTPU_PULL_MUX=1 for the run — the tooth targets the mux path.
        from rocksplicator_tpu.replication.pull_mux import MuxServerState

        saved_mux = os.environ.get("RSTPU_PULL_MUX")
        os.environ["RSTPU_PULL_MUX"] = "1"
        orig_serve = MuxServerState.serve
        state = {"n": 0}

        def _restamp(updates, start):
            out, seq = [], start
            for u in updates:
                u2 = dict(u)
                u2["seq_no"] = seq
                seq += int(u.get("count") or 1)
                out.append(u2)
            return out

        async def misrouting_serve(self, db_map, sections,
                                   max_wait_ms=None, budget=None):
            resp = await orig_serve(self, db_map, sections,
                                    max_wait_ms=max_wait_ms,
                                    budget=budget)
            out = resp.get("sections") or {}
            live = sorted(n for n, sec in out.items()
                          if isinstance(sec, dict) and "error" not in sec)
            state["n"] += 1
            if len(live) >= 2 and state["n"] % 2 == 0:
                a, b = live[0], live[1]
                ua = out[a].get("updates") or []
                ub = out[b].get("updates") or []
                if ua or ub:
                    out[a]["updates"] = _restamp(
                        ub, int(sections[a].get("seq_no", 0)) + 1)
                    out[b]["updates"] = _restamp(
                        ua, int(sections[b].get("seq_no", 0)) + 1)
            return resp

        MuxServerState.serve = misrouting_serve

        def undo():
            MuxServerState.serve = orig_serve
            if saved_mux is None:
                os.environ.pop("RSTPU_PULL_MUX", None)
            else:
                os.environ["RSTPU_PULL_MUX"] = saved_mux

        return undo
    if kind == "fencing":
        # a leader that IGNORES epochs: stale-epoch frames are served and
        # acked, a deposed leader never fences — the no-split-brain
        # invariant must catch the acked-on-deposed-leader writes
        from rocksplicator_tpu.replication.replicated_db import ReplicatedDB

        orig_reject = ReplicatedDB._reject_stale_epoch
        ReplicatedDB._reject_stale_epoch = (
            lambda self, remote_epoch: False)
        return lambda: setattr(
            ReplicatedDB, "_reject_stale_epoch", orig_reject)
    if kind == "cdc_dedup":
        # the at-least-once bug class: the consumer-offset checkpoint
        # DECOUPLED from the apply batch — records commit first, the
        # watermark follows in a separate write (what a naive port of
        # the reference's commit()-after-apply would do). The
        # kafka.checkpoint seam moves with it: it now fires BETWEEN the
        # records commit and the watermark write, so a seam kill leaves
        # applied records above a stale watermark; resume re-applies
        # them. State-compare can't see it (applies are idempotent
        # upserts) — the applies-counter witness must catch
        # ``applies_total > watermark.offset`` at quiesce.
        from rocksplicator_tpu.kafka.checkpoint import (encode_watermark,
                                                        watermark_key)
        from rocksplicator_tpu.kafka.ingestion import IngestionWatcher
        from rocksplicator_tpu.storage.records import (
            WriteBatch as _WriteBatch)

        orig_fold = IngestionWatcher._fold_checkpoint
        orig_apply = IngestionWatcher._apply_group

        def naive_fold(self, batch, partition, next_offset, applied,
                       ts_ms):
            pending = getattr(self, "_naive_pending", None)
            if pending is None:
                pending = self._naive_pending = []
            pending.append((partition, next_offset, applied, ts_ms))

        def naive_apply(self, batches):
            orig_apply(self, batches)
            pending, self._naive_pending = \
                getattr(self, "_naive_pending", []) or [], []
            for p, off, applied, ts in pending:
                fp.hit("kafka.checkpoint")
                wb = _WriteBatch()
                wb.put(watermark_key(self._topic, p),
                       encode_watermark(off, applied, ts))
                self._write_many([wb])

        IngestionWatcher._fold_checkpoint = naive_fold
        IngestionWatcher._apply_group = naive_apply

        def undo():
            IngestionWatcher._fold_checkpoint = orig_fold
            IngestionWatcher._apply_group = orig_apply

        return undo
    raise ValueError(f"unknown break-guard: {kind}")


# ---------------------------------------------------------------------------
# failover schedules (every parameter drawn from the schedule RNG)
# ---------------------------------------------------------------------------


def _wait_replicas_equal(cluster: FailoverCluster, timeout: float = 10.0,
                         replicas: int = 3) -> bool:
    """Baseline writes are only held to the zero-loss invariant once they
    are on EVERY replica — then any single survivor carries them through
    arbitrary later flaps. Hosting-aware: with spare nodes (reshard
    mode), exactly ``replicas`` nodes must host the db at equal seqs —
    nodes without the db (the move's free node) are not required to."""
    def equal():
        for db in cluster.db_names:
            seqs = [s for s in cluster.seqs(db) if s is not None]
            if len(seqs) != replicas or len(set(seqs)) != 1:
                return False
        return True

    return cluster.wait(equal, timeout)


def _schedule_leader_crash(cluster, rng, acked, violations, tag, timings):
    """Crash the acting leader while it holds a full AckWindow, then
    prove the no-split-brain invariant: after the new leader's epoch is
    visible, the deposed leader cannot ack a single write. Follower
    pulls are blocked for the whole window so NO ack can legitimately
    land between fill and promotion."""
    partition, db = cluster.partitions[0], cluster.db_names[0]
    leader = cluster.leader_node(partition)
    if leader is None:
        violations.append(f"{tag}: no leader before the fault")
        return
    cluster.write_some(rng, tag + "-pre", rng.randint(6, 12), acked)
    if not _wait_replicas_equal(cluster):
        violations.append(f"{tag}: baseline never converged")
        return
    rdb = leader.rdb(db)
    app = leader.handler.db_manager.get_db(db)
    fp.activate("repl.pull",
                f"fail_prob:1.0@seed{rng.randrange(1 << 16)}")
    # drain pulls already PARKED in the leader's long-poll (they predate
    # the failpoint and would legitimately serve+ack the fill writes)
    time.sleep(FAILOVER_FLAGS.server_long_poll_ms / 1000.0 + 0.2)
    base_seq = app.db.latest_sequence_number_relaxed()
    # fill the window: none of these can ack while pulls are blocked
    # (they expire un-acked on the 800 ms timeout — either way, zero acks)
    pending = []
    for i in range(min(rdb.ack_window_free, rng.randint(6, 16))):
        key = f"{tag}-pend{i:03d}".encode()
        try:
            pending.append(
                (key, key, app.write_async(WriteBatch().put(key, key))))
        except Exception:
            break
    t_fault = time.monotonic()
    leader.participant.coord.suspend_heartbeats()  # the wedge: data plane
    # stays alive and thinks it leads — the classic deposed-but-running
    # belt-and-braces: a fill write that somehow acked BEFORE the wedge
    # (a straggler pull) is a legitimate pre-crash ack, not a stale one
    pre_wedge: List = []
    still_pending: List = []
    for item in pending:
        w = item[2]
        if w.future.done() and w.acked:
            pre_wedge.append(item)
        else:
            still_pending.append(item)
    acked.extend((k, v) for k, v, _w in pre_wedge)
    pending = still_pending
    if not cluster.wait(
            lambda: cluster.leader_node(partition, exclude=(leader,))
            is not None, 12.0):
        violations.append(
            f"{tag}: no new leader within 12s of the wedge "
            f"({cluster.states(partition)})")
        fp.deactivate("repl.pull")
        leader.participant.coord.resume_heartbeats()
        return
    t_one_leader = time.monotonic()
    new_leader = cluster.leader_node(partition, exclude=(leader,))
    fp.deactivate("repl.pull")
    nrdb = new_leader.rdb(db)
    new_epoch = nrdb.epoch if nrdb is not None else 0
    # THE stale frame: a late follower pull carrying the new epoch hits
    # the deposed leader over the real wire. Fencing: STALE_EPOCH, the
    # pending window fails un-acked, writes refused. --break-guard
    # fencing: the pull is served and mode-1 acks it.
    try:
        cluster.rpc(leader.replicator.port, "replicate",
                    dict(db_name=db, seq_no=base_seq, max_wait_ms=0,
                         max_updates=1024, role="FOLLOWER",
                         epoch=new_epoch))
    except Exception:
        pass  # STALE_EPOCH is the expected outcome with the guard intact
    # post-visibility write probes at the DEPOSED leader: with fencing
    # they are refused outright; without it they commit locally and the
    # second stale pull acks them — the split brain the harness must see
    probe_waiters = []
    for i in range(3):
        key = f"{tag}-stale{i}".encode()
        try:
            probe_waiters.append(
                (key, key, rdb.write_async(WriteBatch().put(key, key))))
        except Exception:
            pass
    try:
        cluster.rpc(leader.replicator.port, "replicate",
                    dict(db_name=db, seq_no=base_seq + len(pending),
                         max_wait_ms=0, max_updates=1024, role="FOLLOWER",
                         epoch=new_epoch))
    except Exception:
        pass
    # DEPOSED-LINEAGE READ PROBES (round 13): once the new epoch is
    # visible, the deposed leader must refuse reads exactly as it
    # refuses stale-epoch pulls — with the new epoch on the request
    # (the fencing trigger) AND without one (it is already fenced).
    for probe_epoch in (new_epoch, None):
        try:
            resp = cluster.rpc(
                leader.replicator.port, "read",
                dict(db_name=db, op="get", keys=[b"probe"],
                     max_lag=0, epoch=probe_epoch))
        except Exception:
            timings["read_bounces"] += 1
            continue  # STALE_EPOCH is the required outcome
        violations.append(
            f"{tag}: READ SERVED FROM DEPOSED LINEAGE — fenced leader "
            f"answered a read (epoch on request: {probe_epoch}, "
            f"response epoch {resp.get('epoch')})")
    # failover-time metric: fault → first acked write on the new leader
    ack2: List[Tuple[bytes, bytes]] = []
    deadline = time.monotonic() + 10.0
    seq = 0
    while time.monotonic() < deadline and not ack2:
        cluster.write_some(rng, f"{tag}-post{seq}", 2, ack2,
                           exclude=(leader,))
        seq += 1
    if ack2:
        t_first_ack = time.monotonic()
        timings["first_ack_ms"].append((t_first_ack - t_fault) * 1000.0)
        acked.extend(ack2)
    else:
        violations.append(
            f"{tag}: no acked write on the new leader within 10s")
    timings["failover_ms"].append((t_one_leader - t_fault) * 1000.0)
    # zero stale acks: nothing written at/after the wedge may ack on the
    # deposed leader once the new epoch was visible
    stale = []
    for key, val, w in pending + probe_waiters:
        try:
            w.future.result(3.0)
        except Exception:
            continue
        if w.acked:
            stale.append(key)
            acked.append((key, val))  # it claimed durability: hold it to it
    if stale:
        violations.append(
            f"{tag}: SPLIT BRAIN — deposed leader acked {len(stale)} "
            f"write(s) after epoch {new_epoch} was visible "
            f"(first {stale[0]!r})")
    # heal: resume heartbeats → session re-establishes → participant
    # rejoins → controller demotes it → deposed resync from the new
    # lineage (the _check_failover_invariants wait covers all of it)
    leader.participant.coord.resume_heartbeats()


def _schedule_session_expiry(cluster, rng, acked, violations, tag,
                             timings):
    """Expire participant sessions mid-write by dropping heartbeats at
    the coordinator.heartbeat seam (real server-side TTL lapses, mass
    ephemeral teardown, rejoin storm). Writes issued DURING the outage
    ride the semi-sync window (availability over durability — the
    reference contract) and are counted but not held to the strict
    ledger; pre-fault and post-heal acks are."""
    cluster.write_some(rng, tag + "-pre", rng.randint(6, 12), acked)
    if not _wait_replicas_equal(cluster):
        violations.append(f"{tag}: baseline never converged")
        return
    n = rng.randint(25, 45)  # ~1.5-2.5 TTLs of failed beats, all clients
    fp.activate("coordinator.heartbeat", f"fail_first:{n}")
    window: List[Tuple[bytes, bytes]] = []
    cluster.write_some(rng, tag + "-mid", rng.randint(3, 6), window)
    timings["window_acked"] += len(window)
    time.sleep(FAILOVER_SESSION_TTL * 1.7)
    fp.deactivate("coordinator.heartbeat")
    cluster.write_some(rng, tag + "-post", rng.randint(3, 6), acked)


def _schedule_follower_expiry(cluster, rng, acked, violations, tag,
                              timings):
    """Wedge a FOLLOWER past its session TTL (leadership must NOT move),
    optionally with a transition/assignment fault armed, then prove the
    reaped participant re-registers and resumes FOLLOWER without a
    restart."""
    from rocksplicator_tpu.cluster.model import cluster_path

    partition = cluster.partitions[0]
    followers = [n for n in cluster.nodes
                 if n.state_of(partition) in ("FOLLOWER", "SLAVE")]
    if not followers:
        violations.append(f"{tag}: no follower to expire")
        return
    cluster.write_some(rng, tag + "-pre", rng.randint(4, 8), acked)
    if not _wait_replicas_equal(cluster):
        violations.append(f"{tag}: baseline never converged")
        return
    victim = rng.choice(followers)
    extra = rng.choice([None,
                        ("participant.transition", "fail_nth:1"),
                        ("controller.assign", "fail_nth:1")])
    if extra is not None:
        fp.activate(*extra)
    victim.participant.coord.suspend_heartbeats()
    node_path = cluster_path(cluster.cluster, "instances",
                             victim.instance.instance_id)
    if not cluster.wait(lambda: not cluster.client.exists(node_path), 8.0):
        violations.append(f"{tag}: {victim.name} session never expired")
    # leader untouched: these acks ride the surviving follower — safe
    cluster.write_some(rng, tag + "-mid", rng.randint(3, 6), acked)
    victim.participant.coord.resume_heartbeats()
    if not cluster.wait(lambda: cluster.client.exists(node_path), 8.0):
        violations.append(
            f"{tag}: {victim.name} never re-registered after expiry "
            f"(rejoin gap)")
    if extra is not None:
        fp.deactivate(extra[0])


def _coordinator_failover(cluster, tag, violations):
    """Kill the primary, promote the standby, spin up a fresh standby,
    and teach every client the new standby's endpoint (stands in for
    ensemble discovery, which needs routable IPs — loopback standbys
    advertise nothing)."""
    from rocksplicator_tpu.cluster.coordinator import CoordinatorServer

    old_primary = cluster.primary
    old_primary.stop()
    cluster.standby.promote()
    cluster.primary = cluster.standby
    cluster.standby = CoordinatorServer(
        port=0, session_ttl=FAILOVER_SESSION_TTL,
        data_dir=cluster._coord_dir(),
        replica_of=("127.0.0.1", cluster.primary.port))
    ep = ("127.0.0.1", cluster.standby.port)
    for coord_client in [n.participant.coord for n in cluster.nodes] + [
            cluster.controller.coord, cluster.spectator.coord,
            cluster.client]:
        if ep not in coord_client._endpoints:
            coord_client._endpoints.append(ep)


def _schedule_coordinator_failover(cluster, rng, acked, violations, tag,
                                   timings):
    """Kill the coordinator primary mid-write. Sessions survive on the
    promoted standby (replicated, TTL grace), clients rotate, and the
    data plane never blinks — leadership must NOT move."""
    cluster.write_some(rng, tag + "-pre", rng.randint(4, 8), acked)
    if not _wait_replicas_equal(cluster):
        violations.append(f"{tag}: baseline never converged")
        return
    _coordinator_failover(cluster, tag, violations)
    # a coordinator failover is invisible to the data plane: these acks
    # are strict-ledger safe
    cluster.write_some(rng, tag + "-mid", rng.randint(4, 8), acked)


def _schedule_coordinator_wal_torn(cluster, rng, acked, violations, tag,
                                   timings):
    """Torn-write the coordinator WAL: the primary fail-stops for
    mutations (every pending and future mutation fenced — the
    coordinator.py _Wal contract), and the cluster heals by failing over
    to the standby."""
    cluster.write_some(rng, tag + "-pre", rng.randint(4, 8), acked)
    if not _wait_replicas_equal(cluster):
        violations.append(f"{tag}: baseline never converged")
        return
    fp.activate("coordinator.wal.append",
                f"torn:1.0@seed{rng.randrange(1 << 16)},one_shot")
    # poke durable mutations until the one-shot policy is consumed — a
    # single put can die on a stale endpoint (mutations never blind-
    # retry after a connection error) without ever reaching a WAL
    for attempt in range(6):
        try:
            cluster.client.put(f"/chaos/poke/{tag}/{attempt}", b"x")
        except Exception:
            pass  # the poke itself may be the torn mutation
        if not fp.is_active("coordinator.wal.append"):
            break  # one_shot consumed: the tear landed
    fp.deactivate("coordinator.wal.append")
    primary_fenced = (cluster.primary._wal is not None
                     and cluster.primary._wal.failed is not None)
    standby_fenced = (cluster.standby._wal is not None
                      and cluster.standby._wal.failed is not None)
    if primary_fenced:
        # fail-stop contract: NOTHING mutates after the fence
        try:
            cluster.client.put(f"/chaos/poke2/{tag}", b"y")
            violations.append(
                f"{tag}: mutation SUCCEEDED on a fenced coordinator WAL")
        except Exception:
            pass
        _coordinator_failover(cluster, tag, violations)
    elif standby_fenced:
        # the replicated append tripped on the standby first: its durable
        # persistence stopped (promote would refuse) — replace it
        from rocksplicator_tpu.cluster.coordinator import CoordinatorServer

        cluster.standby.stop()
        cluster.standby = CoordinatorServer(
            port=0, session_ttl=FAILOVER_SESSION_TTL,
            data_dir=cluster._coord_dir(),
            replica_of=("127.0.0.1", cluster.primary.port))
    else:
        violations.append(f"{tag}: torn WAL append fenced neither "
                          f"coordinator")
    cluster.write_some(rng, tag + "-post", rng.randint(3, 6), acked)


def _schedule_blip(kind):
    def run(cluster, rng, acked, violations, tag, timings):
        s = rng.randrange(1 << 16)
        if kind == "hb_delay":
            fp.activate("coordinator.heartbeat",
                        f"delay_ms:{rng.randint(40, 120)}:"
                        f"{rng.uniform(0.2, 0.5):.2f}@seed{s}")
        elif kind == "reap_blip":
            fp.activate("coordinator.reap",
                        f"fail_first:{rng.randint(1, 3)}")
        elif kind == "shardmap_blip":
            fp.activate("shardmap.publish",
                        f"fail_first:{rng.randint(1, 2)}")
        elif kind == "read_blip":
            # round-13 seam: failing reads mid-schedule must only ever
            # surface as errors/bounces at the client, never as a
            # served-but-wrong read (the post-schedule invariant check)
            fp.activate("repl.read",
                        f"fail_prob:{rng.uniform(0.3, 0.7):.2f}@seed{s}")
        cluster.write_some(rng, tag, rng.randint(6, 12), acked)
        if kind == "read_blip":
            # drive reads THROUGH the armed seam at every replica
            db = cluster.db_names[0]
            for node in cluster.nodes:
                for _ in range(rng.randint(2, 4)):
                    try:
                        cluster.rpc(node.replicator.port, "read",
                                    dict(db_name=db, op="get",
                                         keys=[b"probe"], max_lag=5))
                    except Exception:
                        timings["read_bounces"] += 1
        time.sleep(rng.uniform(0.1, 0.4))
        fp.clear()

    return run


def _schedule_overload_shed(cluster, rng, acked, violations, tag, timings):
    """Round-19 overload schedule: tail-armor sheds and hedges fire
    while acked writes keep landing. The armed seams force the TYPED
    degrade paths — ``rpc.deadline.check`` forces expired verdicts,
    ``admission.shed`` forces tenant RETRY_LATER sheds, and
    ``router.hedge.fire`` makes hedge launches fall back to the primary
    arm — while ``repl.read`` delays make real hedges (and their
    loser-cancel frames, the obvious new race) actually fire. A shed is
    a typed refusal, never damage: the standing invariants (zero
    acked-write loss, bounded staleness) must hold unchanged."""
    from rocksplicator_tpu.rpc.errors import RpcApplicationError
    from rocksplicator_tpu.rpc.router import (ClusterLayout, ReadPolicy,
                                              RpcRouter)

    s = rng.randrange(1 << 16)
    fp.activate("rpc.deadline.check",
                f"fail_prob:{rng.uniform(0.2, 0.5):.2f}@seed{s}")
    fp.activate("admission.shed",
                f"fail_prob:{rng.uniform(0.2, 0.5):.2f}@seed{s + 1}")
    fp.activate("router.hedge.fire",
                f"fail_prob:{rng.uniform(0.2, 0.4):.2f}@seed{s + 2}")
    # stall ~half the read serves so the p95-floored hedge delay is
    # actually beaten and backup arms launch (then get cancelled)
    fp.activate("repl.read",
                f"delay_ms:{rng.randint(15, 30)}:0.5@seed{s + 3}")
    saved_floor = os.environ.get("RSTPU_HEDGE_FLOOR_MS")
    os.environ["RSTPU_HEDGE_FLOOR_MS"] = "2"
    router = RpcRouter(pool=cluster._pool)
    sheds = 0
    try:
        cluster.write_some(rng, tag, rng.randint(6, 12), acked)
        db = cluster.db_names[0]
        for node in cluster.nodes:
            # one zero-budget probe per node guarantees the deadline
            # shed fires even if every probability roll misses
            for deadline_ms in [0.0] + [
                    rng.choice([50.0, 2000.0])
                    for _ in range(rng.randint(2, 4))]:
                try:
                    cluster.rpc(node.replicator.port, "read",
                                dict(db_name=db, op="get", keys=[b"probe"],
                                     max_lag=5),
                                deadline_ms=deadline_ms,
                                tenant=rng.choice(["noisy", "quiet"]))
                except RpcApplicationError as e:
                    if e.code in ("DEADLINE_EXCEEDED", "RETRY_LATER"):
                        sheds += 1
                    timings["read_bounces"] += 1
                except Exception:
                    timings["read_bounces"] += 1
        if cluster.maps:
            router.update_layout(ClusterLayout.parse(
                json.dumps(cluster.maps[-1]).encode()))
            router._hedge_credit = router._hedge_credit_cap  # prime budget

            async def hedged():
                return await router.read(
                    cluster.segment, 0, op="get", keys=[b"probe"],
                    policy=ReadPolicy.follower_ok(max_lag=5), timeout=5.0)

            for _ in range(rng.randint(6, 10)):
                try:
                    cluster._ioloop.run_sync(hedged(), timeout=10)
                except Exception:
                    timings["read_bounces"] += 1
        # writes must keep landing while the serving path is shedding
        cluster.write_some(rng, tag + "-during", rng.randint(4, 8), acked)
    finally:
        if saved_floor is None:
            os.environ.pop("RSTPU_HEDGE_FLOOR_MS", None)
        else:
            os.environ["RSTPU_HEDGE_FLOOR_MS"] = saved_floor
        fp.clear()
    if sheds == 0:
        violations.append(
            f"{tag}: overload schedule armed shed seams but ZERO typed "
            f"sheds fired (the zero-budget probes must shed)")
    time.sleep(rng.uniform(0.1, 0.3))


_FAILOVER_SCHEDULES = {
    "leader_crash": _schedule_leader_crash,
    "session_expiry": _schedule_session_expiry,
    "follower_expiry": _schedule_follower_expiry,
    "coordinator_failover": _schedule_coordinator_failover,
    "coordinator_wal_torn": _schedule_coordinator_wal_torn,
    "hb_delay": _schedule_blip("hb_delay"),
    "reap_blip": _schedule_blip("reap_blip"),
    "shardmap_blip": _schedule_blip("shardmap_blip"),
    "read_blip": _schedule_blip("read_blip"),
    "overload_shed": _schedule_overload_shed,
}
_HEAVY_KINDS = ["leader_crash", "session_expiry", "coordinator_failover",
                "coordinator_wal_torn", "follower_expiry"]
_LIGHT_KINDS = ["hb_delay", "reap_blip", "shardmap_blip", "read_blip",
                "overload_shed"]


def _failover_deck(rng: random.Random, schedules: int,
                   break_guard: Optional[str]) -> List[str]:
    """Seeded schedule deck: every heavy kind appears at least once when
    the run is long enough; the rest is a light-weighted draw. The
    fencing tooth leads with the schedule that carries the stale-frame
    probes."""
    deck: List[str] = []
    if break_guard == "fencing":
        deck.append("leader_crash")
    core = list(_HEAVY_KINDS)
    rng.shuffle(core)
    deck.extend(core[:max(0, schedules - len(deck))])
    while len(deck) < schedules:
        deck.append(rng.choice(_HEAVY_KINDS + _LIGHT_KINDS * 4))
    return deck[:schedules]


def _check_failover_invariants(cluster: FailoverCluster, acked, tag,
                               violations, timeout: float = 15.0) -> int:
    """The fourth standing invariant, checked after EVERY schedule:
    exactly one LEADER per shard (current states AND the published shard
    map), zero acked-write loss (every strict-ledger ack readable on
    every replica), and convergence within a bounded number of
    controller passes."""
    passes0 = cluster.controller.passes
    detail = {}

    def healthy():
        for partition in cluster.partitions:
            states = [s for s in cluster.states(partition).values() if s]
            if sorted(states) != ["FOLLOWER", "FOLLOWER", "LEADER"]:
                detail["states"] = cluster.states(partition)
                return False
        for db in cluster.db_names:
            seqs = cluster.seqs(db)
            if None in seqs or len(set(seqs)) != 1:
                detail["seqs"] = seqs
                return False
        for db in cluster.db_names:
            for n in cluster.nodes:
                app = n.handler.db_manager.get_db(db)
                if app is None:  # mid-repoint reopen
                    detail["lost"] = (n.name, "db closed")
                    return False
                for key, val in acked:
                    if app.db.get(key) != val:
                        detail["lost"] = (n.name, key)
                        return False
        if not cluster.maps:
            detail["map"] = "never published"
            return False
        seg = cluster.maps[-1].get(cluster.segment) or {}
        for s in range(cluster.num_shards):
            mark = f"{s:05d}:M"
            leaders = sum(
                1 for host, entries in seg.items()
                if host != "num_shards" for e in entries if e == mark)
            if leaders != 1:
                detail["map"] = f"shard {s}: {leaders} leaders in map"
                return False
        return True

    ok = cluster.wait(healthy, timeout)
    passes = cluster.controller.passes - passes0
    if not ok:
        violations.append(
            f"{tag}: NO HEAL within {timeout}s / {passes} controller "
            f"passes — {detail}")
    elif passes > FAILOVER_PASS_BOUND:
        violations.append(
            f"{tag}: healed but took {passes} controller passes "
            f"(bound {FAILOVER_PASS_BOUND})")
    return passes


def _check_read_invariants(cluster: FailoverCluster, acked, tag,
                           violations, timings) -> None:
    """Round-13 standing invariant, checked after every healed schedule:
    ZERO reads violate the client's staleness bound and ZERO reads are
    served from a deposed lineage.

    Method (race-free by construction): the workload is quiesced here,
    so after sleeping out ``read_info_ttl_ms`` every estimate a serving
    follower may rely on was heard AFTER the last commit — sampling the
    leader's committed seq L0 then makes ``applied_seq >= L0 - bound``
    an EXACT requirement for any served bounded read, not a heuristic.
    Bounces (STALE_READ / STALE_EPOCH) are always legal; serving outside
    the bound or from a stale lineage never is."""
    partition, db = cluster.partitions[0], cluster.db_names[0]
    leader = cluster.leader_node(partition)
    if leader is None:
        return  # heal already failed; invariant 4 reported it
    lrdb = leader.rdb(db)
    if lrdb is None:
        return
    epoch = lrdb.epoch
    time.sleep(FAILOVER_FLAGS.read_info_ttl_ms / 1000.0 + 0.05)
    lapp = leader.handler.db_manager.get_db(db)
    if lapp is None:
        return
    l0 = lapp.db.latest_sequence_number_relaxed()
    key, val = acked[-1] if acked else (b"probe", None)
    for node in cluster.nodes:
        for bound in (0, 5):
            timings["reads_checked"] += 1
            try:
                resp = cluster.rpc(
                    node.replicator.port, "read",
                    dict(db_name=db, op="get", keys=[key],
                         max_lag=bound, epoch=epoch))
            except Exception:
                timings["read_bounces"] += 1
                continue  # bouncing is always legal
            timings["reads_served"] += 1
            applied = int(resp.get("applied_seq") or 0)
            resp_epoch = int(resp.get("epoch") or 0)
            if applied < l0 - bound:
                violations.append(
                    f"{tag}: STALENESS BOUND VIOLATED — {node.name} "
                    f"served a max_lag={bound} read at applied_seq "
                    f"{applied} with leader committed {l0}")
            if resp_epoch < epoch:
                violations.append(
                    f"{tag}: READ SERVED FROM DEPOSED LINEAGE — "
                    f"{node.name} served at epoch {resp_epoch} < "
                    f"current {epoch}")
            if val is not None:
                got = resp["values"][0]
                got = bytes(got) if got is not None else None
                if got != val:
                    violations.append(
                        f"{tag}: read of acked key {key!r} on "
                        f"{node.name} returned {got!r} (want {val!r})")


def _gauge_snapshot(tag: str) -> Dict:
    """Round-14 state picture recorded in the artifact after each
    schedule: per-shard replication lag, ack-window occupancy, and
    compaction debt (the chaos clusters are in-process, so the gauges
    live on this process's Stats registry). An invariant violation now
    ships WITH the cluster's load/debt state at check time instead of
    leaving the reproducer to re-derive it."""
    from rocksplicator_tpu.utils.stats import Stats

    gauges = Stats.get().gauge_values(prefixes=(
        "replicator.applied_seq_lag",
        "replicator.ack_window_depth",
        "storage.compaction_debt_bytes",
        "storage.memtable_bytes",
    ))
    # debt gauges are per level — drop the all-zero ones so the
    # snapshot stays readable at 7 levels x N shards
    return {
        "schedule": tag,
        "gauges": {k: round(v, 1) for k, v in sorted(gauges.items())
                   if v or not k.startswith("storage.compaction_debt")},
    }


def run_failover_chaos(
    root: str,
    schedules: int = 15,
    seed: int = 1,
    break_guard: Optional[str] = None,
    heal_timeout: float = 15.0,
    log=print,
) -> Dict:
    """Coordinator-backed chaos: seeded control-plane fault schedules
    against a full Controller + Spectator + 3-participant cluster,
    holding the fourth standing invariant after every schedule."""
    saved_env = {
        k: os.environ.get(k)
        for k in ("RSTPU_RETRY_SEED", "RSTPU_PULL_RETRY_SEED")
    }
    os.environ["RSTPU_RETRY_SEED"] = str(seed)
    os.environ["RSTPU_PULL_RETRY_SEED"] = str(seed)
    undo = _break_guard(break_guard) if break_guard else None
    violations: List[str] = []
    acked: List[Tuple[bytes, bytes]] = []
    timings: Dict = {"failover_ms": [], "first_ack_ms": [],
                     "passes_used": [], "window_acked": 0,
                     "reads_checked": 0, "reads_served": 0,
                     "read_bounces": 0}
    gauge_snapshots: List[Dict] = []
    fp.clear()
    t_setup = time.monotonic()
    cluster = FailoverCluster(root)
    deck: List[str] = []
    try:
        cluster.wait_initial_convergence()
        setup_sec = round(time.monotonic() - t_setup, 1)
        deck = _failover_deck(random.Random(seed), schedules, break_guard)
        log(f"  cluster up in {setup_sec}s; deck: {deck}")
        for si, kind in enumerate(deck):
            rng = random.Random(seed * 1_000_003 + si)
            tag = f"s{si:02d}-{kind}/seed {seed}"
            try:
                _FAILOVER_SCHEDULES[kind](
                    cluster, rng, acked, violations, tag, timings)
            finally:
                fp.clear()  # no fault outlives its schedule
            timings["passes_used"].append(
                _check_failover_invariants(cluster, acked, tag, violations,
                                           timeout=heal_timeout))
            # round-13 standing invariant: bounded-staleness + lineage
            # rules hold on every replica once the schedule healed
            _check_read_invariants(cluster, acked, tag, violations,
                                   timings)
            gauge_snapshots.append(_gauge_snapshot(tag))
            log(f"  [{si + 1}/{len(deck)}] {kind}: acked={len(acked)} "
                f"reads={timings['reads_served']}"
                f"/{timings['reads_checked']} "
                f"violations={len(violations)}")
            if violations and break_guard:
                break  # teeth demonstrated
    finally:
        fp.clear()
        if undo:
            undo()
        cluster.stop()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    def _med(xs):
        return round(sorted(xs)[len(xs) // 2], 1) if xs else None

    return {
        "mode": "failover",
        "schedules": len(deck),
        "deck": deck,
        "seed": seed,
        "acked": len(acked),
        "window_acked": timings["window_acked"],
        "violations": violations,
        "failover_ms": [round(x, 1) for x in timings["failover_ms"]],
        "failover_ms_median": _med(timings["failover_ms"]),
        "first_ack_ms": [round(x, 1) for x in timings["first_ack_ms"]],
        "first_ack_ms_median": _med(timings["first_ack_ms"]),
        "passes_used": timings["passes_used"],
        "reads_checked": timings["reads_checked"],
        "reads_served": timings["reads_served"],
        "read_bounces": timings["read_bounces"],
        "gauge_snapshots": gauge_snapshots,
        "failpoint_trips": fp.trip_counts(),
        "break_guard": break_guard,
    }


# ---------------------------------------------------------------------------
# reshard chaos: live shard moves under fault (round 15)
# ---------------------------------------------------------------------------

# the move step machine's failpoint seams: arming fail_nth on one IS the
# "kill the move coordinator at this phase" schedule (registration
# asserted by tests like the other menus)
_RESHARD_FAULT_SITES = [
    "move.record", "move.snapshot", "move.restore", "move.catchup",
    "move.flip", "move.retire",
    "coordinator.heartbeat", "coordinator.wal.append", "repl.pull",
    "rpc.frame.send",
]

# every actor × phase: the mover killed at each of its five seams (+ the
# ledger-write seam), the source/target participants killed mid-move,
# cluster-wide session expiry, the coordinator torn/killed, a data-plane
# fault riding the whole move, plus clean leader/follower moves and a
# whole-node drain
_RESHARD_KINDS = [
    "move_clean_leader", "move_clean_follower", "move_drain",
    "move_crash_record", "move_crash_snapshot", "move_crash_restore",
    "move_crash_catchup", "move_crash_flip", "move_crash_retire",
    "move_kill_source", "move_kill_target", "move_session_expiry",
    "move_coord_torn", "move_coord_failover", "move_fault_dataplane",
]


def _move_flags():
    """Chaos-sized move pacing: many move→fault→heal cycles per minute."""
    from rocksplicator_tpu.cluster.shard_move import MoveFlags

    return MoveFlags(
        catchup_lag_threshold=16, catchup_timeout=40.0,
        cutover_pause_ms=4000.0, cutover_attempts=3,
        flip_timeout=25.0, retire_timeout=25.0,
        poll_interval=0.05, record_update_interval=0.25,
    )


class _BgWriter:
    """Continuous write load riding THROUGH every move phase — the acked
    ledger the zero-loss-across-the-move invariant is checked against.
    Writes go to whichever node currently claims leadership; errors
    (WRITE_PAUSED during cutover, NOT_LEADER mid-flip, no leader) are
    expected and counted, never acked."""

    def __init__(self, cluster: FailoverCluster, tag: str,
                 interval: float = 0.02):
        self.cluster = cluster
        self.tag = tag
        self.interval = interval
        self.errors = 0
        self.window_acked = 0
        # participant-kill / session-expiry schedules flip this ON at
        # the kill: from that instant leadership may churn with a
        # deposed-but-uninformed leader still granting acks — the
        # documented r11 semi-sync visibility-window residual. Writes
        # SUBMITTED while the window is open are counted but not held
        # to the strict ledger (exactly the r11 session-expiry
        # accounting); pre-kill acks stay strict.
        self.window_mode = False
        self._waiters: List = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="chaos-move-writer", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        partition = self.cluster.partitions[0]
        db = self.cluster.db_names[0]
        i = 0
        while not self._stop.wait(self.interval):
            i += 1
            key = f"{self.tag}-bg{i:05d}".encode()
            node = self.cluster.leader_node(partition)
            app = (node.handler.db_manager.get_db(db)
                   if node is not None else None)
            if app is None:
                self.errors += 1
                continue
            strict = not self.window_mode
            try:
                w = app.write_async(WriteBatch().put(key, key))
            except Exception:
                self.errors += 1
                continue
            with self._lock:
                self._waiters.append((key, key, w, strict))

    def _collect_one(self, item, acked) -> None:
        key, val, w, strict = item
        if not w.acked:
            return
        if strict:
            acked.append((key, val))
        else:
            self.window_acked += 1

    def harvest(self, acked: List[Tuple[bytes, bytes]]) -> None:
        """Move already-resolved acks into the ledger NOW — the sharp
        post-flip probes check against writes acked before the flip."""
        with self._lock:
            pending = []
            for item in self._waiters:
                if item[2].future.done():
                    self._collect_one(item, acked)
                else:
                    pending.append(item)
            self._waiters = pending

    def stop_collect(self, acked: List[Tuple[bytes, bytes]]) -> None:
        self._stop.set()
        self._thread.join(timeout=10.0)
        with self._lock:
            waiters, self._waiters = self._waiters, []
        for item in waiters:
            try:
                item[2].future.result(3.0)
            except Exception:
                continue
            self._collect_one(item, acked)


def _start_move_bg(cluster: FailoverCluster, source_iid: str,
                   target_iid: str, flags) -> Dict:
    """Run one coordinated move in a background thread (the 'move
    coordinator' actor the schedules kill) against the harness's shared
    coordinator/admin clients."""
    from rocksplicator_tpu.cluster.shard_move import ShardMove

    box: Dict = {"mover": None, "error": None, "record": None,
                 "done": threading.Event()}
    partition = cluster.partitions[0]

    def go():
        try:
            mv = ShardMove.start(
                cluster.client, cluster.cluster, partition, source_iid,
                target_iid, cluster.store_uri, admin=cluster.admin,
                flags=flags)
            box["mover"] = mv
            box["record"] = mv.run()
        except BaseException as e:
            box["error"] = e
        finally:
            box["done"].set()

    t = threading.Thread(target=go, name="chaos-mover", daemon=True)
    t.start()
    box["thread"] = t
    return box


def _start_drain_bg(cluster: FailoverCluster, node, flags) -> Dict:
    from rocksplicator_tpu.cluster.shard_move import drain_node

    box: Dict = {"mover": None, "error": None, "record": None,
                 "done": threading.Event()}

    def go():
        try:
            box["record"] = drain_node(
                cluster.client, cluster.cluster,
                node.instance.instance_id, cluster.store_uri,
                admin=cluster.admin, flags=flags,
                log_fn=lambda *_a, **_k: None)
        except BaseException as e:
            box["error"] = e
        finally:
            box["done"].set()

    t = threading.Thread(target=go, name="chaos-drainer", daemon=True)
    t.start()
    box["thread"] = t
    return box


def _wait_move_phase(box: Dict, phase: str, timeout: float = 30.0) -> bool:
    """Wait until the mover has ENTERED ``phase`` (or finished/crashed —
    both mean the seam was passed or will never be reached)."""
    from rocksplicator_tpu.cluster.shard_move import PHASES

    order = {p: i for i, p in enumerate(PHASES)}
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if box["done"].is_set():
            return True
        mv = box.get("mover")
        if mv is not None and order.get(mv.rec.phase, -1) >= order[phase]:
            return True
        time.sleep(0.02)
    return False


def _finish_move(cluster: FailoverCluster, box: Dict, rng: random.Random,
                 tag: str, violations: List[str], flags,
                 timeout: float = 90.0) -> str:
    """Drive the move to a TERMINAL state: completed as launched, or —
    after a crash — either resumed to completion or cleanly aborted
    (seeded choice where both are legal). A move that can do neither is
    the 'half-flipped map' state the step machine exists to prevent:
    a violation."""
    from rocksplicator_tpu.cluster.model import cluster_path
    from rocksplicator_tpu.cluster.shard_move import MoveRecord, ShardMove

    if not box["done"].wait(timeout):
        violations.append(f"{tag}: move wedged (no exit in {timeout}s)")
        return "wedged"
    partition = cluster.partitions[0]
    if box["error"] is None:
        return "completed"
    raw = cluster.client.get_or_none(
        cluster_path(cluster.cluster, "moves", partition))
    if raw is None:
        # crashed before the ledger write landed (or after the final
        # delete): nothing half-done exists to resume
        return "no_record"
    rec = MoveRecord.decode(raw)
    abortable = rec.phase in ("planned", "snapshot", "restore", "catchup")
    if abortable and rng.random() < 0.5:
        try:
            ShardMove.resume(cluster.client, cluster.cluster, partition,
                             admin=cluster.admin, flags=flags).abort()
            return "aborted"
        except Exception as e:
            violations.append(
                f"{tag}: ABORT FAILED from phase {rec.phase}: {e!r}")
            return "abort_failed"
    last: Optional[BaseException] = None
    for _attempt in range(2):
        try:
            ShardMove.resume(cluster.client, cluster.cluster, partition,
                             admin=cluster.admin, flags=flags).run()
            return "resumed"
        except Exception as e:
            last = e
            time.sleep(0.5)
    violations.append(
        f"{tag}: RESUME FAILED from phase {rec.phase} (half-flipped "
        f"state left behind): {last!r}")
    return "resume_failed"


def _probe_serving_lineages(cluster: FailoverCluster, tag: str,
                            violations: List[str],
                            duration: float = 1.5) -> None:
    """Sharp lineage check sampled across the flip window: at NO instant
    may two unfenced data-plane LEADERs coexist — the pinned two-phase
    flip demotes the source before the target may promote, and the
    ``move_flip`` tooth (force-promote without drain/demote) is exactly
    what this catches."""
    db = cluster.db_names[0]
    deadline = time.monotonic() + duration
    while time.monotonic() < deadline:
        leaders = []
        for n in cluster.nodes:
            rdb = n.rdb(db)
            if (rdb is not None and rdb.role is ReplicaRole.LEADER
                    and not rdb.fenced and not rdb.removed):
                leaders.append(n.name)
        if len(leaders) > 1:
            violations.append(
                f"{tag}: TWO SERVING LINEAGES — unfenced leaders "
                f"{leaders} coexist (flip before demote)")
            return
        time.sleep(0.03)


def _probe_new_lineage(cluster: FailoverCluster, box: Dict,
                       acked: List[Tuple[bytes, bytes]], tag: str,
                       violations: List[str]) -> None:
    """The moment the cutover claims completion (phase → retire), every
    write acked so far must be readable on the NEW leader — a flip that
    beat catch-up shows up here as a hole in the acked ledger."""
    mv = box.get("mover")
    if mv is None or not mv.rec.moving_leader:
        return
    node = next((n for n in cluster.nodes
                 if n.instance.instance_id == mv.rec.target), None)
    if node is None:
        return
    db = cluster.db_names[0]
    deadline = time.monotonic() + 2.0
    app = None
    while time.monotonic() < deadline and app is None:
        app = node.handler.db_manager.get_db(db)
        if app is None:
            time.sleep(0.05)
    if app is None:
        return  # mid-reopen; the post-schedule invariant check covers it
    for key, val in list(acked)[-20:]:
        try:
            got = app.db.get(key)
        except Exception:
            return
        if got != val:
            violations.append(
                f"{tag}: ACKED WRITE {key!r} MISSING ON NEW LINEAGE "
                f"{mv.rec.target} (flip before catch-up completed)")
            return


def _reshard_schedule(kind: str):
    def run(cluster: FailoverCluster, rng: random.Random, acked,
            violations: List[str], tag: str, timings: Dict) -> None:
        from rocksplicator_tpu.cluster.model import cluster_path

        partition = cluster.partitions[0]
        cluster.write_some(rng, tag + "-pre", rng.randint(4, 8), acked)
        # generous window: the PREVIOUS schedule's healed participants
        # (rejoins, deposed resyncs, late drops) may still be settling
        if not _wait_replicas_equal(cluster, timeout=25.0):
            violations.append(f"{tag}: baseline never converged")
            return
        move_leader = kind != "move_clean_follower"
        leader = cluster.leader_node(partition)
        followers = [n for n in cluster.nodes
                     if n.state_of(partition) in ("FOLLOWER", "SLAVE")]
        free = [n for n in cluster.nodes if not n.state_of(partition)]
        if leader is None or not free or (
                not move_leader and not followers):
            violations.append(f"{tag}: no legal move endpoints "
                              f"({cluster.states(partition)})")
            return
        source = leader if move_leader else rng.choice(followers)
        target = rng.choice(free)
        flags = _move_flags()
        writer = _BgWriter(cluster, tag)
        healers: List[FailoverNode] = []
        t0 = time.monotonic()
        outcome = "?"
        try:
            if kind == "move_coord_torn":
                # the flip's durable writes (move ledger, pin, epoch)
                # hit a torn coordinator WAL: the primary fail-stops and
                # the mover's mutation dies mid-flight
                fp.activate(
                    "coordinator.wal.append",
                    f"torn:1.0@seed{rng.randrange(1 << 16)},one_shot")
            crash_site = {
                "move_crash_record": ("move.record", "fail_nth:2"),
                "move_crash_snapshot": ("move.snapshot", "fail_nth:1"),
                "move_crash_restore": ("move.restore", "fail_nth:1"),
                "move_crash_catchup": ("move.catchup", "fail_nth:1"),
                "move_crash_flip": ("move.flip", "fail_nth:1"),
                "move_crash_retire": ("move.retire", "fail_nth:1"),
            }.get(kind)
            if crash_site:
                fp.activate(*crash_site)
            if kind == "move_fault_dataplane":
                s = rng.randrange(1 << 16)
                fp.activate(
                    rng.choice(["repl.pull", "rpc.frame.send"]),
                    f"fail_prob:{rng.uniform(0.03, 0.10):.3f}@seed{s}")
            if kind == "move_drain":
                box = _start_drain_bg(cluster, source, flags)
            else:
                box = _start_move_bg(
                    cluster, source.instance.instance_id,
                    target.instance.instance_id, flags)
            if kind == "move_kill_source":
                if _wait_move_phase(box, "catchup"):
                    # from here leadership may churn with deposed-but-
                    # uninformed claimers: acks ride the r11-documented
                    # visibility window, not the strict ledger
                    writer.window_mode = True
                    source.participant.coord.suspend_heartbeats()
                    healers.append(source)
            elif kind == "move_kill_target":
                if _wait_move_phase(box,
                                    rng.choice(["restore", "catchup"])):
                    writer.window_mode = True
                    target.participant.coord.suspend_heartbeats()
                    healers.append(target)
            elif kind == "move_session_expiry":
                if _wait_move_phase(
                        box, rng.choice(["snapshot", "restore",
                                         "catchup"])):
                    writer.window_mode = True
                    fp.activate("coordinator.heartbeat",
                                f"fail_first:{rng.randint(25, 45)}")
                    time.sleep(FAILOVER_SESSION_TTL * 1.7)
                    fp.deactivate("coordinator.heartbeat")
            elif kind == "move_coord_failover":
                if _wait_move_phase(box,
                                    rng.choice(["restore", "catchup"])):
                    _coordinator_failover(cluster, tag, violations)
            # sharp flip-window probes — only where every participant
            # stays responsive, so the two-phase demote-before-promote
            # discipline is actually observable: under participant
            # kills / session expiry / coordinator faults a wedged
            # deposed leader legitimately lingers as an unfenced zombie
            # (the documented r11 state — it cannot ACK and cannot
            # serve lineage-valid reads, which invariants 4/5 check;
            # it fences on first contact)
            probing = kind in (
                "move_clean_leader", "move_clean_follower", "move_drain",
                "move_crash_record", "move_crash_snapshot",
                "move_crash_restore", "move_crash_catchup",
                "move_crash_flip", "move_crash_retire",
                "move_fault_dataplane")
            if probing and _wait_move_phase(box, "retire", timeout=60.0):
                writer.harvest(acked)
                _probe_serving_lineages(cluster, tag, violations)
                _probe_new_lineage(cluster, box, acked, tag, violations)
            if violations and timings.get("fast_fail"):
                # teeth run: the broken guard is caught — don't spend a
                # minute trying to recover a deliberately-broken flip
                return
            if crash_site:
                fp.deactivate(crash_site[0])
            if kind == "move_coord_torn":
                # the tear fail-stopped a coordinator (the mover's
                # ledger write died with it): heal the control plane
                # BEFORE terminal recovery, exactly like the r11
                # coordinator_wal_torn schedule
                box["done"].wait(30.0)
                fp.deactivate("coordinator.wal.append")
                primary_fenced = (cluster.primary._wal is not None
                                  and cluster.primary._wal.failed
                                  is not None)
                standby_fenced = (cluster.standby._wal is not None
                                  and cluster.standby._wal.failed
                                  is not None)
                if primary_fenced:
                    _coordinator_failover(cluster, tag, violations)
                elif standby_fenced:
                    from rocksplicator_tpu.cluster.coordinator import \
                        CoordinatorServer

                    cluster.standby.stop()
                    cluster.standby = CoordinatorServer(
                        port=0, session_ttl=FAILOVER_SESSION_TTL,
                        data_dir=cluster._coord_dir(),
                        replica_of=("127.0.0.1", cluster.primary.port))
            # a killed participant must heal BEFORE terminal recovery:
            # resume/abort legitimately need its admin plane back
            if healers:
                box["done"].wait(60.0)
                for n in healers:
                    n.participant.coord.resume_heartbeats()
                for n in healers:
                    node_path = cluster_path(
                        cluster.cluster, "instances",
                        n.instance.instance_id)
                    cluster.wait(
                        lambda: cluster.client.exists(node_path), 10.0)
                healers.clear()
            outcome = _finish_move(cluster, box, rng, tag, violations,
                                   flags)
            if probing and outcome in ("completed", "resumed"):
                _probe_serving_lineages(cluster, tag, violations,
                                        duration=0.5)
        finally:
            for n in healers:
                n.participant.coord.resume_heartbeats()
            fp.clear()
            writer.stop_collect(acked)
        timings["move_outcomes"][outcome] = \
            timings["move_outcomes"].get(outcome, 0) + 1
        timings["move_ms"].append(
            round((time.monotonic() - t0) * 1000.0, 1))
        timings["write_errors"] += writer.errors
        timings["window_acked"] += writer.window_acked

    return run


def _reshard_deck(rng: random.Random, schedules: int,
                  break_guard: Optional[str]) -> List[str]:
    """Every kind at least once when the run is long enough; the
    move_flip tooth leads with the clean leader move it breaks."""
    deck: List[str] = []
    if break_guard == "move_flip":
        deck.append("move_clean_leader")
    core = list(_RESHARD_KINDS)
    rng.shuffle(core)
    deck.extend(core[:max(0, schedules - len(deck))])
    while len(deck) < schedules:
        deck.append(rng.choice(_RESHARD_KINDS))
    return deck[:schedules]


def _check_reshard_invariants(cluster: FailoverCluster, acked, tag: str,
                              violations: List[str],
                              timeout: float = 30.0) -> int:
    """The SIXTH standing invariant, after EVERY reshard schedule:
    exactly one serving lineage per shard (current states, the
    published map, AND the data plane agree on one unfenced leader),
    zero acked-write loss across the move (every acked key readable on
    every CURRENT host — the hosting set itself may have moved), no
    stranded replicas (a non-host holding the db = un-swept move
    garbage), and convergence within the controller-pass bound."""
    partition, db = cluster.partitions[0], cluster.db_names[0]
    passes0 = cluster.controller.passes
    detail: Dict = {}

    def healthy():
        from rocksplicator_tpu.storage.errors import StorageError

        hosts = [n for n in cluster.nodes if n.state_of(partition)]
        states = sorted(n.state_of(partition) for n in hosts)
        if states != ["FOLLOWER", "FOLLOWER", "LEADER"]:
            detail["states"] = cluster.states(partition)
            return False
        seqs = []
        apps = {}
        try:
            for n in hosts:
                app = n.handler.db_manager.get_db(db)
                if app is None:
                    detail["lost"] = (n.name, "db closed")
                    return False
                apps[n.name] = app
                seqs.append(app.db.latest_sequence_number_relaxed())
            if len(set(seqs)) != 1:
                detail["seqs"] = seqs
                return False
            host_names = {n.name for n in hosts}
            for n in cluster.nodes:
                if n.name not in host_names and \
                        n.handler.db_manager.get_db(db) is not None:
                    detail["garbage"] = n.name  # un-swept move replica
                    return False
            for n in hosts:
                app = apps[n.name]
                for key, val in acked:
                    if app.db.get(key) != val:
                        detail["lost"] = (n.name, key)
                        return False
        except StorageError as e:
            # a handle we resolved raced a reopen (repoint/rejoin
            # transition mid-sample): not healthy YET, re-sample
            detail["transition"] = repr(e)
            return False
        if not cluster.maps:
            detail["map"] = "never published"
            return False
        seg = cluster.maps[-1].get(cluster.segment) or {}
        for s in range(cluster.num_shards):
            mark = f"{s:05d}:M"
            leaders = sum(
                1 for host, entries in seg.items()
                if host != "num_shards" for e in entries if e == mark)
            if leaders != 1:
                detail["map"] = f"shard {s}: {leaders} leaders in map"
                return False
        dp_leaders = []
        for n in cluster.nodes:
            rdb = n.rdb(db)
            if (rdb is not None and rdb.role is ReplicaRole.LEADER
                    and not rdb.fenced and not rdb.removed):
                dp_leaders.append(n.name)
        if len(dp_leaders) != 1:
            detail["lineages"] = dp_leaders
            return False
        return True

    def stable_healthy():
        # a rejoining participant can look healthy for an instant while
        # its re-applied assignment is about to reopen a db — require
        # the state to hold across a short window
        if not healthy():
            return False
        time.sleep(0.35)
        return healthy()

    ok = cluster.wait(stable_healthy, timeout)
    passes = cluster.controller.passes - passes0
    if not ok:
        violations.append(
            f"{tag}: NO HEAL within {timeout}s / {passes} controller "
            f"passes — {detail}")
    elif passes > RESHARD_PASS_BOUND:
        violations.append(
            f"{tag}: healed but took {passes} controller passes "
            f"(bound {RESHARD_PASS_BOUND})")
    return passes


def run_reshard_chaos(
    root: str,
    schedules: int = 15,
    seed: int = 1,
    break_guard: Optional[str] = None,
    heal_timeout: float = 30.0,
    log=print,
) -> Dict:
    """Live shard moves under fault: seeded schedules kill the move
    coordinator at every step-machine seam, kill the source/target
    participants mid-move, tear the coordinator WAL during the flip,
    and expire sessions mid-catch-up — holding the SIXTH standing
    invariant after every schedule, with continuous write load riding
    through every move."""
    saved_env = {
        k: os.environ.get(k)
        for k in ("RSTPU_RETRY_SEED", "RSTPU_PULL_RETRY_SEED")
    }
    os.environ["RSTPU_RETRY_SEED"] = str(seed)
    os.environ["RSTPU_PULL_RETRY_SEED"] = str(seed)
    undo = _break_guard(break_guard) if break_guard else None
    violations: List[str] = []
    acked: List[Tuple[bytes, bytes]] = []
    timings: Dict = {"move_ms": [], "move_outcomes": {},
                     "passes_used": [], "write_errors": 0,
                     "window_acked": 0,
                     "reads_checked": 0, "reads_served": 0,
                     "read_bounces": 0,
                     "fast_fail": bool(break_guard)}
    gauge_snapshots: List[Dict] = []
    fp.clear()
    t_setup = time.monotonic()
    cluster = FailoverCluster(root, num_nodes=4)
    deck: List[str] = []
    try:
        cluster.wait_initial_convergence()
        setup_sec = round(time.monotonic() - t_setup, 1)
        deck = _reshard_deck(random.Random(seed), schedules, break_guard)
        log(f"  cluster up in {setup_sec}s (4 nodes / 3 replicas); "
            f"deck: {deck}")
        for si, kind in enumerate(deck):
            rng = random.Random(seed * 1_000_003 + si)
            tag = f"s{si:02d}-{kind}/seed {seed}"
            try:
                _reshard_schedule(kind)(
                    cluster, rng, acked, violations, tag, timings)
            finally:
                fp.clear()
            if violations and break_guard:
                break  # teeth demonstrated — skip the 30 s heal wait
            timings["passes_used"].append(
                _check_reshard_invariants(cluster, acked, tag, violations,
                                          timeout=heal_timeout))
            _check_read_invariants(cluster, acked, tag, violations,
                                   timings)
            gauge_snapshots.append(_gauge_snapshot(tag))
            log(f"  [{si + 1}/{len(deck)}] {kind}: acked={len(acked)} "
                f"moves={timings['move_outcomes']} "
                f"violations={len(violations)}")
            if violations and break_guard:
                break
    finally:
        fp.clear()
        if undo:
            undo()
        cluster.stop()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    def _med(xs):
        return round(sorted(xs)[len(xs) // 2], 1) if xs else None

    return {
        "mode": "reshard",
        "schedules": len(deck),
        "deck": deck,
        "seed": seed,
        "acked": len(acked),
        "window_acked": timings["window_acked"],
        "write_errors": timings["write_errors"],
        "violations": violations,
        "move_outcomes": timings["move_outcomes"],
        "move_ms": timings["move_ms"],
        "move_ms_median": _med(timings["move_ms"]),
        "passes_used": timings["passes_used"],
        "reads_checked": timings["reads_checked"],
        "reads_served": timings["reads_served"],
        "read_bounces": timings["read_bounces"],
        "gauge_snapshots": gauge_snapshots,
        "failpoint_trips": fp.trip_counts(),
        "break_guard": break_guard,
    }


# ---------------------------------------------------------------------------
# rebalance schedules (round 20): the POLICY decides — the harness only
# drives skewed load and checks the aftermath
# ---------------------------------------------------------------------------

# 2 policy-initiated moves + 1 policy-initiated split per 3-schedule
# smoke: the harness never names a source/target/split key — it offers
# a hot shard and the rebalancer's sense→decide→plan→dispatch loop does
# the rest (the faulted variant blips every rebalancer seam —
# "rebalance.decide" / "rebalance.plan" / "rebalance.dispatch" — plus
# the dispatched move's catch-up, and the split schedule kills the
# splitter AT "split.cutover"; both must recover via resume)
_REBALANCE_KINDS = [
    "rebalance_move_hot", "rebalance_split_hot", "rebalance_move_faulted",
]


def _rebalance_flags(split: bool):
    """Chaos-sized policy knobs: fast EWMA, 2-tick sustain, thresholds
    scaled to a 2-shard fleet (with N=2 the reference hot_factor=2.0 is
    unreachable — hot > 2x mean needs hot > hot + cold)."""
    from rocksplicator_tpu.cluster.rebalancer import RebalancerFlags

    return RebalancerFlags(
        interval=0.0, ewma_alpha=0.7, hot_factor=1.2, cool_factor=1.05,
        sustain=2, max_concurrent=1,
        split_factor=(1.5 if split else 100.0), min_rate=2.0)


class _SeqRateLoad:
    """db_name -> write rate measured from the data plane's OWN sequence
    numbers (per-db max across nodes, delta over wall time). The
    rebalancer's production load_fn scrapes /cluster_stats; the chaos
    cluster runs no status servers, so the harness feeds the policy the
    same signal from the source those rates are derived from — real
    load, never a synthesized number."""

    def __init__(self, cluster: FailoverCluster):
        self.cluster = cluster
        self._prev: Dict[str, Tuple[int, float]] = {}

    def __call__(self) -> Optional[Dict[str, float]]:
        from rocksplicator_tpu.utils.segment_utils import \
            partition_name_to_db_name

        now = time.monotonic()
        dbs = set()
        for n in self.cluster.nodes:
            for partition, st in list(
                    n.participant.current_states.items()):
                if st in ("LEADER", "MASTER", "FOLLOWER", "SLAVE"):
                    dbs.add(partition_name_to_db_name(partition))
        rates: Dict[str, float] = {}
        for db in dbs:
            seqs = [s for s in self.cluster.seqs(db) if s is not None]
            if not seqs:
                continue
            seq = max(seqs)
            prev = self._prev.get(db)
            self._prev[db] = (seq, now)
            if prev is None:
                continue  # first sighting (fresh split child): no rate
            rates[db] = max(0.0, (seq - prev[0]) / max(1e-3,
                                                       now - prev[1]))
        for db in list(self._prev):
            if db not in dbs:
                del self._prev[db]  # renamed away mid-split / retired
        return rates or None


class _ShardWriter:
    """Write load aimed at ONE partition's current leader — the hot (or
    cold) side of the skew the policy observes. Acked (key, val) pairs
    land in a per-hash-shard ledger; after a split the checker resolves
    each key to its OWNING child by range, so the acked-readability
    probe follows the keys across the cutover."""

    def __init__(self, cluster: FailoverCluster, shard: int, tag: str,
                 interval: float, acked_by_shard: Dict[int, List],
                 prefix: bytes = b"k"):
        from rocksplicator_tpu.utils.segment_utils import (
            db_name_to_partition_name, segment_to_db_name)

        self.cluster = cluster
        self.shard = shard
        self.db = segment_to_db_name(cluster.segment, shard)
        self.partition = db_name_to_partition_name(self.db)
        self.tag = tag
        self.interval = interval
        self.prefix = prefix
        self.errors = 0
        self._ledger = acked_by_shard.setdefault(shard, [])
        self._waiters: List = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"chaos-rebalance-writer-{shard}",
            daemon=True)
        self._thread.start()

    def _run(self) -> None:
        i = 0
        while not self._stop.wait(self.interval):
            i += 1
            key = self.prefix + (b"%s-%05d" % (self.tag.encode(), i))
            node = self.cluster.leader_node(self.partition)
            app = (node.handler.db_manager.get_db(self.db)
                   if node is not None else None)
            if app is None:
                self.errors += 1  # paused / renamed mid-split: expected
                continue
            try:
                w = app.write_async(WriteBatch().put(key, key))
            except Exception:
                self.errors += 1
                continue
            with self._lock:
                self._waiters.append((key, w))

    def stop_collect(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10.0)
        with self._lock:
            waiters, self._waiters = self._waiters, []
        for key, w in waiters:
            try:
                w.future.result(3.0)
            except Exception:
                continue
            if w.acked:
                self._ledger.append((key, key))


def _split_leaves(cluster: FailoverCluster) -> List[int]:
    """The serving frontier: every hash slot chased through ACTIVE
    split records to its leaf children (the controller's
    effective_shards over the live ledger)."""
    from rocksplicator_tpu.cluster.shard_split import list_splits

    by_parent = {
        r.parent_shard: r
        for r in list_splits(cluster.client, cluster.cluster)
        if r.segment == cluster.segment and r.phase == "active"}
    leaves: List[int] = []

    def chase(s: int) -> None:
        r = by_parent.get(s)
        if r is None:
            leaves.append(s)
        else:
            chase(r.low_shard)
            chase(r.high_shard)

    for s in range(cluster.num_shards):
        chase(s)
    return leaves


def _owning_leaf(cluster: FailoverCluster, shard: int, key: bytes) -> int:
    """Which leaf serves ``key`` under hash slot ``shard`` — the same
    transitive range chase the router runs."""
    from rocksplicator_tpu.cluster.shard_split import list_splits

    by_parent = {
        r.parent_shard: r
        for r in list_splits(cluster.client, cluster.cluster)
        if r.segment == cluster.segment and r.phase == "active"}
    while shard in by_parent:
        r = by_parent[shard]
        shard = (r.low_shard if key < r.split_key_bytes
                 else r.high_shard)
    return shard


def _tick_rebalancer(reb, timings: Dict, tag: str,
                     violations: List[str], want_kind: str,
                     timeout: float = 30.0) -> List[dict]:
    """Drive sense→decide→plan→dispatch ticks until a plan of
    ``want_kind`` dispatches. Armed rebalancer seams raise out of a
    tick; the next tick re-derives everything from the durable ledgers
    (exactly what run_forever's catch-all rides on)."""
    dispatched: List[dict] = []
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            dispatched += reb.once()
        except Exception:
            timings["tick_errors"] += 1
        if any(p["kind"] == want_kind for p in dispatched):
            return dispatched
        time.sleep(0.3)
    violations.append(
        f"{tag}: rebalancer never dispatched a {want_kind} "
        f"(policy {reb.policy.snapshot()}, dispatched {dispatched})")
    return dispatched


def _join_rebalance_workers(reb, tag: str, violations: List[str],
                            timeout: float = 90.0) -> None:
    deadline = time.monotonic() + timeout
    for t in list(reb._workers):
        t.join(max(0.1, deadline - time.monotonic()))
        if t.is_alive():
            violations.append(f"{tag}: actuator {t.name} wedged "
                              f"(no exit in {timeout}s)")


def _rebalance_schedule(kind: str):
    def run(cluster: FailoverCluster, rng: random.Random,
            acked_by_shard: Dict[int, List], violations: List[str],
            tag: str, timings: Dict) -> None:
        from rocksplicator_tpu.cluster.model import cluster_path
        from rocksplicator_tpu.cluster.rebalancer import Rebalancer
        from rocksplicator_tpu.cluster.shard_move import MoveRecord, \
            ShardMove
        from rocksplicator_tpu.cluster.shard_split import ShardSplit
        from rocksplicator_tpu.utils.segment_utils import (
            db_name_to_partition_name, segment_to_db_name)

        want_split = kind == "rebalance_split_hot"
        leaves = _split_leaves(cluster)
        hot, cold = leaves[0], leaves[1:]
        hot_db = segment_to_db_name(cluster.segment, hot)
        hot_partition = db_name_to_partition_name(hot_db)
        if want_split:
            # enough keys on BOTH sides of the eventual median that
            # choose_split_key has a real keyspace to bisect and both
            # children inherit acked history to be checked against
            pre = acked_by_shard.setdefault(hot, [])
            node = cluster.leader_node(hot_partition)
            app = (node.handler.db_manager.get_db(hot_db)
                   if node is not None else None)
            if app is None:
                violations.append(f"{tag}: no leader to preload")
                return
            waiters = []
            for i in range(120):
                for prefix in (b"a", b"z"):
                    key = prefix + (b"%05d" % i)
                    waiters.append((key, app.write_async(
                        WriteBatch().put(key, key))))
            for key, w in waiters:
                try:
                    w.future.result(5.0)
                except Exception:
                    continue
                if w.acked:
                    pre.append((key, key))
        reb = Rebalancer(
            cluster.client, cluster.cluster, cluster.store_uri,
            flags=_rebalance_flags(split=want_split),
            move_flags=_move_flags(), admin=cluster.admin,
            load_fn=_SeqRateLoad(cluster))
        # the durable pause flag gates the tick before any sensing
        Rebalancer.set_paused(cluster.client, cluster.cluster, True)
        if not reb.paused:
            violations.append(f"{tag}: durable pause flag not visible")
        Rebalancer.set_paused(cluster.client, cluster.cluster, False)
        if kind == "rebalance_move_faulted":
            # every rebalancer seam blipped once + the dispatched
            # move killed mid-catch-up: the loop must ride the seam
            # faults and the harness must RESUME the crashed move
            fp.activate("rebalance.decide", "fail_nth:2")
            fp.activate("rebalance.plan", "fail_nth:1")
            fp.activate("rebalance.dispatch", "fail_nth:1")
            fp.activate("move.catchup", "fail_nth:1")
        elif want_split and not timings.get("fast_fail"):
            # kill the splitter AT the fenced flip: the durable record
            # holds phase=cutover; resume finishes it idempotently
            fp.activate("split.cutover", "fail_nth:1")
        # the skew: one shard driven hard, the rest trickling — fleet
        # mean stays low, the hot EWMA must clear the enter band for
        # `sustain` consecutive ticks before the policy may act
        writers = [_ShardWriter(cluster, hot, tag, interval=0.004,
                                acked_by_shard=acked_by_shard,
                                prefix=b"zz")]
        writers += [_ShardWriter(cluster, s, tag, interval=0.25,
                                 acked_by_shard=acked_by_shard)
                    for s in cold[:2]]
        t0 = time.monotonic()
        try:
            plans = _tick_rebalancer(
                reb, timings, tag, violations,
                want_kind="split" if want_split else "move")
            _join_rebalance_workers(reb, tag, violations)
            for p in plans:
                timings["dispatched"][p["kind"]] = \
                    timings["dispatched"].get(p["kind"], 0) + 1
            fp.clear()
            # a crashed actuator left its durable record mid-phase:
            # finish the job the way an operator (or the next tick's
            # budget accounting + resume tooling) would
            if kind == "rebalance_move_faulted":
                raw = cluster.client.get_or_none(cluster_path(
                    cluster.cluster, "moves", hot_partition))
                if raw is not None:
                    rec = MoveRecord.decode(raw)
                    try:
                        ShardMove.resume(
                            cluster.client, cluster.cluster,
                            hot_partition, admin=cluster.admin,
                            flags=_move_flags()).run()
                        timings["resumes"] += 1
                    except Exception as e:
                        violations.append(
                            f"{tag}: RESUME FAILED from phase "
                            f"{rec.phase}: {e!r}")
            elif want_split:
                from rocksplicator_tpu.cluster.model import SplitRecord

                raw = cluster.client.get_or_none(cluster_path(
                    cluster.cluster, "splits", hot_partition))
                rec = SplitRecord.decode(raw) if raw is not None else None
                if rec is not None and rec.phase != "active":
                    try:
                        ShardSplit.resume(
                            cluster.client, cluster.cluster,
                            hot_partition, admin=cluster.admin,
                            flags=_move_flags()).run()
                        timings["resumes"] += 1
                    except Exception as e:
                        violations.append(
                            f"{tag}: SPLIT RESUME FAILED from phase "
                            f"{rec.phase}: {e!r}")
                elif rec is None:
                    violations.append(
                        f"{tag}: split dispatched but no record left")
        finally:
            fp.clear()
            for w in writers:
                w.stop_collect()
                timings["write_errors"] += w.errors
            reb.stop(timeout=5.0)
        timings["schedule_ms"].append(
            round((time.monotonic() - t0) * 1000.0, 1))

    return run


def _check_rebalance_invariants(cluster: FailoverCluster,
                                acked_by_shard: Dict[int, List],
                                tag: str, violations: List[str],
                                timeout: float = 45.0) -> int:
    """The SEVENTH standing invariant, after EVERY rebalance schedule:
    every leaf partition of the split forest converges (one leader +
    full replica set at equal seqs; the shard map and the data plane
    agree on exactly one unfenced leader each), NO node holds a db
    outside the leaf set (the split-retired parent must be gone
    everywhere), active splits are published in the map's __splits__
    section, every acked write is readable on every current host of
    the child that OWNS its key range, and the heal stays inside the
    controller-pass bound."""
    from rocksplicator_tpu.cluster.shard_split import list_splits
    from rocksplicator_tpu.utils.segment_utils import (
        db_name_to_partition_name, segment_to_db_name)

    passes0 = cluster.controller.passes
    detail: Dict = {}

    def leaf_view():
        leaves = _split_leaves(cluster)
        return leaves, {
            s: (segment_to_db_name(cluster.segment, s),
                db_name_to_partition_name(
                    segment_to_db_name(cluster.segment, s)))
            for s in leaves}

    def healthy():
        from rocksplicator_tpu.storage.errors import StorageError

        leaves, names = leaf_view()
        expected_dbs = {db for db, _p in names.values()}
        splits = list_splits(cluster.client, cluster.cluster)
        try:
            for s in leaves:
                db, partition = names[s]
                hosts = [n for n in cluster.nodes
                         if n.state_of(partition)]
                states = sorted(n.state_of(partition) for n in hosts)
                if states != ["FOLLOWER", "FOLLOWER", "LEADER"]:
                    detail[partition] = cluster.states(partition)
                    return False
                seqs = []
                for n in hosts:
                    app = n.handler.db_manager.get_db(db)
                    if app is None:
                        detail[partition] = (n.name, "db closed")
                        return False
                    seqs.append(app.db.latest_sequence_number_relaxed())
                if len(set(seqs)) != 1:
                    detail[partition] = ("seqs", seqs)
                    return False
                host_names = {n.name for n in hosts}
                for n in cluster.nodes:
                    if n.name not in host_names and \
                            n.handler.db_manager.get_db(db) is not None:
                        detail[partition] = ("garbage", n.name)
                        return False
                dp_leaders = [
                    n.name for n in cluster.nodes
                    if (lambda rdb: rdb is not None
                        and rdb.role is ReplicaRole.LEADER
                        and not rdb.fenced
                        and not rdb.removed)(n.rdb(db))]
                if len(dp_leaders) != 1:
                    detail[partition] = ("lineages", dp_leaders)
                    return False
            # the split-retired parent is gone EVERYWHERE: its lineage
            # was closed to writers by the leader rename at cutover and
            # every replica was renamed into a child — a parent-named
            # db still open anywhere is a stranded pre-split lineage a
            # router retry could read stale data from
            for r in splits:
                if r.phase != "active":
                    continue
                parent_db = segment_to_db_name(cluster.segment,
                                               r.parent_shard)
                if parent_db in expected_dbs:
                    continue  # re-split child reusing a leaf id
                for n in cluster.nodes:
                    if n.handler.db_manager.get_db(parent_db) \
                            is not None:
                        detail["parent"] = (parent_db, n.name)
                        return False
        except StorageError as e:
            detail["transition"] = repr(e)
            return False
        if not cluster.maps:
            detail["map"] = "never published"
            return False
        seg = cluster.maps[-1].get(cluster.segment) or {}
        active = [r for r in splits if r.phase == "active"]
        published = seg.get("__splits__") or {}
        for r in active:
            if str(r.parent_shard) not in published:
                detail["map"] = f"split of {r.parent_shard} unpublished"
                return False
        for s in leaves:
            mark = f"{s:05d}:M"
            n_leaders = sum(
                1 for host, entries in seg.items()
                if host not in ("num_shards", "__splits__")
                for e in entries if e == mark)
            if n_leaders != 1:
                detail["map"] = f"shard {s}: {n_leaders} leaders in map"
                return False
        return True

    def stable_healthy():
        if not healthy():
            return False
        time.sleep(0.35)
        return healthy()

    ok = cluster.wait(stable_healthy, timeout)
    passes = cluster.controller.passes - passes0
    if not ok:
        violations.append(
            f"{tag}: NO HEAL within {timeout}s / {passes} controller "
            f"passes — {detail}")
        # fall through to the acked probe anyway: when the cluster is
        # wedged BECAUSE data went missing (the split_cutover tooth),
        # the loss itself is the diagnosis, not the non-convergence
    elif passes > RESHARD_PASS_BOUND:
        violations.append(
            f"{tag}: healed but took {passes} controller passes "
            f"(bound {RESHARD_PASS_BOUND})")
    # the sharp probe, strict and waitless once converged: every acked
    # key readable on every current host of the child OWNING its range
    # — what the split_cutover tooth (rename without pause/drain) loses
    _leaves, names = leaf_view()
    for shard, ledger in sorted(acked_by_shard.items()):
        for key, val in ledger:
            leaf = _owning_leaf(cluster, shard, key)
            db, partition = names.get(leaf, (None, None))
            if db is None:
                violations.append(
                    f"{tag}: acked key {key!r} resolves to unserved "
                    f"leaf {leaf}")
                return passes
            for n in cluster.nodes:
                if not n.state_of(partition):
                    continue
                if not ok and n.state_of(partition) not in _LEADERLIKE:
                    # unconverged cluster: a follower mid-rebuild is
                    # legitimately incomplete — only the child LEADER's
                    # copy is the can-never-heal truth
                    continue
                app = n.handler.db_manager.get_db(db)
                if app is None or app.db.get(key) != val:
                    violations.append(
                        f"{tag}: ACKED WRITE {key!r} MISSING ON CHILD "
                        f"{db} host {n.name} (lost across the split "
                        f"cutover)")
                    return passes
    return passes


def _rebalance_deck(rng: random.Random, schedules: int,
                    break_guard: Optional[str]) -> List[str]:
    """move, split, faulted-move in order (the smoke's 2 moves + 1
    split); the split_cutover tooth leads with the split it breaks."""
    if break_guard == "split_cutover":
        deck = ["rebalance_split_hot"]
    else:
        deck = []
    core = list(_REBALANCE_KINDS)
    while len(deck) < schedules:
        deck.extend(core)
    return deck[:schedules]


def run_rebalance_chaos(
    root: str,
    schedules: int = 3,
    seed: int = 1,
    break_guard: Optional[str] = None,
    heal_timeout: float = 45.0,
    log=print,
) -> Dict:
    """Autonomous-rebalancer schedules: the harness drives SKEWED load
    at a 4-node / 2-hash-shard cluster and the policy loop must sense
    the sustained hot spot, plan, and dispatch the move — or, past the
    split threshold, the range split — on its own. Seam faults
    ("rebalance.decide/plan/dispatch", "split.cutover",
    move.catchup) kill the loop and its actuators mid-flight; durable
    ledgers + resume must finish every job. After every schedule the
    SEVENTH standing invariant is checked."""
    saved_env = {
        k: os.environ.get(k)
        for k in ("RSTPU_RETRY_SEED", "RSTPU_PULL_RETRY_SEED")
    }
    os.environ["RSTPU_RETRY_SEED"] = str(seed)
    os.environ["RSTPU_PULL_RETRY_SEED"] = str(seed)
    undo = _break_guard(break_guard) if break_guard else None
    violations: List[str] = []
    acked_by_shard: Dict[int, List[Tuple[bytes, bytes]]] = {}
    timings: Dict = {"schedule_ms": [], "dispatched": {}, "resumes": 0,
                     "tick_errors": 0, "write_errors": 0,
                     "passes_used": [],
                     "fast_fail": bool(break_guard)}
    gauge_snapshots: List[Dict] = []
    fp.clear()
    t_setup = time.monotonic()
    cluster = FailoverCluster(root, num_nodes=4, num_shards=2)
    deck: List[str] = []
    try:
        cluster.wait_initial_convergence()
        setup_sec = round(time.monotonic() - t_setup, 1)
        deck = _rebalance_deck(random.Random(seed), schedules,
                               break_guard)
        log(f"  cluster up in {setup_sec}s (4 nodes / 2 hash shards); "
            f"deck: {deck}")
        for si, kind in enumerate(deck):
            rng = random.Random(seed * 1_000_003 + si)
            tag = f"s{si:02d}-{kind}/seed {seed}"
            try:
                _rebalance_schedule(kind)(
                    cluster, rng, acked_by_shard, violations, tag,
                    timings)
            finally:
                fp.clear()
            if violations and break_guard:
                break
            timings["passes_used"].append(
                _check_rebalance_invariants(
                    cluster, acked_by_shard, tag, violations,
                    timeout=heal_timeout))
            gauge_snapshots.append(_gauge_snapshot(tag))
            acked = sum(len(v) for v in acked_by_shard.values())
            log(f"  [{si + 1}/{len(deck)}] {kind}: acked={acked} "
                f"dispatched={timings['dispatched']} "
                f"resumes={timings['resumes']} "
                f"violations={len(violations)}")
            if violations and break_guard:
                break
    finally:
        fp.clear()
        if undo:
            undo()
        cluster.stop()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return {
        "mode": "rebalance",
        "schedules": len(deck),
        "deck": deck,
        "seed": seed,
        "acked": sum(len(v) for v in acked_by_shard.values()),
        "write_errors": timings["write_errors"],
        "violations": violations,
        "dispatched": timings["dispatched"],
        "resumes": timings["resumes"],
        "tick_errors": timings["tick_errors"],
        "schedule_ms": timings["schedule_ms"],
        "passes_used": timings["passes_used"],
        "gauge_snapshots": gauge_snapshots,
        "failpoint_trips": fp.trip_counts(),
        "break_guard": break_guard,
    }


# ---------------------------------------------------------------------------
# the run loop
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# CDC streaming ingest (round 21): the cdc_burst deck + the EIGHTH
# standing invariant
# ---------------------------------------------------------------------------

CDC_TOPIC = "cdc_events"

# the deck rotates one scenario per schedule; kills land at every
# consumer seam mid-batch, plus a multi-kill burst and a leader
# failover mid-consume. Order matters for the tooth: schedule 0 is the
# checkpoint seam, where the cdc_dedup break-guard must be caught.
_CDC_DECK = [
    "seam:kafka.checkpoint",
    "seam:kafka.apply",
    "seam:kafka.fetch",
    "burst",
    "leader_failover",
]


class _CdcApplyTarget:
    """ApplicationDB-shaped shim over a ReplicatedDB: ``.db`` exposes
    the local engine (watermark reads, pacing gauges), ``write_many``
    routes each batch through semi-sync replication — the watermark PUT
    replicates with the records it covers, and fencing surfaces as a
    write error exactly as on the serving stack."""

    def __init__(self, engine: DB, rdb):
        self.db = engine
        self._rdb = rdb

    def write_many(self, batches):
        for b in batches:
            self._rdb.write(b)


def _cdc_deck_msgs(n: int) -> Tuple[List[Tuple[bytes, bytes]], Dict]:
    """Deterministic produce history with overwrites and deletes: the
    expected final state is the FOLD of the log, so a dropped or
    doubled delete would surface even without the applies witness."""
    msgs: List[Tuple[bytes, bytes]] = []
    expect: Dict[bytes, bytes] = {}
    for i in range(n):
        key = b"c%03d" % (i % 120)
        value = b"" if (i % 29 == 7) else b"v%d" % i
        msgs.append((key, value))
        if value:
            expect[key] = value
        else:
            expect.pop(key, None)
    return msgs, expect


def _cdc_produce_bg(kafka, msgs, base_ts: int, pace_sec: float):
    done = threading.Event()

    def run():
        for i, (k, v) in enumerate(msgs):
            kafka.produce(CDC_TOPIC, 0, k, v, timestamp_ms=base_ts + i)
            if pace_sec:
                time.sleep(pace_sec)
        done.set()

    t = threading.Thread(target=run, name="cdc-producer", daemon=True)
    t.start()
    return t, done


def _check_cdc_invariant(tag: str, kafka, engines: List[DB], expect,
                         violations: List[str]) -> None:
    """Invariant 8: applied records == produced prefix, EXACTLY once,
    per partition — on every replica of the serving lineage. The
    watermark names the prefix; the applies counter is the duplicate
    witness (idempotent upserts make state-compare blind to re-applies,
    the counter is not); the fold check catches drops."""
    from rocksplicator_tpu.kafka.checkpoint import (read_applies,
                                                    read_watermark)

    produced = kafka.high_watermark(CDC_TOPIC, 0)
    for i, engine in enumerate(engines):
        wm = read_watermark(engine, CDC_TOPIC, 0)
        off = None if wm is None else wm["offset"]
        if off != produced:
            violations.append(
                f"{tag}: replica {i}: watermark {off} != produced "
                f"{produced} — the applied prefix is not the produced "
                f"prefix")
            continue
        applies = read_applies(engine, CDC_TOPIC, 0)
        if applies != produced:
            violations.append(
                f"{tag}: replica {i}: applies_total {applies} != "
                f"produced {produced} — records were NOT applied "
                f"exactly once (duplicate applies survive state-compare; "
                f"the counter witness does not)")
        for k, v in expect.items():
            got = engine.get(k)
            if got != v:
                violations.append(
                    f"{tag}: replica {i}: fold mismatch at {k!r}: "
                    f"read {got!r}, want {v!r}")
                break


def _run_cdc_schedule(root: str, si: int, rng: random.Random,
                      scenario: str, violations: List[str],
                      counters: Dict, heal_timeout: float) -> None:
    from rocksplicator_tpu.kafka.broker import (MockConsumer,
                                                MockKafkaCluster)
    from rocksplicator_tpu.kafka.ingestion import IngestionWatcher

    kafka = MockKafkaCluster()
    kafka.create_topic(CDC_TOPIC, 1)
    n = rng.randint(150, 300)
    msgs, expect = _cdc_deck_msgs(n)
    counters["produced"] += n
    cluster = ChaosCluster(os.path.join(root, f"cdc{si}"))
    tag = f"cdc schedule {si} [{scenario}]"
    watchers = []

    def start_watcher(node_idx: int, rdb) -> "IngestionWatcher":
        w = IngestionWatcher(
            None, DB_NAME,
            _CdcApplyTarget(cluster.dbs[node_idx], rdb),
            MockConsumer(kafka), CDC_TOPIC, [0], 0)
        w.start()
        watchers.append(w)
        counters["consumer_starts"] += 1
        return w

    def wait(pred, timeout=20.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return True
            time.sleep(0.02)
        return pred()

    try:
        if not cluster.wait_converged(20.0):
            raise RuntimeError(f"{tag}: cluster never converged at start")
        producer, prod_done = _cdc_produce_bg(
            kafka, msgs, base_ts=1_000 + si, pace_sec=0.002)
        engines: List[DB] = list(cluster.dbs)
        if scenario.startswith("seam:"):
            site = scenario[len("seam:"):]
            fp.activate(site, f"fail_nth:{rng.randint(2, 6)}")
            w = start_watcher(0, cluster.leader)
            died = wait(lambda: w.error is not None
                        or (prod_done.is_set() and w.watermark(0) == n))
            fp.deactivate(site)
            if w.error is not None:
                counters["kills"] += 1
            elif not died:
                violations.append(
                    f"{tag}: consumer neither died nor finished")
            w.stop()
            w2 = start_watcher(0, cluster.leader)
            if not wait(lambda: w2.watermark(0) == n):
                violations.append(
                    f"{tag}: resumed consumer stalled at watermark "
                    f"{w2.watermark(0)}/{n} (error {w2.error!r})")
            w2.stop()
        elif scenario == "burst":
            # kill/restart at a random seam, repeatedly, racing the
            # producer — then one clean pass to quiesce
            for _cycle in range(3):
                site = rng.choice(["kafka.fetch", "kafka.apply",
                                   "kafka.checkpoint"])
                fp.activate(site, f"fail_nth:{rng.randint(1, 4)}")
                w = start_watcher(0, cluster.leader)
                if wait(lambda: w.error is not None
                        or (prod_done.is_set()
                            and w.watermark(0) == n), timeout=10.0) \
                        and w.error is not None:
                    counters["kills"] += 1
                fp.deactivate(site)
                w.stop()
            w = start_watcher(0, cluster.leader)
            if not wait(lambda: w.watermark(0) == n):
                violations.append(
                    f"{tag}: post-burst consumer stalled at "
                    f"{w.watermark(0)}/{n} (error {w.error!r})")
            w.stop()
        elif scenario == "leader_failover":
            old_leader = cluster.leader
            w = start_watcher(0, old_leader)
            wait(lambda: w.watermark(0) >= n // 3)
            # the controller's promotion at the data plane: follower 1
            # takes epoch 2; follower 2's next pull (still aimed at the
            # old leader) fences the deposed lineage — the consumer's
            # next replicated write dies loudly
            cluster.hosts[1].remove_db(DB_NAME)
            new_leader = cluster.hosts[1].add_db(
                DB_NAME, StorageDbWrapper(cluster.dbs[1]),
                ReplicaRole.LEADER, replication_mode=1, epoch=2)
            cluster.rdbs[1] = new_leader
            cluster.rdbs[2].adopt_epoch(2)
            if not wait(lambda: old_leader.fenced, timeout=10.0):
                violations.append(f"{tag}: deposed leader never fenced")
            if not wait(lambda: w.error is not None, timeout=15.0):
                violations.append(
                    f"{tag}: consumer survived its leader's deposition")
            counters["kills"] += 1
            w.stop()
            cluster.rdbs[2].reset_upstream(
                ("127.0.0.1", cluster.hosts[1].port))
            prod_done.wait(20.0)
            # resume against the promoted follower: its own replicated
            # watermark names the resume point
            w2 = start_watcher(1, new_leader)
            if not wait(lambda: w2.watermark(0) == n):
                violations.append(
                    f"{tag}: post-failover consumer stalled at "
                    f"{w2.watermark(0)}/{n} (error {w2.error!r})")
            w2.stop()
            engines = [cluster.dbs[1], cluster.dbs[2]]
        else:
            raise ValueError(f"unknown cdc scenario: {scenario}")
        producer.join(20.0)
        # quiesce: the serving lineage reconverges, then invariant 8
        # holds on EVERY replica of it (watermark + counter rode the
        # replicated batches)
        lead = engines[0]
        if not wait(lambda: all(
                e.latest_sequence_number_relaxed()
                == lead.latest_sequence_number_relaxed()
                for e in engines), timeout=heal_timeout):
            violations.append(
                f"{tag}: lineage did not reconverge in {heal_timeout}s")
        _check_cdc_invariant(tag, kafka, engines, expect, violations)
    finally:
        fp.clear()
        for w in watchers:
            try:
                w.stop()
            except Exception:
                pass
        cluster.stop()


def run_cdc_chaos(
    root: str,
    schedules: int = 5,
    seed: int = 1,
    break_guard: Optional[str] = None,
    heal_timeout: float = 15.0,
    log=print,
) -> Dict:
    """The ``cdc_burst`` chaos mode: kill/restart the CDC consumer at
    every seam mid-batch (plus a multi-kill burst and a leader failover
    mid-consume), asserting invariant 8 after every schedule."""
    from rocksplicator_tpu.kafka import ingestion as ingestion_mod

    saved_shape = (ingestion_mod.MAX_DRAIN, ingestion_mod.BATCH_RECORDS,
                   ingestion_mod.POLL_SEC)
    # chaos scale: small drains/batches so every schedule crosses many
    # batch boundaries (a kill always has partial progress to tear)
    ingestion_mod.MAX_DRAIN = 48
    ingestion_mod.BATCH_RECORDS = 16
    ingestion_mod.POLL_SEC = 0.05
    undo = _break_guard(break_guard) if break_guard else None
    violations: List[str] = []
    counters: Dict = {"kills": 0, "consumer_starts": 0, "produced": 0}
    scenarios: List[str] = []
    fp.clear()
    try:
        for si in range(schedules):
            rng = random.Random(seed * 1_000_003 + si)
            scenario = _CDC_DECK[si % len(_CDC_DECK)]
            scenarios.append(scenario)
            _run_cdc_schedule(root, si, rng, scenario, violations,
                              counters, heal_timeout)
            log(f"  [{si + 1}/{schedules}] {scenario} "
                f"kills={counters['kills']} "
                f"starts={counters['consumer_starts']} "
                f"violations={len(violations)}")
            if violations and break_guard:
                break
    finally:
        fp.clear()
        if undo:
            undo()
        (ingestion_mod.MAX_DRAIN, ingestion_mod.BATCH_RECORDS,
         ingestion_mod.POLL_SEC) = saved_shape
    return {
        "schedules": schedules,
        "seed": seed,
        "scenarios": scenarios,
        "produced": counters["produced"],
        "kills": counters["kills"],
        "consumer_starts": counters["consumer_starts"],
        "violations": violations,
        "failpoint_trips": fp.trip_counts(),
        "break_guard": break_guard,
    }


def run_chaos(
    root: str,
    schedules: int = 20,
    seed: int = 1,
    writes: int = 80,
    ingest_every: int = 4,
    remote_every: int = 3,
    break_guard: Optional[str] = None,
    conv_timeout: float = 30.0,
    transport: Optional[str] = None,
    log=print,
) -> Dict:
    saved_env = {
        k: os.environ.get(k)
        for k in ("RSTPU_RETRY_SEED", "RSTPU_PULL_RETRY_SEED",
                  "RSTPU_TRANSPORT")
    }
    os.environ["RSTPU_RETRY_SEED"] = str(seed)
    os.environ["RSTPU_PULL_RETRY_SEED"] = str(seed)
    if transport:
        # the same seeded schedules must hold the same invariants on
        # every byte layer: the policy reroutes the cluster's RPC plane
        # (leader/followers are colocated in-process, so even loopback
        # applies) while the fault sites arm identically
        os.environ["RSTPU_TRANSPORT"] = transport
    undo = _break_guard(break_guard) if break_guard else None
    violations: List[str] = []
    gauge_snapshots: List[Dict] = []
    acked_total = 0
    write_total = 0
    fp.clear()
    cluster = ChaosCluster(root)
    ingest = IngestFixture(root, cluster.hosts[0])
    remote = RemoteCompactionFixture(root) if remote_every else None
    try:
        if not cluster.wait_converged(20.0):
            raise RuntimeError("cluster never converged at start")
        for si in range(schedules):
            rng = random.Random(seed * 1_000_003 + si)
            faults = rng.sample(_fault_menu(rng), k=rng.randint(1, 3))
            tag = f"schedule {si}/seed {seed}"
            for site, spec in faults:
                fp.activate(site, spec)
            # -- workload under fault -------------------------------------
            waiters = []
            n_writes = rng.randint(writes // 2, writes)
            write_errors = 0
            for i in range(n_writes):
                key = b"s%03dk%04d" % (si, i)
                val = b"s%03dv%04d" % (si, i)
                try:
                    waiters.append(
                        (key, val,
                         cluster.leader.write_async(
                             WriteBatch().put(key, val))))
                except Exception:
                    write_errors += 1  # injected fault; write not acked
            # sibling-shard load: smaller but concurrent, so with mux on
            # the session interleaves both shards' backlogs in one
            # response stream under the same armed faults
            waiters2 = []
            n_writes2 = rng.randint(6, 14)
            for i in range(n_writes2):
                key = b"x%03dk%04d" % (si, i)
                val = b"x%03dv%04d" % (si, i)
                try:
                    waiters2.append(
                        (key, val,
                         cluster.leader2.write_async(
                             WriteBatch().put(key, val))))
                except Exception:
                    write_errors += 1
            write_total += n_writes + n_writes2
            acked: List[Tuple[bytes, bytes]] = []
            for key, val, w in waiters:
                try:
                    w.future.result(5.0)
                except Exception:
                    continue
                if w.acked:
                    acked.append((key, val))
            acked2: List[Tuple[bytes, bytes]] = []
            for key, val, w in waiters2:
                try:
                    w.future.result(5.0)
                except Exception:
                    continue
                if w.acked:
                    acked2.append((key, val))
            acked_total += len(acked) + len(acked2)
            # -- heal + verify --------------------------------------------
            for site, _spec in faults:
                fp.deactivate(site)
            if not cluster.wait_converged(conv_timeout):
                lat = [db.latest_sequence_number_relaxed()
                       for db in cluster.dbs]
                violations.append(
                    f"{tag}: no reconvergence {conv_timeout}s after "
                    f"faults cleared (seqs {lat}, faults {faults})")
            for i, db in enumerate(cluster.dbs + cluster.dbs2):
                msg = check_wal_contiguous(db)
                if msg:
                    violations.append(
                        f"{tag}: node {i % 3} "
                        f"({DB_NAME if i < 3 else DB2_NAME}): {msg} "
                        f"(faults {faults})")
            lost = []
            for key, val in acked:
                for i, db in enumerate(cluster.dbs):
                    if db.get(key) != val:
                        lost.append((i, key))
            for key, val in acked2:
                for i, db in enumerate(cluster.dbs2):
                    if db.get(key) != val:
                        lost.append((i, key))
            if lost:
                violations.append(
                    f"{tag}: {len(lost)} acked writes missing after "
                    f"reconvergence, first {lost[0]} (faults {faults})")
            if ingest_every and si % ingest_every == ingest_every - 1:
                ingest.step(rng, violations, tag)
            if remote is not None and si % remote_every == 0:
                # disaggregated compaction tier (round 18): rotating
                # seam/worker-kill/leader-kill scenario + the standing
                # deposed-install probe — runs on si=0 so a broken
                # remote_install guard is caught on the first schedule
                remote.step(rng, violations, tag)
            gauge_snapshots.append(_gauge_snapshot(tag))
            log(f"  [{si + 1}/{schedules}] faults={faults} "
                f"writes={n_writes + n_writes2} "
                f"acked={len(acked) + len(acked2)} "
                f"errors={write_errors} "
                f"violations={len(violations)}")
            if violations and break_guard:
                break  # teeth demonstrated; no need to keep going
    finally:
        fp.clear()
        if undo:
            undo()
        ingest.close()
        if remote is not None:
            remote.close()
        cluster.stop()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return {
        "schedules": schedules,
        "seed": seed,
        "transport": transport or os.environ.get("RSTPU_TRANSPORT", "tcp")
        or "tcp",
        "writes": write_total,
        "acked": acked_total,
        "violations": violations,
        "gauge_snapshots": gauge_snapshots,
        "failpoint_trips": fp.trip_counts(),
        "break_guard": break_guard,
        "remote_outcomes": dict(remote.outcomes) if remote else {},
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--schedules", type=int, default=20)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--writes", type=int, default=80,
                    help="max writes per schedule")
    ap.add_argument("--ingest-every", type=int, default=4)
    ap.add_argument("--remote-every", type=int, default=3,
                    help="run a disaggregated-compaction scenario every "
                         "N schedules (0 disables; data-plane mode "
                         "only): seam faults, worker kill mid-job, "
                         "leader kill mid-job, plus the standing "
                         "deposed-install fence probe")
    ap.add_argument("--failover", action="store_true",
                    help="coordinator-backed control-plane schedules "
                         "(Controller + Spectator + 3 participants): "
                         "leader crash with a full AckWindow, session "
                         "expiry, coordinator kill/WAL torn — holding "
                         "the fourth standing invariant")
    ap.add_argument("--reshard", action="store_true",
                    help="live-shard-move schedules (4 nodes / 3 "
                         "replicas): the move coordinator killed at "
                         "every step-machine seam, source/target kills "
                         "mid-move, torn coordinator WAL during the "
                         "flip, session expiry mid-catch-up — holding "
                         "the SIXTH standing invariant (exactly one "
                         "serving lineage, zero acked-write loss across "
                         "the move, bounded convergence)")
    ap.add_argument("--rebalance", action="store_true",
                    help="autonomous-rebalancer schedules (4 nodes / 2 "
                         "hash shards): skewed load only — the policy "
                         "loop itself must sense the sustained hot "
                         "shard and dispatch the move (or, past the "
                         "split threshold, the RANGE SPLIT), riding "
                         "decide/plan/dispatch seam faults and a "
                         "splitter killed AT the fenced cutover — "
                         "holding the SEVENTH standing invariant")
    ap.add_argument("--cdc", action="store_true",
                    help="CDC streaming-ingest schedules (the cdc_burst "
                         "deck): kill/restart the exactly-once consumer "
                         "at every seam mid-batch, a multi-kill burst, "
                         "and a leader failover mid-consume — holding "
                         "the EIGHTH standing invariant (applied "
                         "records == produced prefix, exactly once, "
                         "per partition, on every replica of the "
                         "serving lineage)")
    ap.add_argument("--transport", choices=["tcp", "uds", "loopback"],
                    help="run the cluster's RPC plane on this byte layer "
                         "(RSTPU_TRANSPORT for the run; default: ambient "
                         "policy, i.e. tcp; data-plane mode only)")
    ap.add_argument("--break-guard",
                    choices=["wal_hole", "meta_first", "fencing",
                             "move_flip", "remote_install",
                             "split_cutover", "cdc_dedup",
                             "mux_misroute"])
    ap.add_argument("--expect-violation", action="store_true",
                    help="exit 0 iff a violation WAS caught")
    ap.add_argument("--conv-timeout", type=float, default=30.0)
    ap.add_argument("--out", help="write the result JSON here")
    args = ap.parse_args(argv)
    if args.break_guard == "fencing" and not args.failover:
        ap.error("--break-guard fencing requires --failover")
    if args.break_guard == "move_flip" and not args.reshard:
        ap.error("--break-guard move_flip requires --reshard")
    if args.break_guard == "split_cutover" and not args.rebalance:
        ap.error("--break-guard split_cutover requires --rebalance")
    if args.break_guard == "cdc_dedup" and not args.cdc:
        ap.error("--break-guard cdc_dedup requires --cdc")
    if args.break_guard == "mux_misroute" and (
            args.failover or args.reshard or args.rebalance or args.cdc):
        ap.error("--break-guard mux_misroute is data-plane only "
                 "(drop --failover/--reshard/--rebalance/--cdc)")
    if args.break_guard == "remote_install":
        if args.failover or args.reshard:
            ap.error("--break-guard remote_install is data-plane only "
                     "(drop --failover/--reshard)")
        if not args.remote_every:
            ap.error("--break-guard remote_install requires "
                     "--remote-every > 0")
    if sum(map(bool, (args.failover, args.reshard, args.rebalance,
                      args.cdc))) > 1:
        ap.error("--failover / --reshard / --rebalance / --cdc are "
                 "mutually exclusive")

    root = tempfile.mkdtemp(prefix="rstpu-chaos-")
    t0 = time.monotonic()
    try:
        if args.cdc:
            result = run_cdc_chaos(
                root, schedules=args.schedules, seed=args.seed,
                break_guard=args.break_guard,
            )
        elif args.rebalance:
            result = run_rebalance_chaos(
                root, schedules=args.schedules, seed=args.seed,
                break_guard=args.break_guard,
            )
        elif args.reshard:
            result = run_reshard_chaos(
                root, schedules=args.schedules, seed=args.seed,
                break_guard=args.break_guard,
            )
        elif args.failover:
            result = run_failover_chaos(
                root, schedules=args.schedules, seed=args.seed,
                break_guard=args.break_guard,
            )
        else:
            result = run_chaos(
                root, schedules=args.schedules, seed=args.seed,
                writes=args.writes, ingest_every=args.ingest_every,
                remote_every=args.remote_every,
                break_guard=args.break_guard,
                conv_timeout=args.conv_timeout,
                transport=args.transport,
            )
    finally:
        shutil.rmtree(root, ignore_errors=True)
    result["elapsed_sec"] = round(time.monotonic() - t0, 1)
    if args.cdc:
        print(f"chaos[cdc]: {result['schedules']} schedules "
              f"({', '.join(sorted(set(result['scenarios'])))}), "
              f"{result['produced']} records produced, "
              f"{result['kills']} consumer kills / "
              f"{result['consumer_starts']} starts, "
              f"{result['elapsed_sec']}s")
    elif args.rebalance:
        print(f"chaos[rebalance]: {result['schedules']} schedules, "
              f"{result['acked']} acked writes through policy-driven "
              f"placement ({result['write_errors']} refused), "
              f"{result['elapsed_sec']}s")
        print(f"chaos[rebalance]: dispatched {result['dispatched']}, "
              f"{result['resumes']} resumed after kills, "
              f"{result['tick_errors']} seam-faulted ticks, controller "
              f"passes {result['passes_used']}")
    elif args.reshard:
        print(f"chaos[reshard]: {result['schedules']} schedules, "
              f"{result['acked']} acked writes through live moves "
              f"({result['write_errors']} refused), "
              f"{result['elapsed_sec']}s")
        print(f"chaos[reshard]: move outcomes "
              f"{result['move_outcomes']}, move median "
              f"{result['move_ms_median']} ms, controller passes "
              f"{result['passes_used']}")
        print(f"chaos[reshard]: reads {result['reads_served']} served / "
              f"{result['reads_checked']} checked "
              f"({result['read_bounces']} bounces)")
    elif args.failover:
        print(f"chaos[failover]: {result['schedules']} schedules, "
              f"{result['acked']} strict-ledger acks "
              f"(+{result['window_acked']} window), "
              f"{result['elapsed_sec']}s")
        print(f"chaos[failover]: fault→one-leader median "
              f"{result['failover_ms_median']} ms, fault→first-ack "
              f"median {result['first_ack_ms_median']} ms, "
              f"controller passes {result['passes_used']}")
        print(f"chaos[failover]: reads {result['reads_served']} served / "
              f"{result['reads_checked']} checked "
              f"({result['read_bounces']} bounces) — zero staleness-"
              f"bound or deposed-lineage violations required")
    else:
        print(f"chaos: {result['schedules']} schedules "
              f"[{result['transport']}], "
              f"{result['writes']} writes ({result['acked']} acked), "
              f"{result['elapsed_sec']}s")
        if result.get("remote_outcomes"):
            print(f"chaos: remote-compaction outcomes "
                  f"{result['remote_outcomes']}")
    print(f"chaos: failpoint trips: {result['failpoint_trips']}")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
    if result["violations"]:
        for v in result["violations"]:
            print(f"VIOLATION: {v}")
        print(f"REPRO: python -m tools.chaos_soak "
              f"--schedules {args.schedules} --seed {args.seed}"
              + (" --failover" if args.failover else "")
              + (" --reshard" if args.reshard else "")
              + (" --rebalance" if args.rebalance else "")
              + (" --cdc" if args.cdc else "")
              + (f" --transport {args.transport}"
                 if args.transport else "")
              + (f" --break-guard {args.break_guard}"
                 if args.break_guard else ""))
        return 0 if args.expect_violation else 1
    print("chaos: all invariants held"
          + ((" (CDC exactly-once: applied records == produced prefix "
              "per partition on every serving replica — watermark, "
              "applies-counter witness, and log-fold all agree)"
              if args.cdc else
              " (policy-initiated placement: one unfenced leader per "
              "CHILD, zero acked loss resolved per owning range, "
              "parent retired everywhere, bounded convergence)"
              if args.rebalance else
              " (exactly one serving lineage per shard, zero acked "
              "loss across the move, bounded convergence, no stranded "
              "replicas)" if args.reshard else
              " (exactly-one-leader, zero acked loss across handoff, "
              "bounded shard-map convergence, bounded-staleness + "
              "lineage reads)" if args.failover else
              " (hole-free WAL prefix, zero acked loss, ingest "
              "atomicity)")
             if not args.break_guard else ""))
    if args.expect_violation:
        print("ERROR: --expect-violation but the broken guard was "
              "NOT caught — the harness has lost its teeth")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
