#!/usr/bin/env python
"""Fused-Pallas full-shape evidence: multichip scaling series.

ROADMAP's open item asks for ``pallas_fused`` compile+execute evidence
beyond the bounded dryrun shape. This tool runs the SAME 8-device
sharded compaction step as ``__graft_entry__.dryrun_multichip`` (2D
shard×block mesh, all_gather + psum collectives, full production
pipeline: merge-resolve + bloom + planar encode/checksums) over a
scaling series of entries-per-block, recording per shape:

- ``trace_s`` / ``compile_s`` — AOT ``jit.lower()`` / ``.compile()``
  wall times (the compile-time story the ROADMAP item asks for);
- ``execute_s`` — one post-compile dispatch, blocked to completion;
- ``merged_entries`` + an output content hash (cross-shape sanity: the
  pipeline really ran, outputs are deterministic).

On this image the mesh is 8 virtual CPU devices and Pallas runs in
interpret mode, so EXECUTE times scale badly by design — the artifact's
claim is "the fused kernel compiles and runs correctly at these shapes
under the collectives", with compile times as the hardware-relevant
signal (XLA:TPU compile cost tracks program size, not interpret-mode
emulation).

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/multichip_scaling.py --entries 2048,8192,32768 \
        --out MULTICHIP_r02.json
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def run_shape(n_devices: int, backend: str, entries: int) -> dict:
    import jax
    import numpy as np

    from rocksplicator_tpu.models import CompactionModel
    from rocksplicator_tpu.parallel.mesh import (
        make_mesh,
        make_sharded_inputs,
        shard_inputs_on_mesh,
        sharded_compaction_step,
    )

    mesh = make_mesh(n_devices)
    model = CompactionModel(
        capacity=entries, emit_planar=True, sort_backend=backend)
    step = sharded_compaction_step(mesh, model)
    arrays = make_sharded_inputs(
        mesh, shards_per_device=2, entries_per_block=entries, model=model)
    arrays = shard_inputs_on_mesh(mesh, arrays)
    args = (
        arrays["key_words_be"], arrays["key_len"],
        arrays["seq_hi"], arrays["seq_lo"], arrays["vtype"],
        arrays["val_words"], arrays["val_len"], arrays["valid"],
    )
    t0 = time.perf_counter()
    lowered = step.lower(*args)
    t1 = time.perf_counter()
    compiled = lowered.compile()
    t2 = time.perf_counter()
    final, bloom, counts, global_count, needs_fallback = compiled(*args)
    jax.block_until_ready(global_count)
    t3 = time.perf_counter()

    counts_np = np.asarray(counts).reshape(-1)
    gc = int(np.asarray(global_count).reshape(-1)[0])
    assert gc > 0 and gc == int(counts_np.sum()), (gc, counts_np)
    assert int(np.asarray(needs_fallback).reshape(-1)[0]) == 0
    h = hashlib.sha256()
    fin = {k: np.asarray(v) for k, v in final.items()}
    fin = {k: (v[:, 0] if v.ndim > 1 and v.shape[1] == 1 else v)
           for k, v in fin.items()}
    for s in range(counts_np.shape[0]):
        c = int(counts_np[s])
        for name in ("key_words_be", "key_len", "seq_hi", "seq_lo",
                     "vtype", "val_words", "val_len"):
            h.update(np.ascontiguousarray(fin[name][s][:c]).tobytes())
    row = {
        "backend": backend,
        "entries_per_block": entries,
        "devices": n_devices,
        "mesh": dict(mesh.shape),
        "shards": int(counts_np.shape[0]),
        "input_entries": int(counts_np.shape[0]) * entries,
        "merged_entries": gc,
        "trace_s": round(t1 - t0, 3),
        "compile_s": round(t2 - t1, 3),
        "execute_s": round(t3 - t2, 3),
        "output_sha256": h.hexdigest()[:16],
    }
    log(f"  {backend}@{entries}: trace {row['trace_s']}s, "
        f"compile {row['compile_s']}s, execute {row['execute_s']}s, "
        f"merged {gc}")
    return row


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--entries", default="2048,8192,32768")
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--backends", default="pallas_fused")
    ap.add_argument("--out", default="MULTICHIP_r02.json")
    args = ap.parse_args(argv)

    # force-CPU handling matches __graft_entry__ (the image sitecustomize
    # registers a TPU tunnel that overrides JAX_PLATFORMS)
    import __graft_entry__ as graft

    graft._honor_platform_env()
    import jax

    shapes = [int(s) for s in args.entries.split(",") if s.strip()]
    backends = [b.strip() for b in args.backends.split(",") if b.strip()]
    result = {
        "series": "pallas_fused_scaling",
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "jax": jax.__version__,
        "platform": jax.devices()[0].platform,
        "interpret_mode": jax.devices()[0].platform != "tpu",
        "rows": [],
    }
    for backend in backends:
        for entries in shapes:
            log(f"multichip_scaling: {backend} @ {entries} entries/block")
            result["rows"].append(
                run_shape(args.devices, backend, entries))
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
        f.write("\n")
    print(json.dumps({
        "compile_s": {
            f"{r['backend']}@{r['entries_per_block']}": r["compile_s"]
            for r in result["rows"]},
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
