#!/usr/bin/env python
"""Stateless compaction worker CLI — the serve-forever shell around
rocksplicator_tpu.compaction_remote.worker.CompactionWorker.

    python -m tools.compaction_worker --coord host:port \
        [--workdir DIR] [--worker-id ID] [--backend cpu|tpu] \
        [--once] [--poll-interval S]

The worker owns no shard state: point any number of these at the
cluster coordinator and they drain the compaction job ledger. Kill one
mid-job and the leader reaps its claim on heartbeat expiry — the job
republishes or falls back to the leader's local merge. Environment:
RSTPU_COMPACT_COORD supplies --coord, RSTPU_COMPACT_WORKER_BACKEND
supplies --backend, RSTPU_COMPACT_MEM_BUDGET bounds the streaming
merge exactly as it does in-engine.
"""

import argparse
import logging
import signal
import sys
import tempfile
import threading


def main(argv=None) -> int:
    from rocksplicator_tpu.cluster.coordinator import CoordinatorClient
    from rocksplicator_tpu.compaction_remote.dispatch import \
        coord_endpoint_from_env
    from rocksplicator_tpu.compaction_remote.worker import CompactionWorker

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--coord", default=None,
                    help="coordinator endpoint host:port "
                         "(default: $RSTPU_COMPACT_COORD)")
    ap.add_argument("--workdir", default=None,
                    help="scratch dir for fetched inputs / merged outputs")
    ap.add_argument("--worker-id", default=None)
    ap.add_argument("--backend", default=None, choices=["cpu", "tpu"],
                    help="merge backend (default: "
                         "$RSTPU_COMPACT_WORKER_BACKEND or cpu)")
    ap.add_argument("--once", action="store_true",
                    help="process at most one job, then exit")
    ap.add_argument("--poll-interval", type=float, default=0.5)
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")

    if args.coord:
        host, _, port_s = args.coord.rpartition(":")
        endpoint = (host, int(port_s))
    else:
        endpoint = coord_endpoint_from_env()
    if endpoint is None:
        ap.error("--coord host:port (or RSTPU_COMPACT_COORD) required")

    workdir = args.workdir or tempfile.mkdtemp(prefix="rstpu-compact-")
    coord = CoordinatorClient(endpoint[0], endpoint[1])
    backend = None
    if args.backend:
        from rocksplicator_tpu.compaction_remote.worker import _build_backend

        backend = _build_backend(args.backend)
    worker = CompactionWorker(
        coord, workdir, worker_id=args.worker_id, backend=backend,
        poll_interval=args.poll_interval)
    logging.info("compaction worker %s serving (coord %s:%d, workdir %s)",
                 worker.worker_id, endpoint[0], endpoint[1], workdir)

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    try:
        if args.once:
            worker.run_once()
        else:
            worker.serve_forever(stop)
    finally:
        coord.close()
        logging.info("worker %s done: %d jobs, %d failed",
                     worker.worker_id, worker.jobs_done, worker.jobs_failed)
    return 0


if __name__ == "__main__":
    sys.exit(main())
