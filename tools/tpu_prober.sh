#!/bin/bash
# Opportunistic TPU-grant capture loop (round 5).
#
# The axon pool refused every grant in round 4; the one lever is to keep
# asking all session and convert a grant into measurements the moment it
# lands. Each cycle IS the measurement attempt: profile_device both
# probes the device and, on success, produces the lax/pallas/pallas_fused
# stage timings round 4 was missing; a success immediately triggers a
# full bench.py so a complete real-chip headline JSON is persisted even
# if the grant is gone by the driver's end-of-round run.
#
# Discipline (memory: tpu-tunnel-discipline): TERM-based timeouts only —
# never SIGKILL a process that may hold a tunnel grant.
set -u
cd /root/repo
RES=benchmarks/results
LOG=$RES/prober_r05.log
mkdir -p "$RES"
PROBE_TIMEOUT=${PROBE_TIMEOUT:-2400}   # round-4 failures took ~25 min
BENCH_TIMEOUT=${BENCH_TIMEOUT:-3600}
SLEEP_FAIL=${SLEEP_FAIL:-180}
SLEEP_OK=${SLEEP_OK:-1800}

note() { echo "[prober $(date -u +%H:%M:%S)] $*" >> "$LOG"; }

cycle=0
note "prober start pid=$$"
while true; do
  cycle=$((cycle + 1))
  ts=$(date -u +%Y%m%dT%H%M%S)
  note "cycle $cycle: profile_device attempt"
  # write to .tmp and rename on success: an in-flight/failed attempt
  # must never leave a partial or empty .json in results/
  if RSTPU_REQUIRE_ACCEL=1 timeout --signal=TERM "$PROBE_TIMEOUT" \
      python -m benchmarks.profile_device --set pallas \
      > "$RES/.profile_r05_$ts.tmp" 2>> "$LOG" \
      && [ -s "$RES/.profile_r05_$ts.tmp" ]; then
    mv "$RES/.profile_r05_$ts.tmp" "$RES/profile_r05_$ts.json"
    note "cycle $cycle: GRANT — profile saved profile_r05_$ts.json; running bench"
    touch "$RES/GRANT_SEEN"
    if timeout --signal=TERM "$BENCH_TIMEOUT" \
        python bench.py > "$RES/.bench_r05_$ts.tmp" 2>> "$LOG"; then
      mv "$RES/.bench_r05_$ts.tmp" "$RES/bench_r05_$ts.json"
      note "cycle $cycle: bench saved bench_r05_$ts.json"
    else
      note "cycle $cycle: bench rc=$? (partial kept as .tmp)"
    fi
    sleep "$SLEEP_OK"
  else
    rc=$?
    rm -f "$RES/.profile_r05_$ts.tmp"
    note "cycle $cycle: probe failed rc=$rc; sleeping $SLEEP_FAIL"
    sleep "$SLEEP_FAIL"
  fi
done
