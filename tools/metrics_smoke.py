#!/usr/bin/env python
"""Metrics-plane smoke (round 14, CI `make metrics-smoke`): boot one
replica, exercise the engine + read/write RPC paths, then validate the
whole observability plane end to end:

- ``/metrics`` (StatusServer) parses as Prometheus text exposition and
  contains EVERY registered gauge family (engine level/amp/debt gauges,
  replication lag + ack-window occupancy, block-cache hit rate);
- ``/stats.json`` parses and round-trips the exact histogram states;
- the ``stats`` RPC + spectator aggregation path produces a
  ``/cluster_stats`` document with per-shard rates, max lag, and fleet
  per-op-class percentiles from the exact log-bucket histogram merge.

Runs in-process in a few seconds; any missing family, unparseable line,
or empty aggregate exits nonzero. Also exercised by tier-1
(tests/test_metrics_plane.py) so a regression fails fast.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import urllib.request
from typing import Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the gauge families every replica must export (base dotted names)
REQUIRED_GAUGE_FAMILIES = [
    "storage.level_files",
    "storage.level_bytes",
    "storage.compaction_debt_bytes",
    "storage.memtable_bytes",
    "storage.wal_backlog_bytes",
    "storage.unflushed_seqs",
    "storage.read_amp",
    "storage.write_amp",
    "storage.block_cache.hit_rate",
    "replicator.applied_seq_lag",
    "replicator.ack_window_depth",
]


def _http_get(port: int, path: str) -> str:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as resp:
        return resp.read().decode("utf-8")


def run_smoke(shards: int = 2, keys: int = 200, log=print) -> Dict:
    from rocksplicator_tpu.cluster.stats_aggregator import \
        ClusterStatsAggregator
    from rocksplicator_tpu.replication import (ReplicaRole, Replicator,
                                               StorageDbWrapper)
    from rocksplicator_tpu.rpc.ioloop import IoLoop
    from rocksplicator_tpu.storage.engine import DB, DBOptions
    from rocksplicator_tpu.storage.records import WriteBatch
    from rocksplicator_tpu.utils.segment_utils import segment_to_db_name
    from rocksplicator_tpu.utils.stats import (Stats, _prom_name,
                                               parse_prometheus_text)
    from rocksplicator_tpu.utils.status_server import StatusServer

    failures: List[str] = []
    root = tempfile.mkdtemp(prefix="rstpu-metrics-smoke-")
    replicator = Replicator(port=0)
    status = StatusServer(port=0)
    status.start()
    dbs = []
    ioloop = IoLoop.default()
    try:
        # one replica, `shards` dbs: writes drive flush, reads drive the
        # read-amp accounting AND the reads.latency_ms histograms (via
        # the real read RPC, so the fleet merge has op classes to show)
        for s in range(shards):
            name = segment_to_db_name("msk", s)
            db = DB(os.path.join(root, name),
                    DBOptions(memtable_bytes=8 * 1024))
            dbs.append(db)
            replicator.add_db(name, StorageDbWrapper(db),
                              ReplicaRole.LEADER, replication_mode=0)
        for s in range(shards):
            name = segment_to_db_name("msk", s)
            for i in range(keys):
                replicator.write(
                    name, WriteBatch().put(b"k%05d" % i, b"v" * 64))
            dbs[s].flush()

        async def read_some():
            for s in range(shards):
                for i in range(0, keys, 7):
                    await replicator._pool.call(
                        "127.0.0.1", replicator.port, "read",
                        {"db_name": segment_to_db_name("msk", s),
                         "op": "get", "keys": [b"k%05d" % i]},
                        timeout=5.0)

        ioloop.run_sync(read_some(), timeout=60)

        # -- /metrics: parseable + every family present ----------------
        metrics_text = _http_get(status.port, "/metrics")
        families = parse_prometheus_text(metrics_text)
        for base in REQUIRED_GAUGE_FAMILIES:
            if _prom_name(base) not in families:
                failures.append(f"/metrics missing gauge family {base!r} "
                                f"({_prom_name(base)})")
        for counter in ("replicator.shard_writes", "replicator.shard_reads"):
            if _prom_name(counter) + "_total" not in families:
                failures.append(f"/metrics missing counter family "
                                f"{counter!r}")
        hist = _prom_name("reads.latency_ms")
        if f"{hist}_bucket" not in families or f"{hist}_count" not in families:
            failures.append("/metrics missing reads.latency_ms histogram "
                            "lines")
        log(f"  /metrics: {len(metrics_text.splitlines())} lines, "
            f"{len(families)} families, all required present="
            f"{not failures}")

        # -- /stats.json parses ----------------------------------------
        state = json.loads(_http_get(status.port, "/stats.json"))
        if not state.get("gauges"):
            failures.append("/stats.json has no gauges")

        # -- spectator aggregation -> /cluster_stats -------------------
        agg = ClusterStatsAggregator(pool=replicator._pool, ioloop=ioloop)
        cluster_stats = agg.scrape_and_aggregate(
            [("127.0.0.1", replicator.port)])
        status.register_endpoint(
            "/cluster_stats", lambda: json.dumps(cluster_stats, indent=1))
        served = json.loads(_http_get(status.port, "/cluster_stats"))
        if served.get("replicas_scraped") != 1:
            failures.append("cluster_stats scraped != 1 replica")
        per_shard = served.get("per_shard") or {}
        if len(per_shard) != shards:
            failures.append(
                f"cluster_stats per_shard has {len(per_shard)} shards, "
                f"want {shards}")
        for name, rec in per_shard.items():
            if rec.get("writes_total", 0) <= 0:
                failures.append(f"shard {name}: no writes recorded")
        fleet = (served.get("fleet_latency_ms") or {}).get(
            "reads.latency_ms") or {}
        if "get" not in fleet:
            failures.append("fleet_latency_ms missing the get op class")
        log(f"  /cluster_stats: {len(per_shard)} shards, "
            f"fleet get p99={fleet.get('get', {}).get('p99_ms')}ms")
        return {
            "failures": failures,
            "metrics_families": len(families),
            "cluster_stats": served,
        }
    finally:
        status.stop()
        replicator.stop()
        for db in dbs:
            db.close()
        import shutil

        shutil.rmtree(root, ignore_errors=True)


def main(argv=None) -> int:
    report = run_smoke()
    if report["failures"]:
        for msg in report["failures"]:
            print(f"metrics-smoke: FAILURE: {msg}", file=sys.stderr)
        return 1
    print(f"metrics-smoke: OK ({report['metrics_families']} metric "
          f"families, {len(report['cluster_stats']['per_shard'])} shards "
          f"aggregated)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
