"""TCP latency proxy — DCN-shaped links for single-host benches.

The replication/cluster benches run leader and followers on loopback
(~50 us RTT); real deployments replicate across hosts (DCN, ~0.5-2 ms
RTT). This proxy forwards a TCP port with a configurable one-way delay
so the same single-host harnesses produce cross-host-shaped evidence
(nothing in the framework assumes localhost — this measures it).

    python -m tools.latency_proxy --listen 19400 --target 127.0.0.1:9400 \
        --delay-ms 1.0

Each direction delays every segment by --delay-ms before forwarding
(i.e. RTT ≈ 2 × delay). Asyncio, one process, many connections.
"""

from __future__ import annotations

import argparse
import asyncio
import sys


async def _pump(reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                delay: float) -> None:
    """Latency WITHOUT a bandwidth cap: reads never stall on the delay.
    Each chunk is timestamped into a queue; a drainer task sleeps only
    until each chunk's delivery time (an inline sleep-per-chunk would
    cap throughput at chunk_size/delay, conflating latency with an
    artificial bandwidth ceiling real DCN links don't have)."""
    loop = asyncio.get_running_loop()
    q: asyncio.Queue = asyncio.Queue()

    async def drain():
        try:
            while True:
                item = await q.get()
                if item is None:
                    break
                deliver_at, data = item
                now = loop.time()
                if deliver_at > now:
                    await asyncio.sleep(deliver_at - now)
                writer.write(data)
                await writer.drain()
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    drainer = asyncio.ensure_future(drain())
    try:
        while True:
            data = await reader.read(64 * 1024)
            if not data:
                break
            await q.put((loop.time() + delay, data))
    except (ConnectionError, asyncio.IncompleteReadError, OSError):
        pass
    finally:
        await q.put(None)
        await drainer


async def serve(listen_port: int, target_host: str, target_port: int,
                delay_ms: float, ready_event=None) -> None:
    delay = delay_ms / 1000.0

    async def on_conn(creader, cwriter):
        try:
            treader, twriter = await asyncio.open_connection(
                target_host, target_port)
        except OSError:
            cwriter.close()
            return
        await asyncio.gather(
            _pump(creader, twriter, delay),
            _pump(treader, cwriter, delay),
        )

    server = await asyncio.start_server(on_conn, "127.0.0.1", listen_port)
    if ready_event is not None:
        ready_event.set()
    print(f"READY proxy :{listen_port} -> {target_host}:{target_port} "
          f"one-way {delay_ms} ms", flush=True)
    async with server:
        await server.serve_forever()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--listen", type=int, required=True)
    ap.add_argument("--target", required=True, help="host:port")
    ap.add_argument("--delay-ms", type=float, default=1.0)
    args = ap.parse_args()
    host, _, port = args.target.partition(":")
    try:
        asyncio.run(serve(args.listen, host, int(port), args.delay_ms))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
