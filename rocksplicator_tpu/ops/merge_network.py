"""Bitonic merge network for PRE-SORTED compaction runs.

Real compaction inputs are already sorted: SSTs are sorted by construction
and memtable dumps iterate in key order (the reference's compaction heap
exploits exactly this — rocksdb merges sorted runs, it never re-sorts,
SURVEY §3.3). The full-sort kernel (compaction_kernel.py) pays XLA's
generic bitonic sort anyway: O(log² M) compare-exchange stages over the
concatenated batch. This module replaces phase 1 with a **bitonic merge
tree** over the k sorted runs:

- level j merges pairs of length-L·2^(j-1) sorted sequences by
  concatenating one with the reversal of the other (ascending ++
  descending == bitonic) and running the half-cleaner cascade:
  log2(L·2^j) compare-exchange stages of pure reshape/slice/min-max;
- total stages = log k · log L + log k (log k + 1) / 2 versus
  log M (log M + 1) / 2 for the full sort — ~3× fewer at k=8, L=2^14
  (57 vs 153) and the advantage grows with L;
- every stage is elementwise selects over lane arrays — ZERO gathers,
  zero scatters, same design rule the round-2 kernel rewrite established
  (PERF.md: a single 1-D gather costs ~16 ms at 131k rows on v5e).

The composite comparator matches compaction_kernel._sort_merge_order
exactly: (invalid-last, key words BE asc, [key_len], [~seq_hi], ~seq_lo).
Runs must each be sorted ascending by that composite (key asc, seq desc,
valid prefix) — callers verify host-side (cheap vectorized check) and
fall back to the full-sort kernel otherwise.

Resolution/compaction phases are shared with the full-sort kernel via
compaction_kernel.resolve_sorted_lanes, so outputs are bit-identical for
any input where the composite order is total (distinct (key, seq) pairs —
guaranteed by the engine's unique-seq invariant).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Sequence

import jax
import jax.numpy as jnp

from .compaction_kernel import (MergeKind, composite_key_lanes,
                                resolve_sorted_lanes, split_composite_lanes)
from .kv_format import KEY_WORDS


def _lex_lt(a: Sequence[jnp.ndarray], b: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """Lexicographic a < b over parallel u32 lane lists."""
    lt = jnp.zeros(a[0].shape, dtype=bool)
    eq = jnp.ones(a[0].shape, dtype=bool)
    for aw, bw in zip(a, b):
        lt = lt | (eq & (aw < bw))
        eq = eq & (aw == bw)
    return lt


def _half_cleaner_cascade(
    lanes: List[jnp.ndarray], num_keys: int
) -> List[jnp.ndarray]:
    """Sort a bitonic sequence along the last axis: compare-exchange at
    strides M/2, M/4, .., 1. Each stage is reshape + elementwise select —
    no gathers. ``lanes[:num_keys]`` form the comparator; the rest ride."""
    m = lanes[0].shape[-1]
    step = m // 2
    while step >= 1:
        shp = lanes[0].shape
        lead = shp[:-1]
        r = [l.reshape(lead + (m // (2 * step), 2, step)) for l in lanes]
        a = [x[..., 0, :] for x in r]
        b = [x[..., 1, :] for x in r]
        swap = _lex_lt(b[:num_keys], a[:num_keys])
        lanes = [
            jnp.stack(
                [jnp.where(swap, y, x), jnp.where(swap, x, y)], axis=-2
            ).reshape(shp)
            for x, y in zip(a, b)
        ]
        step //= 2
    return lanes


def merge_sorted_lanes(
    lanes: List[jnp.ndarray], num_keys: int
) -> List[jnp.ndarray]:
    """Merge runs stacked on axis -2: each (.., R, L) lane holds R runs
    individually sorted ascending along the last axis by the composite
    key ``lanes[:num_keys]``. R and L must be powers of two (callers pad
    with invalid rows, which sort last via the leading invalid lane).
    Returns flat (.., R*L) lanes in fully merged order."""
    r, m = lanes[0].shape[-2], lanes[0].shape[-1]
    # static-shape precondition: the half-cleaner strides m/2, m/4, .., 1
    # only form a valid bitonic network for power-of-two lengths — a
    # non-pow2 shape would SILENTLY produce mis-merged order
    if r & (r - 1) or (m and m & (m - 1)):
        raise ValueError(
            f"merge network needs power-of-two runs/length, got ({r}, {m})")
    while r > 1:
        evens = [l[..., 0::2, :] for l in lanes]
        odds = [jnp.flip(l[..., 1::2, :], axis=-1) for l in lanes]
        lanes = [
            jnp.concatenate([e, o], axis=-1) for e, o in zip(evens, odds)
        ]
        lanes = _half_cleaner_cascade(lanes, num_keys)
        r //= 2
    return [l.reshape(l.shape[:-2] + (-1,)) for l in lanes]


@functools.partial(
    jax.jit,
    static_argnames=("merge_kind", "drop_tombstones", "uniform_klen",
                     "seq32", "key_words"),
)
def merge_resolve_runs_kernel(
    key_words_be: jnp.ndarray,  # (R, L, 6) u32
    key_len: jnp.ndarray,       # (R, L) u32
    seq_hi: jnp.ndarray,        # (R, L) u32
    seq_lo: jnp.ndarray,        # (R, L) u32
    vtype: jnp.ndarray,         # (R, L) u32
    val_words: jnp.ndarray,     # (R, L, W) u32
    val_len: jnp.ndarray,       # (R, L) u32
    valid: jnp.ndarray,         # (R, L) bool — valid-prefix per run
    *,
    merge_kind: MergeKind = MergeKind.UINT64_ADD,
    drop_tombstones: bool = True,
    uniform_klen: bool = False,
    seq32: bool = False,
    key_words: int = KEY_WORDS,
) -> Dict[str, jnp.ndarray]:
    """merge_resolve_kernel for R PRE-SORTED runs of L entries each.

    Same outputs (capacity R*L); phase 1's full sort is replaced by the
    bitonic merge tree. Each run must already be sorted by (key asc,
    seq desc) with its valid rows a prefix; R and L powers of two.
    """
    n_val_words = val_words.shape[2]
    klen_const = jnp.max(jnp.where(valid, key_len, jnp.uint32(0)))

    invalid_key = jnp.where(valid, jnp.uint32(0), jnp.uint32(1))
    keys = composite_key_lanes(
        invalid_key, (key_words_be[:, :, w] for w in range(key_words)),
        key_len, seq_hi, seq_lo, uniform_klen=uniform_klen, seq32=seq32)
    num_keys = len(keys)
    payload = [vtype, val_len] + [
        val_words[:, :, w] for w in range(n_val_words)
    ]
    merged = merge_sorted_lanes(keys + payload, num_keys)

    key_lanes, klen_s, shi_s, slo_s, valid_s, pos = split_composite_lanes(
        merged, key_words, uniform_klen=uniform_klen, seq32=seq32)
    return resolve_sorted_lanes(
        key_lanes, klen_s, shi_s, slo_s, valid_s,
        merged[pos], merged[pos + 1], list(merged[pos + 2:]), klen_const,
        merge_kind=merge_kind, drop_tombstones=drop_tombstones,
        uniform_klen=uniform_klen, seq32=seq32, key_words=key_words,
    )


def runs_are_sorted(
    key_words_be, key_len, seq_hi, seq_lo, valid
) -> bool:
    """Host-side (numpy) check that every run is sorted by the composite
    (key asc, seq desc) with valid rows a prefix — the precondition for
    the merge network. Vectorized over all runs; O(total entries)."""
    import numpy as np

    valid = np.asarray(valid)
    n_runs = valid.shape[0]
    # valid must be a prefix of each run
    if valid.shape[1] and not (
        valid[:, :-1] | ~valid[:, 1:]
    ).all():
        return False
    kw = np.asarray(key_words_be)
    # the full comparator (no fast-path reductions): a run sorted by it
    # is also sorted by any reduced variant the kernel may use, because
    # the dropped lanes are constant under the fast-path promises
    lanes = composite_key_lanes(
        np.where(valid, np.uint32(0), np.uint32(1)),
        (kw[:, :, w] for w in range(kw.shape[2])),
        np.asarray(key_len), np.asarray(seq_hi), np.asarray(seq_lo),
        uniform_klen=False, seq32=False)
    if valid.shape[1] < 2:
        return True
    lt = np.zeros((n_runs, valid.shape[1] - 1), dtype=bool)
    eq = np.ones_like(lt)
    for lane in lanes:
        a, b = lane[:, :-1], lane[:, 1:]
        lt |= eq & (a < b)
        eq &= a == b
    return bool((lt | eq).all())
