"""The TPU merge-resolve kernel: k-way merge + LSM resolution as one sort.

Replaces the reference's CPU heap-merge compaction loop (the HOT LOOP of
SURVEY §3.3) with a fixed-shape array program:

1. one multi-key ``lax.sort`` orders every entry by (validity, key lex asc,
   seq desc) — the k-way merge collapses into a sort because the runs are
   concatenated into one batch (XLA's TPU sort is highly tuned; a Pallas
   path exists in ops/pallas_kernels.py for tile-local work);
2. key-boundary detection + per-row segment-start/end indices — computed
   with cumulative max/min, NOT segment scatters;
3. vectorized LSM resolution per key: newest PUT/DELETE wins, MERGE
   operands above the base fold via the uint64-add operator as 16-bit-limb
   prefix-sum differences (carry-safe for < 2^16 operands per key);
4. stream compaction via a second (2-operand) sort.

**TPU design note:** everything here is sorts, cumulative scans, gathers,
and elementwise ops — no scatters and no ``jax.ops.segment_*`` (those lower
to serialized TPU scatters and were measured ~5× slower than this
formulation). Static shapes throughout: capacity N in → capacity N out +
count; the whole pipeline jits once and vmaps over shards.

Reference semantics being reproduced: compaction.py's resolve_stream
(heap-merge + _resolve_group), pinned by test_tpu_ops parity tests.
"""

from __future__ import annotations

import enum
import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .kv_format import KEY_WORDS

# OpType values (storage/records.py) as device constants
_PUT = 1
_DELETE = 2
_MERGE = 3


class MergeKind(enum.Enum):
    # PUT/DELETE only. Batches containing MERGE records without an operator
    # must NOT use this kernel (the backend routes them to the CPU path,
    # which preserves unresolved operand chains like the reference).
    NONE = "none"
    UINT64_ADD = "uint64add"  # the counter operator (merge_operator.h:20-40)


def _sort_batch(
    key_words_be: jnp.ndarray,  # (N, 6) u32
    key_len: jnp.ndarray,       # (N,) u32
    seq_hi: jnp.ndarray,
    seq_lo: jnp.ndarray,
    valid: jnp.ndarray,         # (N,) bool
    uniform_klen: bool = False,
    seq32: bool = False,
    key_words: int = KEY_WORDS,
) -> jnp.ndarray:
    """Returns the permutation ordering entries by (invalid-last, key asc,
    seq desc). The static fast-path flags drop sort operands the batch
    provably doesn't need (callers verify on host): ``uniform_klen`` — all
    valid keys share one length, so the length operand is constant among
    comparable rows; ``seq32`` — every seq fits 32 bits, so the high word
    is zero; ``key_words`` — every valid key fits the first ``key_words``
    u32 lanes, so the later lanes are all-zero and can't affect ordering.
    Multi-operand sort cost scales with operand count, so the common
    counter workload (16B keys, 32-bit seqs) runs 7 operands, not 10."""
    n = key_len.shape[0]
    iota = lax.iota(jnp.uint32, n)
    invalid_key = jnp.where(valid, jnp.uint32(0), jnp.uint32(1))
    operands = [
        invalid_key,
        *(key_words_be[:, w] for w in range(key_words)),
    ]
    if not uniform_klen:
        operands.append(key_len)
    if not seq32:
        operands.append(~seq_hi)  # descending seq == ascending complement
    operands.append(~seq_lo)
    operands.append(iota)
    sorted_ops = lax.sort(tuple(operands), num_keys=len(operands) - 1,
                          is_stable=False)
    return sorted_ops[-1]  # the permutation


def _limb_combine(lo16_0, lo16_1, hi16_0, hi16_1):
    """Four u32 limb sums → (lo, hi) u32 64-bit value with carries."""
    l0 = lo16_0 & 0xFFFF
    c0 = lo16_0 >> 16
    s1 = lo16_1 + c0
    l1 = s1 & 0xFFFF
    c1 = s1 >> 16
    s2 = hi16_0 + c1
    l2 = s2 & 0xFFFF
    c2 = s2 >> 16
    s3 = hi16_1 + c2
    l3 = s3 & 0xFFFF  # overflow beyond 64 bits wraps (two's complement)
    return l0 | (l1 << 16), l2 | (l3 << 16)


@functools.partial(
    jax.jit,
    static_argnames=("merge_kind", "drop_tombstones", "uniform_klen",
                     "seq32", "key_words"),
)
def merge_resolve_kernel(
    key_words_be: jnp.ndarray,  # (N, 6) u32
    key_words_le: jnp.ndarray,  # (N, 6) u32 (carried for bloom)
    key_len: jnp.ndarray,       # (N,) u32
    seq_hi: jnp.ndarray,
    seq_lo: jnp.ndarray,
    vtype: jnp.ndarray,         # (N,) u32
    val_words: jnp.ndarray,     # (N, W) u32
    val_len: jnp.ndarray,       # (N,) u32
    valid: jnp.ndarray,         # (N,) bool
    *,
    merge_kind: MergeKind = MergeKind.UINT64_ADD,
    drop_tombstones: bool = True,
    uniform_klen: bool = False,
    seq32: bool = False,
    key_words: int = KEY_WORDS,
) -> Dict[str, jnp.ndarray]:
    """Merge + resolve a concatenated batch of runs (order-free input).

    Returns dense output arrays (capacity N, first ``count`` rows live):
    key_words_be/le, key_len, seq_hi/lo, vtype, val_words, val_len, count.
    ``uniform_klen``/``seq32``/``key_words`` are caller-verified fast-path
    promises (see _sort_batch); results are identical either way.
    """
    n = key_len.shape[0]
    iota = lax.iota(jnp.int32, n)

    perm = _sort_batch(key_words_be, key_len, seq_hi, seq_lo, valid,
                       uniform_klen=uniform_klen, seq32=seq32,
                       key_words=key_words)
    take = lambda a: jnp.take(a, perm, axis=0)
    key_words_be = take(key_words_be)
    key_words_le = take(key_words_le)
    key_len = take(key_len)
    seq_hi = take(seq_hi)
    seq_lo = take(seq_lo)
    vtype = take(vtype)
    val_words = take(val_words)
    val_len = take(val_len)
    valid = take(valid)

    # --- key boundaries (sorted order) --------------------------------
    # (key_words promise: lanes >= key_words are zero for valid rows, so
    # comparing them cannot change equality among valid rows; invalid rows
    # get their own segments below regardless)
    prev_equal = jnp.ones(n - 1, dtype=bool)
    for w in range(key_words):
        prev_equal &= key_words_be[1:, w] == key_words_be[:-1, w]
    if not uniform_klen:
        # with uniform lengths, equal words imply equal keys among valid
        # rows (invalid rows get their own segments below regardless)
        prev_equal &= key_len[1:] == key_len[:-1]
    new_key = jnp.concatenate([jnp.ones(1, bool), ~prev_equal])
    new_key = new_key | ~valid  # each invalid row = its own segment
    last_key = jnp.concatenate([new_key[1:], jnp.ones(1, bool)])

    # per-row segment start/end indices via cumulative max/min (no scatter)
    seg_start = lax.cummax(jnp.where(new_key, iota, 0))
    seg_end = jnp.flip(lax.cummin(jnp.flip(jnp.where(last_key, iota, n - 1))))

    is_put = (vtype == _PUT) & valid
    is_del = (vtype == _DELETE) & valid
    is_merge = (vtype == _MERGE) & valid
    is_base = is_put | is_del

    # prefix counts of base entries: how many bases strictly before row i
    # within its segment
    base_incl = jnp.cumsum(is_base.astype(jnp.int32))
    base_excl = base_incl - is_base.astype(jnp.int32)
    base_before = base_excl - jnp.take(base_excl, seg_start)
    operand_mask = is_merge & (base_before == 0)
    first_base_mask = is_base & (base_before == 0)

    # per-segment flags evaluated at every row via prefix-count differences
    def seg_any(mask: jnp.ndarray) -> jnp.ndarray:
        c = jnp.cumsum(mask.astype(jnp.int32))
        c_excl_start = jnp.take(c, seg_start) - jnp.take(
            mask.astype(jnp.int32), seg_start
        )
        return (jnp.take(c, seg_end) - c_excl_start) > 0

    seg_has_operands = seg_any(operand_mask)
    seg_base_put = seg_any(first_base_mask & is_put)
    seg_base_del = seg_any(first_base_mask & is_del)

    if merge_kind is MergeKind.UINT64_ADD:
        # Reference parity (merge.py UInt64AddOperator._parse): values whose
        # length is not exactly 8 parse as 0.
        contrib = (
            (operand_mask | (first_base_mask & is_put)) & (val_len == 8)
        )
        lo = val_words[:, 0]
        hi = val_words[:, 1] if val_words.shape[1] > 1 else jnp.zeros_like(lo)
        zero = jnp.uint32(0)
        limbs = [
            jnp.where(contrib, lo & 0xFFFF, zero),
            jnp.where(contrib, lo >> 16, zero),
            jnp.where(contrib, hi & 0xFFFF, zero),
            jnp.where(contrib, hi >> 16, zero),
        ]

        def seg_sum(x: jnp.ndarray) -> jnp.ndarray:
            c = jnp.cumsum(x)
            return jnp.take(c, seg_end) - (jnp.take(c, seg_start) - jnp.take(x, seg_start))

        sums = [seg_sum(limb) for limb in limbs]
        sum_lo, sum_hi = _limb_combine(*sums)

        folded = seg_has_operands
        out_lo = jnp.where(folded, sum_lo, lo)
        out_hi = jnp.where(folded, sum_hi, hi)
        val_words = val_words.at[:, 0].set(out_lo)
        if val_words.shape[1] > 1:
            val_words = val_words.at[:, 1].set(out_hi)
        val_len = jnp.where(folded, jnp.uint32(8), val_len)
        pure_operands = seg_has_operands & ~seg_base_put & ~seg_base_del
        resolved_put = seg_base_put | (seg_has_operands & seg_base_del)
        out_vtype = jnp.where(
            resolved_put | (pure_operands & drop_tombstones),
            jnp.uint32(_PUT),
            jnp.where(pure_operands, jnp.uint32(_MERGE), vtype),
        )
        rep = new_key & valid
        vtype = jnp.where(rep, out_vtype, vtype)
        dropped = seg_base_del & ~seg_has_operands
    else:
        rep = new_key & valid
        dropped = is_del

    if drop_tombstones:
        keep = rep & ~dropped
    else:
        keep = rep

    # --- stream compaction via a 2-operand sort (no scatter) -----------
    not_keep = jnp.where(keep, jnp.uint32(0), jnp.uint32(1))
    _, perm2 = lax.sort((not_keep, lax.iota(jnp.uint32, n)), num_keys=1,
                        is_stable=True)
    take2 = lambda a: jnp.take(a, perm2, axis=0)
    count = jnp.sum(keep.astype(jnp.int32))
    live = lax.iota(jnp.int32, n) < count

    def masked(a: jnp.ndarray) -> jnp.ndarray:
        m = live if a.ndim == 1 else live[:, None]
        return jnp.where(m, take2(a), jnp.zeros_like(a))

    # Limb sums are exact only below 2^16 contributing operands per key;
    # flag oversize groups so callers fall back to CPU instead of silently
    # wrapping (the limit is generous: 65k updates of ONE key in ONE batch).
    seg_size = seg_end - seg_start + 1
    overflow_risk = (
        jnp.any((seg_size >= (1 << 16)) & valid)
        if merge_kind is MergeKind.UINT64_ADD
        else jnp.asarray(False)
    )

    return {
        "key_words_be": masked(key_words_be),
        "key_words_le": masked(key_words_le),
        "key_len": masked(key_len),
        "seq_hi": masked(seq_hi),
        "seq_lo": masked(seq_lo),
        "vtype": masked(vtype),
        "val_words": masked(val_words),
        "val_len": masked(val_len),
        "count": count,
        "needs_cpu_fallback": overflow_risk,
    }
