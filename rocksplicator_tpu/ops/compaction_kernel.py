"""The TPU merge-resolve kernel: k-way merge + LSM resolution as one sort.

Replaces the reference's CPU heap-merge compaction loop (the HOT LOOP of
SURVEY §3.3) with a fixed-shape array program:

1. one multi-key ``lax.sort`` orders every entry by (validity, key lex asc,
   seq desc) — the k-way merge collapses into a sort because the runs are
   concatenated into one batch. Every payload lane RIDES THE SORT as a
   non-key operand: round-2 device profiling showed TPU row gathers cost
   ~16 ms/lane at 131k rows while extra sort operands are nearly free
   (an 18-operand sort times the same as a 10-operand one), so the kernel
   carries payload through the sort network instead of gathering by the
   sorted permutation;
2. key-boundary detection with adjacent-lane compares, then per-segment
   aggregates via cumulative sums + two flagged segmented fills
   (``lax.associative_scan``) — one forward fill of segment-start values,
   one backward fill of segment-end prefix sums. No index gathers;
3. vectorized LSM resolution per key: newest PUT/DELETE wins, MERGE
   operands above the base fold via the uint64-add operator as 16-bit-limb
   prefix-sum differences (carry-safe for < 2^16 operands per key);
4. stream compaction via a second stable sort, again carrying every output
   lane as payload.

**TPU design note:** everything here is sorts, cumulative/associative
scans, and elementwise ops — ZERO gathers, zero scatters, and no
``jax.ops.segment_*``. Gathers were the round-1 kernel's actual bottleneck
(~70% of its 500 ms/launch on hardware); this formulation removes them
entirely. Static shapes throughout: capacity N in → capacity N out +
count; the whole pipeline jits once and vmaps over shards.

``key_words_le`` is never carried: a little-endian key word is the
byteswap of the big-endian word over the same bytes, so it is recomputed
from the sorted BE lanes with 4 shift/mask ops per word.

Reference semantics being reproduced: compaction.py's resolve_stream
(heap-merge + _resolve_group), pinned by test_tpu_ops parity tests.
"""

from __future__ import annotations

import enum
import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.flags import FLAGS, define_flag
from .kv_format import KEY_WORDS

# OpType values (storage/records.py) as device constants
_PUT = 1
_DELETE = 2
_MERGE = 3


_SORT_BACKENDS = ("lax", "pallas", "pallas_fused")

define_flag(
    "sort_backend", "lax",
    "merge_resolve_kernel sort backend for consumers with no per-call "
    "configuration (compaction service / engine-seam TPU backend / "
    "chunked merge): lax | pallas | pallas_fused. Env override: "
    "RSTPU_FLAG_SORT_BACKEND; runtime: FLAGS.set('sort_backend', ...)")


def deployment_sort_backend() -> str:
    """The deployment-wide sort backend choice — the ``sort_backend``
    flag (utils/flags.py: env ``RSTPU_FLAG_SORT_BACKEND``, runtime
    ``FLAGS.set``, visible in the /gflags.txt dump). One source of truth
    for every runtime consumer of merge_resolve_kernel that has no
    per-call configuration. An unknown value logs loudly once and runs
    the lax path rather than silently misconfiguring the fleet."""
    v = FLAGS.get("sort_backend")
    if v not in _SORT_BACKENDS:
        import logging

        logging.getLogger(__name__).warning(
            "sort_backend flag %r is not one of %s — using lax",
            v, _SORT_BACKENDS)
        return "lax"
    return v


class MergeKind(enum.Enum):
    # PUT/DELETE only. Batches containing MERGE records without an operator
    # must NOT use this kernel (the backend routes them to the CPU path,
    # which preserves unresolved operand chains like the reference).
    NONE = "none"
    UINT64_ADD = "uint64add"  # the counter operator (merge_operator.h:20-40)


def bswap32(w: jnp.ndarray) -> jnp.ndarray:
    """Byteswap u32 lanes: the LE word over the same 4 bytes as a BE word."""
    return ((w >> 24) | ((w >> 8) & jnp.uint32(0xFF00))
            | ((w << 8) & jnp.uint32(0xFF0000)) | (w << 24))


def composite_key_lanes(invalid, key_word_lanes, key_len, seq_hi, seq_lo,
                        *, uniform_klen: bool, seq32: bool):
    """THE canonical comparator lane order — (invalid-last, key words BE
    asc, [key_len], [~seq_hi], ~seq_lo) — as a lane list. Every consumer
    of the composite order builds it here so they cannot desync: the
    full-sort kernel (_sort_merge_order), the sorted-runs merge network
    (ops/merge_network.py), and its host-side precondition check
    (runs_are_sorted — numpy arrays work too: only list-building and
    ``~`` are used)."""
    keys = [invalid, *key_word_lanes]
    if not uniform_klen:
        keys.append(key_len)
    if not seq32:
        keys.append(~seq_hi)
    keys.append(~seq_lo)
    return keys


def split_composite_lanes(lanes, key_words: int, *, uniform_klen: bool,
                          seq32: bool):
    """Inverse of composite_key_lanes over an ordered lane sequence (the
    comparator lanes, already reordered by a sort/merge). Returns
    (key_word_lanes, key_len_or_None, seq_hi_or_None, seq_lo, valid,
    next_pos) — seq lanes are un-complemented."""
    pos = 1
    key_lanes = list(lanes[pos:pos + key_words])
    pos += key_words
    klen = None
    if not uniform_klen:
        klen = lanes[pos]
        pos += 1
    shi = None
    if not seq32:
        shi = ~lanes[pos]
        pos += 1
    slo = ~lanes[pos]
    pos += 1
    valid = lanes[0] == 0
    return key_lanes, klen, shi, slo, valid, pos


def _sort_merge_order(
    key_words_be: jnp.ndarray,  # (N, 6) u32
    key_len: jnp.ndarray,       # (N,) u32
    seq_hi: jnp.ndarray,
    seq_lo: jnp.ndarray,
    valid: jnp.ndarray,         # (N,) bool
    payload: Tuple[jnp.ndarray, ...],
    uniform_klen: bool = False,
    seq32: bool = False,
    key_words: int = KEY_WORDS,
    sort_backend: str = "lax",
):
    """One variadic sort into (invalid-last, key asc, seq desc) order,
    carrying ``payload`` lanes through the sort network. Returns
    (key_lanes_sorted, klen_sorted_or_None, seq_hi_sorted_or_None,
    seq_lo_sorted, valid_sorted, payload_sorted).

    The static fast-path flags drop sort operands the batch provably
    doesn't need (callers verify on host): ``uniform_klen`` — all valid
    keys share one length; ``seq32`` — every seq fits 32 bits; and
    ``key_words`` — lanes beyond it are zero for valid rows. Operand
    count barely affects TPU sort cost (measured), but fewer key operands
    still shorten the comparator."""
    invalid_key = jnp.where(valid, jnp.uint32(0), jnp.uint32(1))
    operands = composite_key_lanes(
        invalid_key, (key_words_be[:, w] for w in range(key_words)),
        key_len, seq_hi, seq_lo, uniform_klen=uniform_klen, seq32=seq32)
    num_keys = len(operands)
    operands.extend(payload)
    if sort_backend == "pallas":
        from .pallas_sort import sort_lanes

        sorted_ops = sort_lanes(tuple(operands), num_keys=num_keys,
                                backend="pallas")
    else:
        sorted_ops = lax.sort(tuple(operands), num_keys=num_keys,
                              is_stable=False)
    key_lanes, klen_s, shi_s, slo_s, valid_s, pos = split_composite_lanes(
        sorted_ops, key_words, uniform_klen=uniform_klen, seq32=seq32)
    return key_lanes, klen_s, shi_s, slo_s, valid_s, sorted_ops[pos:]


def _seg_fill_forward(flag: jnp.ndarray, values):
    """Segmented forward fill: every row receives each value as it was at
    its segment's FIRST row. ``flag`` marks segment starts (row 0 must be
    flagged). One flagged associative scan — no index gathers."""
    def comb(a, b):
        af, bf = a[0], b[0]
        return (af | bf,) + tuple(
            jnp.where(bf, bv, av) for av, bv in zip(a[1:], b[1:])
        )

    out = lax.associative_scan(comb, (flag,) + tuple(values))
    return out[1:]


def _seg_fill_backward(flag_last: jnp.ndarray, values):
    """Segmented backward fill: every row receives each value as it is at
    its segment's LAST row (``flag_last`` marks segment ends; the final
    row must be flagged). Same flagged combine as the forward fill, run
    as a reverse scan (reverse=True ≡ flip∘scan∘flip, without the
    materialized flips)."""
    def comb(a, b):
        af, bf = a[0], b[0]
        return (af | bf,) + tuple(
            jnp.where(bf, bv, av) for av, bv in zip(a[1:], b[1:])
        )

    out = lax.associative_scan(comb, (flag_last,) + tuple(values),
                               reverse=True)
    return out[1:]


def _limb_combine(lo16_0, lo16_1, hi16_0, hi16_1):
    """Four u32 limb sums → (lo, hi) u32 64-bit value with carries."""
    l0 = lo16_0 & 0xFFFF
    c0 = lo16_0 >> 16
    s1 = lo16_1 + c0
    l1 = s1 & 0xFFFF
    c1 = s1 >> 16
    s2 = hi16_0 + c1
    l2 = s2 & 0xFFFF
    c2 = s2 >> 16
    s3 = hi16_1 + c2
    l3 = s3 & 0xFFFF  # overflow beyond 64 bits wraps (two's complement)
    return l0 | (l1 << 16), l2 | (l3 << 16)


class ScanPrims:
    """The shift/scan primitive seam phases 2-3 are written against, so
    the XLA lane path (``resolve_sorted_lanes``) and the fused VMEM
    kernel (ops/pallas_resolve.py) share ONE copy of the resolve math:
    the XLA instance works on (N,) lanes with ``cumsum``/
    ``associative_scan``; the Pallas instance works on (R, 128) VMEM
    values with Hillis-Steele shift ladders. ``iota`` is the linear
    entry index in the instance's layout."""

    def __init__(self, iota, size, shift_prev, shift_next, cumsum_tuple,
                 fill_forward, fill_backward):
        self.iota = iota              # linear int32 index array
        self.size = size              # static N
        self.shift_prev = shift_prev  # y[i] = x[i-1] (x[0] arbitrary)
        self.shift_next = shift_next  # y[i] = x[i+1] (x[n-1] arbitrary)
        self.cumsum_tuple = cumsum_tuple    # inclusive prefix sums
        self.fill_forward = fill_forward    # (flag, values) seg fill
        self.fill_backward = fill_backward  # (flag_last, values)


def _prims_1d(n: int) -> ScanPrims:
    iota = lax.iota(jnp.int32, n)

    def shift_prev(x):
        return jnp.concatenate([jnp.zeros((1,), x.dtype), x[:-1]])

    def shift_next(x):
        return jnp.concatenate([x[1:], jnp.zeros((1,), x.dtype)])

    return ScanPrims(
        iota, n, shift_prev, shift_next,
        lambda values: tuple(jnp.cumsum(v) for v in values),
        _seg_fill_forward, _seg_fill_backward)


def resolve_decisions(
    prims: ScanPrims, key_lanes, key_len, valid, vtype, val_len,
    vw_lanes, *, merge_kind: MergeKind, drop_tombstones: bool,
    uniform_klen: bool, key_words: int,
):
    """Phases 2-3 on merge-ordered lanes: key-boundary detection +
    segmented LSM resolution, in terms of the ``prims`` seam only.
    Returns ``(vtype, val_len, vw_lanes, keep, overflow_mask_or_None)``
    — ``keep`` marks each key's representative row for the compaction
    phase; ``overflow_mask`` (UINT64_ADD only) marks rows whose segment
    exceeds the 2^16-operand limb-sum bound."""
    iota = prims.iota
    n = prims.size
    n_val_words = len(vw_lanes)
    vw_lanes = list(vw_lanes)

    # --- key boundaries: adjacent compare via a 1-shift; row 0 and
    # invalid rows are forced segment starts --------------------------
    prev_equal = None
    for w in range(key_words):
        eq = key_lanes[w] == prims.shift_prev(key_lanes[w])
        prev_equal = eq if prev_equal is None else prev_equal & eq
    if not uniform_klen:
        # with uniform lengths, equal words imply equal keys among valid
        # rows (invalid rows get their own segments below regardless)
        prev_equal = prev_equal & (key_len == prims.shift_prev(key_len))
    new_key = ~prev_equal | (iota == 0) | ~valid
    last_key = prims.shift_next(new_key) | (iota == n - 1)

    is_put = (vtype == _PUT) & valid
    is_del = (vtype == _DELETE) & valid
    is_merge = (vtype == _MERGE) & valid
    is_base = is_put | is_del

    overflow_mask = None
    if merge_kind is MergeKind.UINT64_ADD:
        # prefix counts of base entries: how many bases strictly before
        # row i within its segment. Segment-start values arrive via ONE
        # forward flagged fill — no index gathers.
        (base_incl,) = prims.cumsum_tuple((is_base.astype(jnp.int32),))
        base_excl = base_incl - is_base.astype(jnp.int32)
        base_excl_start, iota_start = prims.fill_forward(
            new_key, (base_excl, iota))
        base_before = base_excl - base_excl_start
        operand_mask = is_merge & (base_before == 0)
        first_base_mask = is_base & (base_before == 0)

        # Reference parity (merge.py UInt64AddOperator._parse): values
        # whose length is not exactly 8 parse as 0.
        contrib = (
            (operand_mask | (first_base_mask & is_put)) & (val_len == 8)
        )
        lo = vw_lanes[0]
        hi = vw_lanes[1] if n_val_words > 1 else jnp.zeros_like(lo)
        zero = jnp.uint32(0)
        limbs = [
            jnp.where(contrib, lo & 0xFFFF, zero),
            jnp.where(contrib, lo >> 16, zero),
            jnp.where(contrib, hi & 0xFFFF, zero),
            jnp.where(contrib, hi >> 16, zero),
        ]

        # inclusive prefix sums; their value AT THE SEGMENT END comes
        # back to every row via one backward flagged fill. Segment total
        # for a row = end_prefix - (own_prefix - own_x) — all local
        # afterwards.
        pref = list(prims.cumsum_tuple(tuple(limbs) + (
            operand_mask.astype(jnp.int32),
            (first_base_mask & is_put).astype(jnp.int32),
            (first_base_mask & is_del).astype(jnp.int32),
        ))) + [iota]
        ends = prims.fill_backward(last_key, tuple(pref))
        excl = lambda c, x: c - x  # noqa: E731

        sums = [
            ends[i] - excl(pref[i], limbs[i]) for i in range(4)
        ]
        seg_has_operands = (
            ends[4] - excl(pref[4], operand_mask.astype(jnp.int32))
        ) > 0
        seg_base_put = (
            ends[5] - excl(pref[5],
                           (first_base_mask & is_put).astype(jnp.int32))
        ) > 0
        seg_base_del = (
            ends[6] - excl(pref[6],
                           (first_base_mask & is_del).astype(jnp.int32))
        ) > 0
        seg_size = ends[7] - iota_start + 1
        sum_lo, sum_hi = _limb_combine(*sums)

        folded = seg_has_operands
        vw_lanes[0] = jnp.where(folded, sum_lo, lo)
        if n_val_words > 1:
            vw_lanes[1] = jnp.where(folded, sum_hi, hi)
        val_len = jnp.where(folded, jnp.uint32(8), val_len)
        pure_operands = seg_has_operands & ~seg_base_put & ~seg_base_del
        resolved_put = seg_base_put | (seg_has_operands & seg_base_del)
        out_vtype = jnp.where(
            resolved_put | (pure_operands & drop_tombstones),
            jnp.uint32(_PUT),
            jnp.where(pure_operands, jnp.uint32(_MERGE), vtype),
        )
        rep = new_key & valid
        vtype = jnp.where(rep, out_vtype, vtype)
        dropped = seg_base_del & ~seg_has_operands
        # Limb sums are exact only below 2^16 contributing operands per
        # key; flag oversize groups so callers fall back to CPU instead
        # of silently wrapping (generous: 65k updates of ONE key in ONE
        # batch).
        overflow_mask = (seg_size >= (1 << 16)) & valid
    else:
        rep = new_key & valid
        dropped = is_del

    if drop_tombstones:
        keep = rep & ~dropped
    else:
        keep = rep
    return vtype, val_len, vw_lanes, keep, overflow_mask


def resolve_sorted_lanes(
    key_lanes,                  # list of (N,) u32, length == key_words
    key_len,                    # (N,) u32 or None (uniform_klen path)
    seq_hi,                     # (N,) u32 or None (seq32 path)
    seq_lo,                     # (N,) u32
    valid,                      # (N,) bool
    vtype,                      # (N,) u32
    val_len,                    # (N,) u32
    vw_lanes,                   # list of (N,) u32 value-word lanes
    klen_const,                 # scalar u32 (uniform_klen reconstruction)
    *,
    merge_kind: MergeKind,
    drop_tombstones: bool,
    uniform_klen: bool,
    seq32: bool,
    key_words: int,
) -> Dict[str, jnp.ndarray]:
    """Phases 2-4 of the kernel on ALREADY merge-ordered lanes
    ((invalid-last, key asc, seq desc) order): boundary detection,
    segmented LSM resolution, stream compaction. Shared by the full-sort
    kernel below and the sorted-runs merge-network kernel
    (ops/merge_network.py), which produce that order two different ways."""
    n = seq_lo.shape[0]
    n_val_words = len(vw_lanes)
    seq_hi = seq_hi if seq_hi is not None else jnp.zeros_like(seq_lo)

    vtype, val_len, vw_lanes, keep, overflow_mask = resolve_decisions(
        _prims_1d(n), key_lanes, key_len, valid, vtype, val_len,
        vw_lanes, merge_kind=merge_kind, drop_tombstones=drop_tombstones,
        uniform_klen=uniform_klen, key_words=key_words)
    overflow_risk = (jnp.any(overflow_mask) if overflow_mask is not None
                     else jnp.asarray(False))

    # --- stream compaction: stable sort, output lanes as payload -------
    not_keep = jnp.where(keep, jnp.uint32(0), jnp.uint32(1))
    out_payload = list(key_lanes) + [seq_lo, vtype, val_len] + vw_lanes
    if not seq32:
        out_payload.append(seq_hi)
    if not uniform_klen:
        out_payload.append(key_len)
    sorted2 = lax.sort(tuple([not_keep] + out_payload), num_keys=1,
                       is_stable=True)
    count = jnp.sum(keep.astype(jnp.int32))
    live = lax.iota(jnp.int32, n) < count

    def m1(a: jnp.ndarray) -> jnp.ndarray:
        return jnp.where(live, a, jnp.zeros_like(a))

    pos = 1
    out_key_lanes = [m1(sorted2[pos + w]) for w in range(key_words)]
    pos += key_words
    out_seq_lo = m1(sorted2[pos]); pos += 1
    out_vtype = m1(sorted2[pos]); pos += 1
    out_val_len = m1(sorted2[pos]); pos += 1
    out_vw = [m1(sorted2[pos + w]) for w in range(n_val_words)]
    pos += n_val_words
    if not seq32:
        out_seq_hi = m1(sorted2[pos]); pos += 1
    else:
        out_seq_hi = jnp.zeros_like(out_seq_lo)
    if not uniform_klen:
        out_key_len = m1(sorted2[pos]); pos += 1
    else:
        out_key_len = jnp.where(live, klen_const, jnp.uint32(0))

    # full-width (6-lane) key matrices; lanes >= key_words are zero by the
    # caller-verified promise, LE lanes are byteswaps of the BE lanes
    zeros_tail = [jnp.zeros_like(out_seq_lo)] * (KEY_WORDS - key_words)
    out_kw_be = jnp.stack(out_key_lanes + zeros_tail, axis=1)
    out_kw_le = jnp.stack(
        [bswap32(w) for w in out_key_lanes] + zeros_tail, axis=1)

    return {
        "key_words_be": out_kw_be,
        "key_words_le": out_kw_le,
        "key_len": out_key_len,
        "seq_hi": out_seq_hi,
        "seq_lo": out_seq_lo,
        "vtype": out_vtype,
        "val_words": jnp.stack(out_vw, axis=1),
        "val_len": out_val_len,
        "count": count,
        "needs_cpu_fallback": overflow_risk,
    }


@functools.partial(
    jax.jit,
    static_argnames=("merge_kind", "drop_tombstones", "uniform_klen",
                     "seq32", "key_words", "sort_backend"),
)
def merge_resolve_kernel(
    key_words_be: jnp.ndarray,  # (N, 6) u32
    key_len: jnp.ndarray,       # (N,) u32
    seq_hi: jnp.ndarray,
    seq_lo: jnp.ndarray,
    vtype: jnp.ndarray,         # (N,) u32
    val_words: jnp.ndarray,     # (N, W) u32
    val_len: jnp.ndarray,       # (N,) u32
    valid: jnp.ndarray,         # (N,) bool
    *,
    merge_kind: MergeKind = MergeKind.UINT64_ADD,
    drop_tombstones: bool = True,
    uniform_klen: bool = False,
    seq32: bool = False,
    key_words: int = KEY_WORDS,
    sort_backend: str = "lax",
) -> Dict[str, jnp.ndarray]:
    """Merge + resolve a concatenated batch of runs (order-free input).

    Returns dense output arrays (capacity N, first ``count`` rows live):
    key_words_be/le, key_len, seq_hi/lo, vtype, val_words, val_len, count.
    (LE key lanes are not an input: they are byteswaps of the BE lanes,
    recomputed on the outputs — callers save the H2D transfer.)
    ``uniform_klen``/``seq32``/``key_words`` are caller-verified fast-path
    promises (see _sort_merge_order); results are identical either way.
    """
    if sort_backend == "pallas_fused":
        from .pallas_resolve import fused_merge_resolve, fused_supported

        n = seq_lo.shape[0]
        if fused_supported(n):
            return fused_merge_resolve(
                key_words_be, key_len, seq_hi, seq_lo, vtype, val_words,
                val_len, valid, merge_kind=merge_kind,
                drop_tombstones=drop_tombstones,
                uniform_klen=uniform_klen, seq32=seq32,
                key_words=key_words,
            )
        import logging

        logging.getLogger(__name__).warning(
            "pallas_fused backend requested but capacity %d is "
            "unsupported (needs a power of two >= 256) — falling back "
            "to the lax path", n)

    n_val_words = val_words.shape[1]
    # uniform_klen reconstruction constant: the one valid key length
    # (input order differs from output order, so the lane itself can't be
    # passed through; invalid rows may carry zero lengths)
    klen_const = jnp.max(jnp.where(valid, key_len, jnp.uint32(0)))

    # --- phase 1: merge-order sort, payload riding the network ---------
    payload = (vtype, val_len) + tuple(
        val_words[:, w] for w in range(n_val_words)
    )
    key_lanes, klen_s, shi_s, slo_s, valid_s, payload = _sort_merge_order(
        key_words_be, key_len, seq_hi, seq_lo, valid, payload,
        uniform_klen=uniform_klen, seq32=seq32, key_words=key_words,
        sort_backend=sort_backend,
    )
    return resolve_sorted_lanes(
        list(key_lanes), klen_s, shi_s, slo_s, valid_s,
        payload[0], payload[1], list(payload[2:]), klen_const,
        merge_kind=merge_kind, drop_tombstones=drop_tombstones,
        uniform_klen=uniform_klen, seq32=seq32, key_words=key_words,
    )
