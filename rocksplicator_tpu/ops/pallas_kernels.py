"""Pallas TPU kernels for the compaction pipeline hot ops.

The bloom hash (7-word FNV fold + murmur finalizer per key) is pure VPU
lane arithmetic — an ideal Pallas kernel: keys arrive as an (8, N) u32
panel (6 prefix words + length + padding row) so the sublane dimension is
exactly one tile and N rides the 128-wide lanes.

The lax implementation in bloom_tpu.py remains the default (XLA fuses it
into the surrounding pipeline); this kernel is the explicit-VMEM variant,
kept byte-identical and selected via ``use_pallas=True``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..storage.bloom import _FNV_OFFSET, _FNV_PRIME, _H2_MUL
from .bloom_tpu import _avalanche  # shared so both paths stay byte-identical

_U32 = jnp.uint32
_LANES = 512  # block width (multiple of 128)


def _bloom_hash_kernel(panel_ref, out_ref):
    """panel_ref: (8, L) u32 — rows 0..5 key words (LE), row 6 key length.
    out_ref: (8, L) u32 — row 0 = h1, row 1 = h2."""
    h = jnp.full((panel_ref.shape[1],), _U32(_FNV_OFFSET))
    for w in range(6):
        h = (h ^ panel_ref[w, :]) * _U32(_FNV_PRIME)
    h = (h ^ panel_ref[6, :]) * _U32(_FNV_PRIME)
    h1 = _avalanche(h)
    h2 = _avalanche(h * _U32(_H2_MUL) + _U32(1))
    out_ref[0, :] = h1
    out_ref[1, :] = h2
    # rows 2..7 are padding; leave them zeroed
    for r in range(2, 8):
        out_ref[r, :] = jnp.zeros_like(h1)


@functools.partial(jax.jit, static_argnames=("interpret",))
def bloom_hash_pallas(
    key_words_le: jnp.ndarray,  # (N, 6) u32
    key_len: jnp.ndarray,       # (N,) u32
    interpret: bool = False,
) -> tuple:
    """(h1, h2) per key via the Pallas kernel. ``interpret=True`` runs the
    kernel in interpreter mode (CPU tests)."""
    n = key_len.shape[0]
    padded = ((n + _LANES - 1) // _LANES) * _LANES
    panel = jnp.zeros((8, padded), dtype=_U32)
    panel = panel.at[:6, :n].set(key_words_le.T.astype(_U32))
    panel = panel.at[6, :n].set(key_len.astype(_U32))
    out = pl.pallas_call(
        _bloom_hash_kernel,
        out_shape=jax.ShapeDtypeStruct((8, padded), _U32),
        grid=(padded // _LANES,),
        in_specs=[pl.BlockSpec((8, _LANES), lambda i: (0, i))],
        out_specs=pl.BlockSpec((8, _LANES), lambda i: (0, i)),
        interpret=interpret,
    )(panel)
    return out[0, :n], out[1, :n]
