"""Fused VMEM-resident merge-resolve — ONE kernel, one HBM round-trip.

``merge_resolve_kernel`` (ops/compaction_kernel.py) is four phases:
merge-order sort, boundary detection, segmented LSM resolution, and a
second stable sort for stream compaction. With ``sort_backend="pallas"``
only phase 1 runs in VMEM; phases 2-4 still lower through XLA, so every
intermediate lane (prefix sums, segment fills, the full second sort
network) round-trips HBM — by the round-2 roofline analysis the same
tax the Pallas sort was built to remove.

This kernel runs ALL FOUR phases inside one ``pallas_call``: lanes are
read from HBM once, sorted by the shared bitonic network
(pallas_sort.bitonic_network), resolved with shift-based scans, stream-
compacted by a second in-VMEM bitonic pass (keyed by the packed
``not_keep<<31 | index`` composite — one lane whose unique-index
tiebreak reproduces XLA's ``is_stable=True`` ordering exactly), and
written back once.

Scan primitives: every ``cumsum``/segmented fill from the XLA resolve
is re-expressed as a Hillis-Steele ladder of linear-order shifts on the
(R, 128) lane layout. A shift by d decomposes like a bitonic partner
distance: d >= 128 is a sublane (row) shift, d < 128 is an in-row lane
shift with a one-row carry — all concatenates of VMEM slices, no
gathers. The segmented-fill combine has no identity element, so ladder
steps whose partner falls off the edge are masked with the row index
(``iota >= d`` forward / ``iota < n-d`` backward) instead of shifting
in a pad value.

Semantics are pinned element-exact against ``merge_resolve_kernel``'s
lax path by tests/test_tpu_ops.py parity tests (interpret mode on CPU;
the chip compiles the same network). Reference semantics reproduced:
compaction.py resolve_stream, same as the unfused kernel — see
/root/reference/rocksdb_admin (SST compaction) and SURVEY §3.3.

Opt-in via ``CompactionModel(sort_backend="pallas_fused")`` /
``BENCH_PALLAS_SORT=2``; shapes the kernel can't take (non-power-of-two
capacity, N < 256) fall back to the lax path with a warning.
"""

from __future__ import annotations

import functools
from typing import Dict, List

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .compaction_kernel import (
    MergeKind, ScanPrims, bswap32, composite_key_lanes,
    resolve_decisions, split_composite_lanes)
from .kv_format import KEY_WORDS
from .pallas_sort import _LANES, _VMEM, bitonic_network


def fused_supported(n: int) -> bool:
    """True when the fused kernel can take capacity ``n`` (the bitonic
    network needs a power of two spanning at least two rows). The
    dispatcher in merge_resolve_kernel consults this single source of
    truth before routing to ``fused_merge_resolve``."""
    return n >= 2 * _LANES and not (n & (n - 1))


# ---------------------------------------------------------------------
# linear-order shift / scan primitives on (R, 128) lanes
# ---------------------------------------------------------------------

def _shift_down(x, d: int):
    """y[i] = x[i-d] in linear order (i = row·128 + lane); zeros/False
    shifted in at the front. d is a power of two, so it is either a
    row multiple (sublane shift) or < 128 (lane shift + row carry)."""
    r = x.shape[0]
    if d % _LANES == 0:
        dr = d // _LANES
        pad = jnp.zeros((dr, _LANES), x.dtype)
        return jnp.concatenate([pad, x[:r - dr]], axis=0)
    prev_tail = jnp.concatenate(
        [jnp.zeros((1, d), x.dtype), x[:-1, _LANES - d:]], axis=0)
    return jnp.concatenate([prev_tail, x[:, :_LANES - d]], axis=1)


def _shift_up(x, d: int):
    """y[i] = x[i+d] in linear order; zeros/False shifted in at the
    back."""
    r = x.shape[0]
    if d % _LANES == 0:
        dr = d // _LANES
        pad = jnp.zeros((dr, _LANES), x.dtype)
        return jnp.concatenate([x[dr:], pad], axis=0)
    next_head = jnp.concatenate(
        [x[1:, :d], jnp.zeros((1, d), x.dtype)], axis=0)
    return jnp.concatenate([x[:, d:], next_head], axis=1)


def _cumsum_tuple(values, n: int):
    """Inclusive linear-order prefix sums of each array, one shared
    Hillis-Steele ladder (shifted-in zeros are the add identity — no
    edge masking needed)."""
    acc = tuple(values)
    d = 1
    while d < n:
        acc = tuple(a + _shift_down(a, d) for a in acc)
        d *= 2
    return acc


def _fill_forward(flag, values, iota, n: int):
    """compaction_kernel._seg_fill_forward on (R, 128) lanes: every row
    receives each value as of its segment's FIRST row (``flag`` marks
    segment starts; row 0 must be flagged)."""
    accf = flag
    accv = tuple(values)
    d = 1
    while d < n:
        sf = _shift_down(accf, d)
        sv = tuple(_shift_down(v, d) for v in accv)
        nf = accf | sf
        # combine(earlier=shifted, later=acc): later's flag wins
        nv = tuple(jnp.where(accf, b, a) for a, b in zip(sv, accv))
        ok = iota >= d  # partner exists; edge rows are already final
        accf = jnp.where(ok, nf, accf)
        accv = tuple(jnp.where(ok, v, b) for v, b in zip(nv, accv))
        d *= 2
    return accv


def _fill_backward(flag_last, values, iota, n: int):
    """compaction_kernel._seg_fill_backward on (R, 128) lanes: every row
    receives each value as of its segment's LAST row (``flag_last``
    marks segment ends; the final row must be flagged)."""
    accf = flag_last
    accv = tuple(values)
    d = 1
    while d < n:
        sf = _shift_up(accf, d)
        sv = tuple(_shift_up(v, d) for v in accv)
        nf = accf | sf
        nv = tuple(jnp.where(accf, b, a) for a, b in zip(sv, accv))
        ok = iota < (n - d)
        accf = jnp.where(ok, nf, accf)
        accv = tuple(jnp.where(ok, v, b) for v, b in zip(nv, accv))
        d *= 2
    return accv


# ---------------------------------------------------------------------
# the fused kernel body
# ---------------------------------------------------------------------

def _fused_kernel(
    num_keys: int, r_rows: int, n_in: int, key_words: int,
    uniform_klen: bool, seq32: bool, merge_kind: MergeKind,
    drop_tombstones: bool, n_val_words: int, *refs,
):
    in_refs = refs[:n_in]
    out_refs = refs[n_in:]
    n = r_rows * _LANES

    # --- phase 1: merge-order bitonic sort, all lanes in VMEM ---------
    lanes = [r[:] for r in in_refs]
    lanes = bitonic_network(lanes, num_keys, r_rows)
    key_lanes, klen, shi, slo, valid, pos = split_composite_lanes(
        lanes, key_words, uniform_klen=uniform_klen, seq32=seq32)
    vtype = lanes[pos]
    val_len = lanes[pos + 1]
    vw = list(lanes[pos + 2:pos + 2 + n_val_words])

    iota = (jax.lax.broadcasted_iota(jnp.int32, (r_rows, _LANES), 0)
            * _LANES
            + jax.lax.broadcasted_iota(jnp.int32, (r_rows, _LANES), 1))

    # --- phases 2-3: ONE copy of the resolve math (compaction_kernel.
    # resolve_decisions), instantiated over the VMEM shift ladders -----
    prims = ScanPrims(
        iota, n,
        lambda x: _shift_down(x, 1),
        lambda x: _shift_up(x, 1),
        lambda values: _cumsum_tuple(values, n),
        lambda flag, values: _fill_forward(flag, values, iota, n),
        lambda flag, values: _fill_backward(flag, values, iota, n),
    )
    vtype, val_len, vw, keep, overflow_mask = resolve_decisions(
        prims, key_lanes, klen, valid, vtype, val_len, vw,
        merge_kind=merge_kind, drop_tombstones=drop_tombstones,
        uniform_klen=uniform_klen, key_words=key_words)
    if overflow_mask is not None:
        ovf_u32 = jnp.max(overflow_mask.astype(jnp.uint32),
                          keepdims=True).reshape(1, 1)
    else:
        ovf_u32 = jnp.zeros((1, 1), jnp.uint32)

    # --- phase 4: stream compaction — second bitonic pass. The keep
    # bit and the unique linear index pack into ONE u32 key lane
    # (n <= 2^22 << 2^31): ordering by the composite == ordering by
    # (not_keep, index), which reproduces the lax path's is_stable=True
    # order exactly while saving a full lane through the network. -----
    not_keep = jnp.where(keep, jnp.uint32(0), jnp.uint32(1))
    sort2_key = (not_keep << 31) | iota.astype(jnp.uint32)
    out_payload: List = list(key_lanes) + [slo, vtype, val_len] + vw
    if not seq32:
        out_payload.append(shi)
    if not uniform_klen:
        out_payload.append(klen)
    sorted2 = bitonic_network([sort2_key] + out_payload, 1, r_rows)

    count = jnp.sum(keep.astype(jnp.int32), keepdims=True).reshape(1, 1)
    live = iota < count
    for ref, x in zip(out_refs[:-1], sorted2[1:]):
        ref[:] = jnp.where(live, x, jnp.zeros_like(x))

    lane_ix = jax.lax.broadcasted_iota(jnp.uint32, (1, _LANES), 1)
    meta = jnp.where(
        lane_ix == 0, count.astype(jnp.uint32),
        jnp.where(lane_ix == 1, ovf_u32, jnp.uint32(0)))
    out_refs[-1][:] = meta


@functools.partial(
    jax.jit,
    static_argnames=("merge_kind", "drop_tombstones", "uniform_klen",
                     "seq32", "key_words", "interpret"),
)
def fused_merge_resolve(
    key_words_be: jnp.ndarray,  # (N, 6) u32
    key_len: jnp.ndarray,       # (N,) u32
    seq_hi: jnp.ndarray,
    seq_lo: jnp.ndarray,
    vtype: jnp.ndarray,         # (N,) u32
    val_words: jnp.ndarray,     # (N, W) u32
    val_len: jnp.ndarray,       # (N,) u32
    valid: jnp.ndarray,         # (N,) bool
    *,
    merge_kind: MergeKind = MergeKind.UINT64_ADD,
    drop_tombstones: bool = True,
    uniform_klen: bool = False,
    seq32: bool = False,
    key_words: int = KEY_WORDS,
    interpret: bool = None,
) -> Dict[str, jnp.ndarray]:
    """Drop-in for ``merge_resolve_kernel`` (same contract, same output
    dict) running every phase in one VMEM residency. Requires capacity
    N to be a power of two >= 256 — callers dispatch via
    ``merge_resolve_kernel(..., sort_backend="pallas_fused")``, which
    falls back to the lax path for other shapes."""
    n = seq_lo.shape[0]
    if not fused_supported(n):
        raise ValueError(
            f"fused_merge_resolve needs power-of-two N >= {2 * _LANES}, "
            f"got {n}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n_val_words = val_words.shape[1]
    r_rows = n // _LANES
    klen_const = jnp.max(jnp.where(valid, key_len, jnp.uint32(0)))

    invalid_key = jnp.where(valid, jnp.uint32(0), jnp.uint32(1))
    operands = composite_key_lanes(
        invalid_key, (key_words_be[:, w] for w in range(key_words)),
        key_len, seq_hi, seq_lo, uniform_klen=uniform_klen, seq32=seq32)
    num_keys = len(operands)
    operands += [vtype, val_len] + [
        val_words[:, w] for w in range(n_val_words)]
    lanes2d = [x.reshape(r_rows, _LANES) for x in operands]
    n_in = len(lanes2d)
    # output lane order mirrors resolve_sorted_lanes' sorted2 payload
    n_out = key_words + 3 + n_val_words
    if not seq32:
        n_out += 1
    if not uniform_klen:
        n_out += 1

    kernel = functools.partial(
        _fused_kernel, num_keys, r_rows, n_in, key_words, uniform_klen,
        seq32, merge_kind, drop_tombstones, n_val_words)
    spec = (pl.BlockSpec(memory_space=_VMEM)
            if (_VMEM is not None and not interpret) else pl.BlockSpec())
    out = pl.pallas_call(
        kernel,
        out_shape=(
            [jax.ShapeDtypeStruct((r_rows, _LANES), jnp.uint32)
             for _ in range(n_out)]
            + [jax.ShapeDtypeStruct((1, _LANES), jnp.uint32)]
        ),
        in_specs=[spec] * n_in,
        out_specs=[spec] * (n_out + 1),
        interpret=interpret,
    )(*lanes2d)

    flat = [x.reshape(n) for x in out[:-1]]
    meta = out[-1]
    count = meta[0, 0].astype(jnp.int32)
    needs_cpu_fallback = meta[0, 1] > 0

    pos = 0
    out_key_lanes = flat[pos:pos + key_words]
    pos += key_words
    out_seq_lo = flat[pos]; pos += 1
    out_vtype = flat[pos]; pos += 1
    out_val_len = flat[pos]; pos += 1
    out_vw = flat[pos:pos + n_val_words]
    pos += n_val_words
    if not seq32:
        out_seq_hi = flat[pos]; pos += 1
    else:
        out_seq_hi = jnp.zeros_like(out_seq_lo)
    live = jax.lax.iota(jnp.int32, n) < count
    if not uniform_klen:
        out_key_len = flat[pos]; pos += 1
    else:
        out_key_len = jnp.where(live, klen_const, jnp.uint32(0))

    zeros_tail = [jnp.zeros_like(out_seq_lo)] * (KEY_WORDS - key_words)
    out_kw_be = jnp.stack(list(out_key_lanes) + zeros_tail, axis=1)
    out_kw_le = jnp.stack(
        [bswap32(w) for w in out_key_lanes] + zeros_tail, axis=1)
    return {
        "key_words_be": out_kw_be,
        "key_words_le": out_kw_le,
        "key_len": out_key_len,
        "seq_hi": out_seq_hi,
        "seq_lo": out_seq_lo,
        "vtype": out_vtype,
        "val_words": jnp.stack(out_vw, axis=1),
        "val_len": out_val_len,
        "count": count,
        "needs_cpu_fallback": needs_cpu_fallback,
    }
