"""On-device SST block encoding: kernel output → file bytes on the TPU.

North star (BASELINE.json): "... bloom construction, and block encoding
as batched ops over shards" — the compaction path's LAST host-side
byte-work moves onto the device. The kernel's struct-of-array lanes are
assembled into the TSST fixed-stride entry rows (u32 klen, key bytes,
u64 seq LE, u8 vtype, u32 vlen, value bytes — storage/sst.py layout) as
one (N, stride) u8 matrix, and per-block integrity checksums are
computed on device too, so the sink just slices rows and writes.

Checksum: a polynomial MAC over bytes, H = Σ (b_i + 1) · r^(i+1) mod
2^32 with odd r — order- and position-sensitive, fully data-parallel
(precomputed power vector + wrapping u32 ops), and cheap on both VPU and
numpy. The TSST format carries it in the props JSON ("block_chk"), so
v1 files without it stay readable (golden-format compatibility).

Everything is static-shaped: klen/vlen are caller-verified uniform
widths (the same promise the vectorized sink already requires).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import numpy as np

from ..storage.sst import ENTRY_FIXED_OVERHEAD as _ENTRY_FIXED_OVERHEAD
from ..utils.checksum import CHK_R as _CHK_R
from ..utils.checksum import poly_checksum as poly_checksum_np


@functools.partial(jax.jit, static_argnames=("klen", "vlen"))
def encode_rows_tpu(
    key_words_be,  # (N, 6) u32 big-endian words
    seq_hi, seq_lo,  # (N,) u32
    vtype,  # (N,) u32
    val_words,  # (N, W) u32 little-endian words
    *,
    klen: int,
    vlen: int,
):
    """(N, stride) u8 entry rows, byte-identical to the host sink's
    encode_uniform_block (tpu/format.py) — pinned by parity tests."""
    import jax.numpy as jnp

    n = seq_lo.shape[0]
    u8 = lambda x: x.astype(jnp.uint8)
    cols = []
    # u32 key_len, little-endian
    for b in range(4):
        cols.append(jnp.full((n,), (klen >> (8 * b)) & 0xFF, jnp.uint8))
    # key bytes: big-endian within each u32 lane
    for j in range(klen):
        word = key_words_be[:, j // 4]
        shift = 24 - 8 * (j % 4)
        cols.append(u8((word >> shift) & 0xFF))
    # u64 seq, little-endian (lo word first)
    for b in range(4):
        cols.append(u8((seq_lo >> (8 * b)) & 0xFF))
    for b in range(4):
        cols.append(u8((seq_hi >> (8 * b)) & 0xFF))
    # u8 vtype
    cols.append(u8(vtype & 0xFF))
    # u32 val_len, little-endian
    for b in range(4):
        cols.append(jnp.full((n,), (vlen >> (8 * b)) & 0xFF, jnp.uint8))
    # value bytes: little-endian within each u32 lane
    for j in range(vlen):
        word = val_words[:, j // 4]
        shift = 8 * (j % 4)
        cols.append(u8((word >> shift) & 0xFF))
    return jnp.stack(cols, axis=1)


@functools.partial(jax.jit, static_argnames=("block_entries",))
def block_checksums_tpu(rows, *, block_entries: int):
    """Per-block polynomial checksums over the row matrix.

    rows: (N, stride) u8; blocks are consecutive groups of
    ``block_entries`` rows. The last block may be short; its checksum
    covers the zero-padded canonical block length, and the reader
    (sst.py _verify_block_chk → utils/checksum.poly_checksum with
    length=block_bytes) pads the same way, so tail blocks verify
    against the device value directly."""
    import jax.numpy as jnp

    n, stride = rows.shape
    nblocks = (n + block_entries - 1) // block_entries
    pad = nblocks * block_entries - n
    padded = jnp.pad(rows, ((0, pad), (0, 0)))
    blocks = padded.reshape(nblocks, block_entries * stride)
    # powers r^1..r^L (wrapping u32): cumulative product of the constant
    powers = jnp.cumprod(
        jnp.full((block_entries * stride,), _CHK_R, jnp.uint32))
    vals = blocks.astype(jnp.uint32) + jnp.uint32(1)
    # zero-padding contributes (0+1)*r^i — the same constant the host
    # reference adds for padded tails, so full-vs-padded stays consistent
    return (vals * powers[None, :]).sum(axis=1, dtype=jnp.uint32)


def encode_and_checksum(
    arrays, count: int, klen: int, vlen: int, block_entries: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Convenience: run both device ops over kernel-output arrays and
    return host copies — (count, stride) u8 rows and per-block u32
    checksums (each over the zero-padded canonical block length)."""
    import jax.numpy as jnp

    rows = encode_rows_tpu(
        jnp.asarray(arrays["key_words_be"][:count]),
        jnp.asarray(arrays["seq_hi"][:count]),
        jnp.asarray(arrays["seq_lo"][:count]),
        jnp.asarray(arrays["vtype"][:count]),
        jnp.asarray(arrays["val_words"][:count]),
        klen=klen, vlen=vlen,
    )
    chk = block_checksums_tpu(rows, block_entries=block_entries)
    return np.asarray(rows), np.asarray(chk)


@functools.partial(
    jax.jit, static_argnames=("klen", "vlen", "seq32", "block_entries"))
def encode_planar_words_tpu(
    key_words_be,  # (N, 6) u32
    seq_hi, seq_lo,  # (N,) u32
    vtype,  # (N,) u32
    val_words,  # (N, W) u32
    *,
    klen: int,
    vlen: int,
    seq32: bool,
    block_entries: int,
):
    """PLANAR block encoding on device: (nblocks, words_per_block) u32 —
    each row is one block's plane words, byte-identical (as LE u32) to
    storage/planar.encode_planar_block for FULL blocks. N must be a
    multiple of block_entries (kernel capacities are powers of two); rows
    past the live count are zero, so only the tail block differs from
    the host layout (the sink re-packs that one block on host).

    This is what makes the planar format the TPU-first choice: where the
    row encoder interleaves bytes into an (N, stride) minor-dim matrix
    (the most expensive layout op this hardware has — PERF.md), the
    planar encoder only packs the vtype u8 lane and CONCATENATES existing
    lanes."""
    import jax.numpy as jnp

    n = seq_lo.shape[0]
    # zero-pad to a whole number of blocks (static — shapes are traced):
    # rows past the live count are zero anyway, and the sink only uses
    # blocks that lie fully inside the count
    pad = (-n) % block_entries
    nblocks = (n + pad) // block_entries
    kw = (klen + 3) // 4
    vw = (vlen + 3) // 4
    b = block_entries

    def blocked(lane):  # (N,) -> (nblocks, b)
        if pad:
            lane = jnp.pad(lane, (0, pad))
        return lane.reshape(nblocks, b)

    parts = [blocked(key_words_be[:, w]) for w in range(kw)]
    # plane order within a block: key lanes, seq_lo, [seq_hi], vtype, vals
    parts.append(blocked(seq_lo))
    if not seq32:
        parts.append(blocked(seq_hi))
    # vtype: 4 entries per word, little-endian byte order
    vt = blocked(vtype & jnp.uint32(0xFF)).reshape(nblocks, b // 4, 4)
    shifts = jnp.array([0, 8, 16, 24], jnp.uint32)
    parts.append((vt << shifts[None, None, :]).sum(
        axis=2, dtype=jnp.uint32))
    for w in range(vw):
        parts.append(blocked(val_words[:, w]))
    return jnp.concatenate(parts, axis=1)


@functools.partial(jax.jit, static_argnames=())
def planar_checksums_tpu(words):
    """Word-domain poly MAC per block row: H = Σ (w_i + 1) · r^(i+1)
    mod 2^32 — matches utils/checksum.poly_checksum_words."""
    import jax.numpy as jnp

    nblocks, wpb = words.shape
    powers = jnp.cumprod(jnp.full((wpb,), _CHK_R, jnp.uint32))
    return ((words + jnp.uint32(1)) * powers[None, :]).sum(
        axis=1, dtype=jnp.uint32)
