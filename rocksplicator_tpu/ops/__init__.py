"""JAX ops for the TPU compaction pipeline.

The hot ops of the north star (BASELINE.json): k-way merge-sort with LSM
resolution, bloom bitmap construction, and block encoding — expressed as
fixed-shape array programs that XLA tiles onto the TPU (sorts/segment ops
on the VPU, bulk data movement on HBM-friendly layouts).
"""

from .kv_format import KVBatch, KEY_WORDS, pack_entries, unpack_entries
from .compaction_kernel import merge_resolve_kernel, MergeKind
from .bloom_tpu import bloom_build_tpu

__all__ = [
    "KVBatch", "KEY_WORDS", "pack_entries", "unpack_entries",
    "merge_resolve_kernel", "MergeKind", "bloom_build_tpu",
]
