"""TPU bloom-filter construction — byte-identical to storage/bloom.py.

The register-blocked bloom (one 32-bit word per key, K bits from 5-bit
slices of a second hash) was designed for exactly this kernel: the FNV fold
+ murmur finalizer are pure u32 lane ops.

TPU design note: scatter-OR does not exist and per-bit plane scatters are
slow, so the bitmap materializes scatter-free except for one final store:
sort rows by word index (mask riding the sort as payload), compute each
word's OR with ONE flagged segmented OR-scan (``lax.associative_scan``),
then a single scatter-max of (nonzero only at segment ends) word values.
Sorts + scans + a single scatter — the same op-diet as the merge kernel.
Round-2 device profiling: this formulation is ~4x the bit-plane cumsum +
index-gather version it replaced (41 ms vs 165 ms at 8x131k rows).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..storage.bloom import K_BITS, _FNV_OFFSET, _FNV_PRIME, _H2_MUL
from .kv_format import KEY_WORDS

_U32 = jnp.uint32


def _avalanche(h: jnp.ndarray) -> jnp.ndarray:
    h = h ^ (h >> 16)
    h = h * _U32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * _U32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def bloom_hash_pair(
    key_words_le: jnp.ndarray, key_len: jnp.ndarray
) -> tuple:
    """(h1, h2) per row — vectorized hash_pair (storage/bloom.py)."""
    h = jnp.full(key_len.shape, _U32(_FNV_OFFSET))
    for w in range(KEY_WORDS):
        h = (h ^ key_words_le[:, w]) * _U32(_FNV_PRIME)
    h = (h ^ key_len.astype(_U32)) * _U32(_FNV_PRIME)
    h1 = _avalanche(h)
    h2 = _avalanche(h * _U32(_H2_MUL) + _U32(1))
    return h1, h2


def bloom_word_mask(
    key_words_le: jnp.ndarray, key_len: jnp.ndarray, num_words: int
) -> tuple:
    """(word_idx, 32-bit mask) per row — vectorized word_mask()."""
    h1, h2 = bloom_hash_pair(key_words_le, key_len)
    mask = jnp.zeros_like(h2)
    for j in range(K_BITS):
        mask = mask | (_U32(1) << ((h2 >> _U32(5 * j)) & _U32(31)))
    return (h1 % _U32(num_words)).astype(jnp.int32), mask


@functools.partial(jax.jit, static_argnames=("num_words",))
def bloom_build_tpu(
    key_words_le: jnp.ndarray,  # (N, 6) u32
    key_len: jnp.ndarray,       # (N,) u32
    valid: jnp.ndarray,         # (N,) bool
    *,
    num_words: int,
) -> jnp.ndarray:
    """Returns the (num_words,) u32 bloom bitmap."""
    word_idx, mask = bloom_word_mask(key_words_le, key_len, num_words)
    word_idx = jnp.where(valid, word_idx, num_words)  # invalid -> spill word
    # group rows by word: 2-operand sort, the mask riding as payload
    sorted_idx, sorted_mask = lax.sort(
        (word_idx.astype(jnp.uint32), mask), num_keys=1, is_stable=False
    )
    sorted_idx = sorted_idx.astype(jnp.int32)
    new_word = jnp.concatenate(
        [jnp.ones(1, bool), sorted_idx[1:] != sorted_idx[:-1]]
    )
    last_word = jnp.concatenate([new_word[1:], jnp.ones(1, bool)])

    # flagged segmented OR-scan: row i holds OR of masks from its
    # segment's start through i; at the segment's last row that is the
    # whole word's value. No index gathers, no bit-plane expansion.
    def comb(a, b):
        af, av = a
        bf, bv = b
        return af | bf, jnp.where(bf, bv, av | bv)

    _, seg_or = lax.associative_scan(comb, (new_word, sorted_mask))
    word_val = jnp.where(last_word, seg_or, _U32(0))
    # only segment-end rows carry nonzero values; max == the word's OR
    bitmap = jnp.zeros(num_words + 1, dtype=_U32)
    bitmap = bitmap.at[sorted_idx].max(word_val, mode="drop")
    return bitmap[:num_words]
