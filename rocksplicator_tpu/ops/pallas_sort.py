"""VMEM-resident bitonic sort — the Pallas attack on the kernel's
dominant cost.

Round-2 device profiling (PERF.md) put the merge-resolve's two
``lax.sort`` calls at ~9 ms of the 17 ms device time for 8×131k, and
the roofline analysis says a sort-based pipeline should cost ~1-2 ms of
HBM traffic. The gap is XLA's generic bitonic lowering: every
compare-exchange stage round-trips all operand lanes through HBM
(~log²(N)/2 ≈ 153 stages at 131k → hundreds of MB of traffic per
shard). The hand-rolled XLA merge network (ops/merge_network.py) lost
for exactly that reason — per-stage HBM materialization.

This kernel holds EVERY operand lane in VMEM across ALL stages: one HBM
read per lane at entry, 153 in-register/VMEM compare-exchange stages,
one HBM write at exit. Operand budget: 131072 rows × 18 u32 lanes =
9.4 MB < ~16 MB VMEM/core.

Layout: each (N,) u32 lane is viewed as (R, 128) row-major (linear index
i = r·128 + c). A bitonic partner distance d decomposes as:
- d ≥ 128 (row-partner): reshape (R, 128) → (R/2dr, 2, dr, 128) and
  compare-exchange the two middle halves — pure sublane slicing.
- d < 128 (lane-partner): reshape lanes (R, 128) → (R, 128/2d, 2, d)
  and exchange the halves — an in-VMEM lane shuffle, with no HBM
  round-trip (the catastrophic cost XLA pays for minor-dim relayouts
  does not apply inside VMEM).
The ascending/descending direction of stage (k, j) is constant within
each 2^(k+1)-block, expressed as a broadcasted-iota parity mask.

Comparator: lexicographic over the first ``num_keys`` lanes (the
composite_key_lanes order), payload lanes ride the exchanges — the same
payload-through contract as ``lax.sort(operands, num_keys=...)``, which
this function is a drop-in replacement for (N must be a power of two;
the compaction batches are always 2^k capacities).

Opt-in (CompactionModel(sort_backend="pallas") / BENCH_PALLAS_SORT=1):
the lax.sort path stays the default until the chip measurement says
otherwise; ``interpret=True`` runs on CPU for the parity tests.
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is unavailable on some CPU-only installs; interpret mode
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_LANES = 128


def _lex_less(a_keys, b_keys):
    """Lexicographic a < b over aligned key-lane lists (u32)."""
    less = None
    eq_prefix = None
    for a, b in zip(a_keys, b_keys):
        this_less = a < b
        this_eq = a == b
        if less is None:
            less, eq_prefix = this_less, this_eq
        else:
            less = less | (eq_prefix & this_less)
            eq_prefix = eq_prefix & this_eq
    return less


def _exchange(lanes, num_keys, asc_mask, lo_half, hi_half):
    """One compare-exchange between two aligned half-views. Returns the
    (new_lo, new_hi) per lane. ``asc_mask`` is True where the enclosing
    bitonic block sorts ascending; views are any equal shape."""
    a_keys = [lo_half(x) for x in lanes[:num_keys]]
    b_keys = [hi_half(x) for x in lanes[:num_keys]]
    b_less = _lex_less(b_keys, a_keys)  # partner belongs before me
    swap = jnp.where(asc_mask, b_less, ~b_less)
    new = []
    for x in lanes:
        a, b = lo_half(x), hi_half(x)
        new.append((jnp.where(swap, b, a), jnp.where(swap, a, b)))
    return new


def _stage(lanes, num_keys, r_rows, k, j):
    """Apply bitonic stage (k, j): partner distance d = 2^j inside
    direction blocks of 2^(k+1). ``lanes`` are (R, 128) u32 arrays."""
    d = 1 << j
    blk = 1 << (k + 1)
    n = r_rows * _LANES
    if d >= _LANES:
        dr = d // _LANES  # row-partner distance
        nb = r_rows // (2 * dr)

        def lo(x):
            return x.reshape(nb, 2, dr, _LANES)[:, 0]

        def hi(x):
            return x.reshape(nb, 2, dr, _LANES)[:, 1]

        # direction: block index of linear i is i // blk; constant across
        # a (dr, 128) tile here because blk >= 2d >= 2·128·dr
        pair_base = jax.lax.broadcasted_iota(
            jnp.uint32, (nb, dr, _LANES), 0) * jnp.uint32(2 * dr * _LANES)
        asc = (pair_base // jnp.uint32(blk)) % 2 == 0
        ex = _exchange(lanes, num_keys, asc, lo, hi)
        out = []
        for (a, b) in ex:
            stacked = jnp.stack([a, b], axis=1)  # (nb, 2, dr, 128)
            out.append(stacked.reshape(r_rows, _LANES))
        return out
    # lane-partner stage: d < 128
    nb = _LANES // (2 * d)

    def lo(x):
        return x.reshape(r_rows, nb, 2, d)[:, :, 0]

    def hi(x):
        return x.reshape(r_rows, nb, 2, d)[:, :, 1]

    row_base = jax.lax.broadcasted_iota(
        jnp.uint32, (r_rows, nb, d), 0) * jnp.uint32(_LANES)
    lane_base = jax.lax.broadcasted_iota(
        jnp.uint32, (r_rows, nb, d), 1) * jnp.uint32(2 * d)
    lane_off = jax.lax.broadcasted_iota(jnp.uint32, (r_rows, nb, d), 2)
    i_lo = row_base + lane_base + lane_off
    asc = (i_lo // jnp.uint32(blk)) % 2 == 0
    ex = _exchange(lanes, num_keys, asc, lo, hi)
    out = []
    for (a, b) in ex:
        stacked = jnp.stack([a, b], axis=2)  # (R, nb, 2, d)
        out.append(stacked.reshape(r_rows, _LANES))
    return out


def bitonic_network(lanes, num_keys: int, r_rows: int):
    """The full bitonic network over (R, 128) u32 lane VALUES (already
    VMEM-resident inside a kernel). Shared by the standalone sort kernel
    and the fused sort+resolve kernel (ops/pallas_resolve.py)."""
    n = r_rows * _LANES
    log_n = n.bit_length() - 1
    for k in range(log_n):
        for j in range(k, -1, -1):
            lanes = _stage(lanes, num_keys, r_rows, k, j)
    return lanes


def _sort_kernel(num_keys: int, r_rows: int, n_lanes: int, *refs):
    """Pallas kernel body: refs = n_lanes input refs + n_lanes output
    refs. Loads all lanes into VMEM values, runs the full bitonic
    network, writes back once."""
    in_refs = refs[:n_lanes]
    out_refs = refs[n_lanes:]
    lanes = [r[:] for r in in_refs]
    lanes = bitonic_network(lanes, num_keys, r_rows)
    for r, x in zip(out_refs, lanes):
        r[:] = x


@functools.partial(
    jax.jit, static_argnames=("num_keys", "interpret"))
def bitonic_sort_lanes(
    operands: Tuple[jnp.ndarray, ...],
    num_keys: int,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, ...]:
    """Drop-in for ``lax.sort(operands, num_keys=num_keys)`` on (N,) u32
    lanes with N a power of two ≥ 256. The first ``num_keys`` lanes are
    the lexicographic comparator; the rest ride as payload."""
    n = operands[0].shape[0]
    if n & (n - 1) or n < 2 * _LANES:
        raise ValueError(f"bitonic_sort_lanes needs power-of-two N >= "
                         f"{2 * _LANES}, got {n}")
    for i, x in enumerate(operands):
        if x.dtype != jnp.uint32:
            # silent reinterpretation would order signed lanes differently
            # from lax.sort — enforce the documented u32-lane contract
            raise TypeError(f"operand {i} is {x.dtype}, expected uint32")
    r_rows = n // _LANES
    n_lanes = len(operands)
    lanes2d = [x.reshape(r_rows, _LANES) for x in operands]
    kernel = functools.partial(_sort_kernel, num_keys, r_rows, n_lanes)
    spec = (pl.BlockSpec(memory_space=_VMEM)
            if (_VMEM is not None and not interpret) else pl.BlockSpec())
    out = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((r_rows, _LANES), jnp.uint32)
                   for _ in range(n_lanes)],
        in_specs=[spec] * n_lanes,
        out_specs=[spec] * n_lanes,
        interpret=interpret,
    )(*lanes2d)
    return tuple(x.reshape(n) for x in out)


def sort_lanes(operands: Sequence[jnp.ndarray], num_keys: int,
               backend: str = "lax",
               interpret: bool = None) -> Tuple[jnp.ndarray, ...]:
    """Sort dispatch: ``lax`` = XLA's sort (default), ``pallas`` = the
    VMEM-resident bitonic kernel (falls back to lax for shapes the
    kernel doesn't support). ``interpret=None`` auto-selects interpreter
    mode on non-TPU backends so the same model code runs in the CPU test
    suite and compiles natively on the chip."""
    ops = tuple(operands)
    if backend == "pallas":
        n = ops[0].shape[0]
        if (n >= 2 * _LANES and not (n & (n - 1))
                and all(x.dtype == jnp.uint32 for x in ops)):
            if interpret is None:
                interpret = jax.default_backend() != "tpu"
            return bitonic_sort_lanes(ops, num_keys=num_keys,
                                      interpret=interpret)
        import logging

        logging.getLogger(__name__).warning(
            "pallas sort backend requested but unsupported for this "
            "shape/dtype (n=%d) — falling back to lax.sort; the measured "
            "numbers are NOT the pallas kernel", n)
    return jax.lax.sort(ops, num_keys=num_keys, is_stable=False)
