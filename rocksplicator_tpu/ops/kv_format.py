"""Fixed-shape KV batch representation for TPU kernels.

The hard part the SURVEY flags up front (§7): variable-length keys/values
vs Pallas/XLA's fixed-shape world. Representation chosen:

- **keys** → 24-byte zero-padded prefixes as 6 *big-endian* u32 lanes plus a
  length lane. For keys ≤ 24 bytes (the counter workload and most sharded-KV
  schemas) the prefix is the whole key, so lexicographic byte order ==
  ascending (word0..word5, len) tuple order. Longer keys are detected at
  pack time and routed to the CPU backend.
- **values** → zero-padded to a fixed byte width as u32 lanes + a length
  lane. Counter values are 8 bytes. For the uint64-add merge path values
  are additionally exposed as 4×16-bit limbs (in u32 lanes) so segment sums
  cannot overflow 32 bits for groups < 2^16 operands.
- **seqs** → (hi, lo) u32 pairs (no x64 dependency).

The same 24-byte-prefix convention is shared with the storage bloom filter
(storage/bloom.py) so TPU-built blooms are byte-identical.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..storage.records import OpType

KEY_BYTES = 24
KEY_WORDS = KEY_BYTES // 4
VAL_BYTES_DEFAULT = 8

# The canonical lane set every array pipeline carries (tpu/chunked.py
# kernel passes, the streaming merge's windows/chunks). LE key words are
# byteswap-derived for device bloom hashing; CPU-only consumers drop them.
LANE_FIELDS = (
    "key_words_be", "key_words_le", "key_len", "seq_hi", "seq_lo",
    "vtype", "val_words", "val_len",
)

Entry = Tuple[bytes, int, int, bytes]  # key, seq, vtype, value


class UnsupportedBatch(Exception):
    """Raised when entries don't fit the fixed-shape representation —
    callers fall back to the CPU backend."""


@dataclass
class KVBatch:
    """Struct-of-arrays batch of N entries (numpy, host-side)."""

    key_words_be: np.ndarray   # (N, 6) u32, big-endian word values
    key_words_le: np.ndarray   # (N, 6) u32, little-endian (bloom hashing)
    key_len: np.ndarray        # (N,) u32
    seq_hi: np.ndarray         # (N,) u32
    seq_lo: np.ndarray         # (N,) u32
    vtype: np.ndarray          # (N,) u32 (OpType)
    val_words: np.ndarray      # (N, val_words) u32 little-endian padded
    val_len: np.ndarray        # (N,) u32
    valid: np.ndarray          # (N,) bool
    val_bytes: int

    @property
    def capacity(self) -> int:
        return self.key_len.shape[0]

    def num_valid(self) -> int:
        return int(self.valid.sum())

    def payload_bytes(self) -> int:
        """Logical bytes represented (keys + values of valid entries)."""
        return int((self.key_len[self.valid].sum()
                    + self.val_len[self.valid].sum()))


def pack_entries(
    entries: Sequence[Entry],
    capacity: Optional[int] = None,
    val_bytes: int = VAL_BYTES_DEFAULT,
) -> KVBatch:
    """Pack (key, seq, vtype, value) tuples into fixed lanes.

    Raises UnsupportedBatch for keys > 24B or values > val_bytes.
    """
    n = len(entries)
    cap = capacity or n
    if n > cap:
        raise UnsupportedBatch(f"{n} entries exceed capacity {cap}")
    vw = val_bytes // 4
    key_buf = np.zeros((cap, KEY_BYTES), dtype=np.uint8)
    val_buf = np.zeros((cap, val_bytes), dtype=np.uint8)
    key_len = np.zeros(cap, dtype=np.uint32)
    val_len = np.zeros(cap, dtype=np.uint32)
    seq = np.zeros(cap, dtype=np.uint64)
    vtype = np.zeros(cap, dtype=np.uint32)
    valid = np.zeros(cap, dtype=bool)
    for i, (key, s, vt, value) in enumerate(entries):
        if len(key) > KEY_BYTES:
            raise UnsupportedBatch(f"key too long for TPU lanes: {len(key)}")
        if len(value) > val_bytes:
            raise UnsupportedBatch(f"value too long for TPU lanes: {len(value)}")
        key_buf[i, : len(key)] = np.frombuffer(key, dtype=np.uint8)
        val_buf[i, : len(value)] = np.frombuffer(value, dtype=np.uint8)
        key_len[i] = len(key)
        val_len[i] = len(value)
        seq[i] = s
        vtype[i] = int(vt)
        valid[i] = True
    key_words_be = key_buf.view(">u4").astype(np.uint32).reshape(cap, KEY_WORDS)
    key_words_le = key_buf.view("<u4").reshape(cap, KEY_WORDS).copy()
    val_words = val_buf.view("<u4").reshape(cap, vw).copy()
    return KVBatch(
        key_words_be=key_words_be,
        key_words_le=key_words_le,
        key_len=key_len,
        seq_hi=(seq >> np.uint64(32)).astype(np.uint32),
        seq_lo=(seq & np.uint64(0xFFFFFFFF)).astype(np.uint32),
        vtype=vtype,
        val_words=val_words,
        val_len=val_len,
        valid=valid,
        val_bytes=val_bytes,
    )


def unpack_entries(
    key_words_be: np.ndarray,
    key_len: np.ndarray,
    seq_hi: np.ndarray,
    seq_lo: np.ndarray,
    vtype: np.ndarray,
    val_words: np.ndarray,
    val_len: np.ndarray,
    count: int,
) -> List[Entry]:
    """Device output arrays → entry tuples (first ``count`` rows)."""
    count = int(count)
    kb = (
        np.ascontiguousarray(key_words_be[:count].astype(">u4"))
        .view(np.uint8)
        .reshape(count, KEY_BYTES)
    )
    vb = (
        np.ascontiguousarray(val_words[:count].astype("<u4"))
        .view(np.uint8)
        .reshape(count, -1)
    )
    seqs = (seq_hi[:count].astype(np.uint64) << np.uint64(32)) | seq_lo[
        :count
    ].astype(np.uint64)
    out: List[Entry] = []
    for i in range(count):
        kl = int(key_len[i])
        vl = int(val_len[i])
        out.append(
            (
                kb[i, :kl].tobytes(),
                int(seqs[i]),
                OpType(int(vtype[i])),
                vb[i, :vl].tobytes(),
            )
        )
    return out


def fast_flags(key_len: np.ndarray, seq_hi: np.ndarray,
               valid: np.ndarray) -> Tuple[bool, bool, int]:
    """(uniform_klen, seq32, key_words) host-side checks enabling the
    kernel's reduced-operand sort (ops/compaction_kernel._sort_merge_order).
    ``key_words`` = u32 lanes actually carrying key bytes: lanes beyond
    ceil(max_klen/4) are zero-padding for every valid row, so the sort and
    boundary compare can skip them."""
    kl = key_len[valid]
    uniform = bool(len(kl) == 0 or (kl == kl[0]).all())
    seq32 = bool((seq_hi[valid] == 0).all())
    max_kl = int(kl.max()) if len(kl) else 0
    key_words = max(1, (max_kl + 3) // 4)
    return uniform, seq32, key_words
