"""Controller: leader-elected assignment computation.

Reference: the Helix controller (external to the reference repo but the
brain of its control plane). Responsibilities reproduced:
- watch live instances / resources / current states;
- compute stable partition placement (rendezvous hashing keeps most
  placements unchanged when membership changes);
- leader handoff in two phases (demote-then-promote) so participants'
  no-live-leader guard holds;
- write per-instance assignments the participants converge on;
- reconcile periodically to self-heal.
"""

from __future__ import annotations

import hashlib
import json
import logging
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from ..testing import failpoints as fp
from ..utils.segment_utils import segment_to_db_name, db_name_to_partition_name
from .coordinator import CoordinatorClient
from .model import (
    FOLLOWER,
    LEADER,
    InstanceInfo,
    PartitionAssignment,
    PlacementPin,
    ResourceDef,
    SplitRecord,
    cluster_path,
    decode_states,
    encode_assignments,
)

log = logging.getLogger(__name__)

_LEADERLIKE = {"LEADER", "MASTER"}
_FOLLOWERLIKE = {"FOLLOWER", "SLAVE"}


def _rendezvous(partition: str, instance_id: str) -> int:
    h = hashlib.blake2b(
        f"{partition}|{instance_id}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(h, "big")


def _state_names(state_model: str) -> Tuple[str, str]:
    if state_model == "MasterSlave":
        return "MASTER", "SLAVE"
    if state_model in ("OnlineOffline", "Cache", "Bootstrap"):
        return "ONLINE", "ONLINE"
    if state_model == "CdcLeaderStandby":
        return "LEADER", "STANDBY"
    return LEADER, FOLLOWER


def effective_shards(resource: ResourceDef,
                     splits: Optional[List[SplitRecord]] = None
                     ) -> List[int]:
    """The shard ids this resource actually SERVES: the hash range
    ``range(num_shards)`` with every ACTIVE-split parent replaced —
    transitively, since children can split again — by its range
    children. The hash map is untouched by splits (keys still hash to
    the parent slot; routers chase the split records by range), so this
    is purely the controller's enumeration of which partitions need
    replicas and leaders."""
    by_parent = {r.parent_shard: r for r in (splits or [])
                 if r.segment == resource.segment and r.phase == "active"}
    out: List[int] = []
    for s in range(resource.num_shards):
        frontier = [s]
        while frontier:
            cur = frontier.pop()
            rec = by_parent.get(cur)
            if rec is None:
                out.append(cur)
            else:
                frontier.extend((rec.low_shard, rec.high_shard))
    return sorted(out)


def assign_resource(
    resource: ResourceDef,
    instances: Dict[str, InstanceInfo],
    current: Dict[str, Dict[str, str]],
    per_instance: Dict[str, Dict[str, PartitionAssignment]],
    epochs: Dict[str, Dict],
    pins: Optional[Dict[str, PlacementPin]] = None,
    splits: Optional[List[SplitRecord]] = None,
) -> Set[str]:
    """Compute one resource's target assignments (pure — no coordinator
    I/O, so the two-phase handoff edges are directly unit-testable).

    ``epochs`` is the fencing-epoch ledger: partition -> {"epoch": int,
    "leader": iid}. An epoch bumps EXACTLY when a promotion is issued to
    a different leader than the ledger records — i.e. at the moment a
    new leader may start acking — never during the demote phase of a
    two-phase handoff (the old leader is still the only legitimate
    acker until it reports non-leader). Mutated in place; returns the
    set of partitions whose ledger record changed (the caller persists
    those BEFORE publishing the stamped assignments).

    ``pins`` (live shard moves, round 15) overrides rendezvous placement
    per partition: a pinned partition's replica set is the pin's live
    instances verbatim, and a live ``preferred_leader`` steers the
    two-phase handoff toward it — the flip a shard move requests rides
    the SAME demote → no-live-leader → epoch-mint → promote machinery as
    a failover, so a pinned cutover is epoch-stamped end to end. A pin
    whose instances are all dead is ignored (a pin can never un-serve a
    partition).

    ``splits`` (hot-shard range splits, round 20) swaps ACTIVE-split
    parents out of the enumeration for their range children
    (:func:`effective_shards`): the parent gets NO assignment — its
    stale replicas retire through Offline→Dropped exactly like a
    removed resource's — while each child is assigned like any
    partition. The split cutover pre-seeded the children's epoch ledger
    and pins, so the first child pass finds a recorded leader matching
    the pinned preferred leader and mints nothing."""
    leader_state, follower_state = _state_names(resource.state_model)
    changed: Set[str] = set()
    iids = sorted(instances)
    if not iids:
        return changed
    for shard in effective_shards(resource, splits):
        partition = db_name_to_partition_name(
            segment_to_db_name(resource.segment, shard)
        )
        pin = (pins or {}).get(partition)
        pinned_live = (
            [iid for iid in pin.replicas if iid in instances]
            if pin is not None else []
        )
        ranked = sorted(
            iids, key=lambda iid: _rendezvous(partition, iid),
            reverse=True,
        )
        if pinned_live:
            # pinned placement, TOPPED UP from the rendezvous ranking
            # when pinned replicas died: a moved partition must keep
            # self-healing to full replication like an unpinned one (a
            # frozen pin would serve under-replicated forever after one
            # permanent failure)
            replicas = pinned_live + [
                iid for iid in ranked if iid not in pinned_live
            ][: max(0, resource.replicas - len(pinned_live))]
        else:
            replicas = ranked[: resource.replicas]
        preferred = (
            pin.preferred_leader
            if pinned_live and pin.preferred_leader in pinned_live
            else None
        )
        if not replicas:
            continue
        # who currently leads? A node that rejoins after being deposed
        # still CLAIMS leaderlike in its (persistent) current state until
        # it demotes — with two live claimers the epoch ledger's recorded
        # leader is the truth, and trusting the stale claim instead would
        # flap leadership straight back to the deposed node (observed in
        # the failover chaos harness before this guard existed).
        claimers = [
            iid for iid in iids
            if current.get(iid, {}).get(partition) in _LEADERLIKE
        ]
        recorded_leader = (epochs.get(partition) or {}).get("leader")
        if not claimers:
            live_leader = None
        elif recorded_leader in claimers:
            live_leader = recorded_leader
        else:
            live_leader = claimers[0]
        # target leader: a pinned preferred leader wins (the move's
        # flip request — two-phase rules below still gate the actual
        # promotion); else sticky to the live leader if still placed;
        # else the best-ranked replica that's already serving; else rank-0
        if preferred is not None:
            target_leader = preferred
        elif live_leader in replicas:
            target_leader = live_leader
        else:
            serving = [
                iid for iid in replicas
                if current.get(iid, {}).get(partition) in
                (_FOLLOWERLIKE | _LEADERLIKE)
            ]
            target_leader = serving[0] if serving else replicas[0]
        # two-phase handoff: demote first, promote when no live leader
        promote_ok = live_leader is None or live_leader == target_leader
        rec = epochs.setdefault(partition, {"epoch": 0, "leader": None})
        if promote_ok and rec.get("leader") != target_leader:
            # leadership is moving NOW: mint the new leader's epoch so
            # every assignment written this pass already carries it
            rec["epoch"] = int(rec.get("epoch", 0)) + 1
            rec["leader"] = target_leader
            changed.add(partition)
        epoch = int(rec.get("epoch", 0))
        # followers need the upstream (the acting leader while handoff
        # is in flight, else the target leader)
        upstream_iid = live_leader or target_leader
        upstream_info = instances.get(upstream_iid)
        upstream = (
            f"{upstream_info.host}:{upstream_info.repl_port}"
            if upstream_info else None
        )
        for iid in replicas:
            if iid == target_leader and promote_ok:
                state: str = leader_state
                up = None
            else:
                # includes a demote-in-flight target leader: it stays a
                # follower of the acting leader until promote_ok
                state = follower_state
                up = upstream if upstream_iid != iid else None
            per_instance[iid][partition] = PartitionAssignment(
                state, up, epoch)
    return changed


class Controller:
    def __init__(
        self,
        coord_host: str,
        coord_port: int,
        cluster: str,
        controller_id: str,
        reconcile_interval: float = 2.0,
        coord_fallbacks: Optional[List[Tuple[str, int]]] = None,
    ):
        self.cluster = cluster
        self.controller_id = controller_id
        self.coord = CoordinatorClient(coord_host, coord_port,
                                       fallbacks=coord_fallbacks)
        self._path = lambda *p: cluster_path(cluster, *p)
        self._interval = reconcile_interval
        self._stop = threading.Event()
        self._kick = threading.Event()
        self._is_leader = False
        # reconcile passes completed while leader — the chaos harness's
        # "shard-map convergence within a bounded number of controller
        # passes" invariant reads this
        self.passes = 0
        self._thread = threading.Thread(
            target=self._run, name=f"controller-{controller_id}", daemon=True
        )
        self._thread.start()
        # wake on membership / state / resource changes
        self._watches = [
            self.coord.watch(self._path("instances"), self._on_change),
            self.coord.watch(self._path("currentstates"), self._on_change),
            self.coord.watch(self._path("resources"), self._on_change),
            # a shard move's pin write must wake the reconcile loop
            # immediately — the cutover window is the interval between
            # the pin landing and the flip completing
            self.coord.watch(self._path("placements"), self._on_change),
            # a split's activation re-enumerates the segment's shards:
            # the children need assignments (and the parent needs to
            # retire) on the next pass, not an interval later
            self.coord.watch(self._path("splits"), self._on_change),
        ]

    def _on_change(self, _snap) -> None:
        self._kick.set()

    @property
    def is_leader(self) -> bool:
        return self._is_leader

    # ------------------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                # Re-validate leadership EVERY pass: a session expiry hands
                # leadership to a peer, and a stale latched flag would leave
                # two controllers writing conflicting assignments.
                current = self.coord.current_leader(self._path("controller"))
                if current is None:
                    self.coord.elect_leader(
                        self._path("controller"), self.controller_id
                    )
                    current = self.coord.current_leader(
                        self._path("controller")
                    )
                self._is_leader = current == self.controller_id
                if self._is_leader:
                    self.reconcile()
            except Exception:
                log.exception("controller loop error")
            self._kick.wait(self._interval)
            self._kick.clear()

    # ------------------------------------------------------------------

    def reconcile(self) -> None:
        """One pass: recompute and publish assignments for every resource.

        Ordering matters for fencing: epoch-ledger records changed by
        this pass are persisted BEFORE the stamped assignments are
        published — a controller crash between the two steps leaves a
        minted-but-unpublished epoch, which the next pass (any
        controller) re-reads and re-publishes without a double bump."""
        instances = self._live_instances()
        current = self._current_states()
        epochs = self._load_epochs()
        pins = self._load_pins()
        splits = self._load_splits()
        per_instance: Dict[str, Dict[str, PartitionAssignment]] = {
            iid: {} for iid in instances
        }
        changed: Set[str] = set()
        for seg in self.coord.list(self._path("resources")):
            raw = self.coord.get_or_none(self._path("resources", seg))
            if raw is None:
                continue
            resource = ResourceDef.decode(raw)
            changed |= assign_resource(
                resource, instances, current, per_instance, epochs,
                pins=pins, splits=splits)
        for partition in sorted(changed):
            mine = epochs[partition]
            merged = self._persist_epoch(partition, mine)
            if merged is None:
                continue  # our record landed
            # A peer controller outran us on the ledger. If it minted the
            # SAME record we did, the race was harmless — publish. If it
            # recorded a DIFFERENT leader (or a further epoch), publishing
            # our assignments would promote a second leader under (or
            # hand the winning fencing token to) the wrong node — the
            # split brain the ledger exists to prevent. Abort the pass;
            # the next one recomputes from the merged record, and the
            # recorded-leader preference converges both controllers.
            if (int(merged.get("epoch", 0)) == int(mine["epoch"])
                    and merged.get("leader") == mine.get("leader")):
                continue
            log.warning(
                "epoch ledger conflict on %s: ours %s vs persisted %s — "
                "deferring this reconcile pass", partition, mine, merged)
            return
        for iid, assignments in per_instance.items():
            path = self._path("assignments", iid)
            encoded = encode_assignments(assignments)
            existing = self.coord.get_or_none(path)
            if existing != encoded:
                # the control plane touching durable state: a tripped
                # fault aborts this pass mid-publish — the next pass
                # must converge from the partial write
                fp.hit("controller.assign")
                self.coord.put(path, encoded)
        self.passes += 1

    # -- fencing-epoch ledger ---------------------------------------------

    def _load_pins(self) -> Dict[str, PlacementPin]:
        """Placement pins written by live shard moves — the rendezvous
        override assign_resource honors."""
        out: Dict[str, PlacementPin] = {}
        for p in self.coord.list(self._path("placements")):
            pin = PlacementPin.decode(
                self.coord.get_or_none(self._path("placements", p)))
            if pin is not None and pin.replicas:
                out[p] = pin
        return out

    def _load_splits(self) -> List[SplitRecord]:
        """ACTIVE shard-split records — the routing truth that swaps
        split parents for their range children in assignment."""
        out: List[SplitRecord] = []
        for p in self.coord.list(self._path("splits")):
            rec = SplitRecord.decode(
                self.coord.get_or_none(self._path("splits", p)))
            if rec is not None and rec.phase == "active":
                out.append(rec)
        return out

    def _load_epochs(self) -> Dict[str, Dict]:
        out: Dict[str, Dict] = {}
        for p in self.coord.list(self._path("epochs")):
            raw = self.coord.get_or_none(self._path("epochs", p))
            if not raw:
                continue
            try:
                rec = json.loads(bytes(raw).decode())
            except (ValueError, UnicodeDecodeError):
                continue
            out[p] = {"epoch": int(rec.get("epoch", 0)),
                      "leader": rec.get("leader")}
        return out

    def _persist_epoch(self, partition: str,
                       rec: Dict) -> Optional[Dict]:
        """Version-CAS the ledger record in, max-merging against
        concurrent writers (a deposed-but-racing peer controller must
        never regress an epoch — last-write-wins here would undo the
        very fencing the ledger exists for). Returns the winning record
        when a peer's beats ours, None when OUR record landed; RAISES
        when the write could not land at all, so the caller never
        publishes assignments stamped with a minted-but-unpersisted
        epoch."""
        from ..rpc.errors import RpcApplicationError

        path = self._path("epochs", partition)
        payload = json.dumps(rec).encode()
        last_exc: Optional[Exception] = None
        for _ in range(4):
            try:
                try:
                    existing_raw, version = self.coord.get(path)
                except RpcApplicationError as e:
                    if e.code != "NO_NODE":
                        raise
                    existing_raw, version = None, None
                if existing_raw is not None:
                    try:
                        existing = json.loads(bytes(existing_raw).decode())
                    except (ValueError, UnicodeDecodeError):
                        existing = {"epoch": 0, "leader": None}
                    if int(existing.get("epoch", 0)) >= int(rec["epoch"]):
                        return {"epoch": int(existing.get("epoch", 0)),
                                "leader": existing.get("leader")}
                    self.coord.set(path, payload,
                                   expected_version=version)
                else:
                    self.coord.create(path, payload)
                return None
            except RpcApplicationError as e:
                if e.code in ("BAD_VERSION", "NODE_EXISTS", "NO_NODE"):
                    last_exc = e
                    continue  # lost the CAS race: re-read and max-merge
                last_exc = e
                time.sleep(0.05)
            except Exception as e:
                last_exc = e
                time.sleep(0.05)
        raise RuntimeError(
            f"epoch ledger write for {partition} failed: {last_exc!r}")

    # ------------------------------------------------------------------

    def _live_instances(self) -> Dict[str, InstanceInfo]:
        out = {}
        for iid in self.coord.list(self._path("instances")):
            raw = self.coord.get_or_none(self._path("instances", iid))
            if raw:
                out[iid] = InstanceInfo.decode(raw)
        return out

    def _current_states(self) -> Dict[str, Dict[str, str]]:
        out = {}
        for iid in self.coord.list(self._path("currentstates")):
            out[iid] = decode_states(
                self.coord.get_or_none(self._path("currentstates", iid))
            )
        return out

    # -- admin API -------------------------------------------------------

    def add_resource(self, resource: ResourceDef) -> None:
        self.coord.put(
            self._path("resources", resource.segment), resource.encode()
        )
        self._kick.set()

    def remove_resource(self, segment: str) -> None:
        self.coord.delete_if_exists(self._path("resources", segment))
        self._kick.set()

    def stop(self) -> None:
        self._stop.set()
        self._kick.set()
        for w in self._watches:
            w.set()
        self._thread.join(timeout=5.0)
        self.coord.close()
