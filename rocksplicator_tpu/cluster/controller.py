"""Controller: leader-elected assignment computation.

Reference: the Helix controller (external to the reference repo but the
brain of its control plane). Responsibilities reproduced:
- watch live instances / resources / current states;
- compute stable partition placement (rendezvous hashing keeps most
  placements unchanged when membership changes);
- leader handoff in two phases (demote-then-promote) so participants'
  no-live-leader guard holds;
- write per-instance assignments the participants converge on;
- reconcile periodically to self-heal.
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..utils.segment_utils import segment_to_db_name, db_name_to_partition_name
from .coordinator import CoordinatorClient
from .model import (
    FOLLOWER,
    LEADER,
    InstanceInfo,
    PartitionAssignment,
    ResourceDef,
    cluster_path,
    decode_states,
    encode_assignments,
)

log = logging.getLogger(__name__)

_LEADERLIKE = {"LEADER", "MASTER"}
_FOLLOWERLIKE = {"FOLLOWER", "SLAVE"}


def _rendezvous(partition: str, instance_id: str) -> int:
    h = hashlib.blake2b(
        f"{partition}|{instance_id}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(h, "big")


class Controller:
    def __init__(
        self,
        coord_host: str,
        coord_port: int,
        cluster: str,
        controller_id: str,
        reconcile_interval: float = 2.0,
        coord_fallbacks: Optional[List[Tuple[str, int]]] = None,
    ):
        self.cluster = cluster
        self.controller_id = controller_id
        self.coord = CoordinatorClient(coord_host, coord_port,
                                       fallbacks=coord_fallbacks)
        self._path = lambda *p: cluster_path(cluster, *p)
        self._interval = reconcile_interval
        self._stop = threading.Event()
        self._kick = threading.Event()
        self._is_leader = False
        self._thread = threading.Thread(
            target=self._run, name=f"controller-{controller_id}", daemon=True
        )
        self._thread.start()
        # wake on membership / state / resource changes
        self._watches = [
            self.coord.watch(self._path("instances"), self._on_change),
            self.coord.watch(self._path("currentstates"), self._on_change),
            self.coord.watch(self._path("resources"), self._on_change),
        ]

    def _on_change(self, _snap) -> None:
        self._kick.set()

    @property
    def is_leader(self) -> bool:
        return self._is_leader

    # ------------------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                # Re-validate leadership EVERY pass: a session expiry hands
                # leadership to a peer, and a stale latched flag would leave
                # two controllers writing conflicting assignments.
                current = self.coord.current_leader(self._path("controller"))
                if current is None:
                    self.coord.elect_leader(
                        self._path("controller"), self.controller_id
                    )
                    current = self.coord.current_leader(
                        self._path("controller")
                    )
                self._is_leader = current == self.controller_id
                if self._is_leader:
                    self.reconcile()
            except Exception:
                log.exception("controller loop error")
            self._kick.wait(self._interval)
            self._kick.clear()

    # ------------------------------------------------------------------

    def reconcile(self) -> None:
        """One pass: recompute and publish assignments for every resource."""
        instances = self._live_instances()
        current = self._current_states()
        per_instance: Dict[str, Dict[str, PartitionAssignment]] = {
            iid: {} for iid in instances
        }
        for seg in self.coord.list(self._path("resources")):
            raw = self.coord.get_or_none(self._path("resources", seg))
            if raw is None:
                continue
            resource = ResourceDef.decode(raw)
            self._assign_resource(resource, instances, current, per_instance)
        for iid, assignments in per_instance.items():
            path = self._path("assignments", iid)
            encoded = encode_assignments(assignments)
            existing = self.coord.get_or_none(path)
            if existing != encoded:
                self.coord.put(path, encoded)

    def _assign_resource(
        self,
        resource: ResourceDef,
        instances: Dict[str, InstanceInfo],
        current: Dict[str, Dict[str, str]],
        per_instance: Dict[str, Dict[str, PartitionAssignment]],
    ) -> None:
        leader_state, follower_state = self._state_names(resource.state_model)
        iids = sorted(instances)
        if not iids:
            return
        for shard in range(resource.num_shards):
            partition = db_name_to_partition_name(
                segment_to_db_name(resource.segment, shard)
            )
            ranked = sorted(
                iids, key=lambda iid: _rendezvous(partition, iid),
                reverse=True,
            )
            replicas = ranked[: resource.replicas]
            if not replicas:
                continue
            # who currently leads?
            live_leader = None
            for iid in iids:
                if current.get(iid, {}).get(partition) in _LEADERLIKE:
                    live_leader = iid
                    break
            # target leader: sticky to the live leader if still placed;
            # else the best-ranked replica that's already serving; else rank-0
            if live_leader in replicas:
                target_leader = live_leader
            else:
                serving = [
                    iid for iid in replicas
                    if current.get(iid, {}).get(partition) in
                    (_FOLLOWERLIKE | _LEADERLIKE)
                ]
                target_leader = serving[0] if serving else replicas[0]
            # two-phase handoff: demote first, promote when no live leader
            promote_ok = live_leader is None or live_leader == target_leader
            # followers need the upstream (the acting leader while handoff
            # is in flight, else the target leader)
            upstream_iid = live_leader or target_leader
            upstream_info = instances.get(upstream_iid)
            upstream = (
                f"{upstream_info.host}:{upstream_info.repl_port}"
                if upstream_info else None
            )
            for iid in replicas:
                if iid == target_leader and promote_ok:
                    state: str = leader_state
                    up = None
                else:
                    # includes a demote-in-flight target leader: it stays a
                    # follower of the acting leader until promote_ok
                    state = follower_state
                    up = upstream if upstream_iid != iid else None
                per_instance[iid][partition] = PartitionAssignment(state, up)

    @staticmethod
    def _state_names(state_model: str) -> Tuple[str, str]:
        if state_model == "MasterSlave":
            return "MASTER", "SLAVE"
        if state_model in ("OnlineOffline", "Cache", "Bootstrap"):
            return "ONLINE", "ONLINE"
        if state_model == "CdcLeaderStandby":
            return "LEADER", "STANDBY"
        return LEADER, FOLLOWER

    # ------------------------------------------------------------------

    def _live_instances(self) -> Dict[str, InstanceInfo]:
        out = {}
        for iid in self.coord.list(self._path("instances")):
            raw = self.coord.get_or_none(self._path("instances", iid))
            if raw:
                out[iid] = InstanceInfo.decode(raw)
        return out

    def _current_states(self) -> Dict[str, Dict[str, str]]:
        out = {}
        for iid in self.coord.list(self._path("currentstates")):
            out[iid] = decode_states(
                self.coord.get_or_none(self._path("currentstates", iid))
            )
        return out

    # -- admin API -------------------------------------------------------

    def add_resource(self, resource: ResourceDef) -> None:
        self.coord.put(
            self._path("resources", resource.segment), resource.encode()
        )
        self._kick.set()

    def remove_resource(self, segment: str) -> None:
        self.coord.delete_if_exists(self._path("resources", segment))
        self._kick.set()

    def stop(self) -> None:
        self._stop.set()
        self._kick.set()
        for w in self._watches:
            w.set()
        self._thread.join(timeout=5.0)
        self.coord.close()
