"""Cluster-wide stats aggregation: scrape every replica, merge exactly.

Reference: the rocksplicator deployment fans per-host common/stats into
statsd and aggregates fleet-wide in the Helix spectator's dashboards
(PAPER.md L1/L4). Here the spectator itself owns the loop: it already
watches the external view, so it knows every replica's replication
endpoint from the shard map it publishes — the scrape pulls each node's
``stats`` RPC (``Stats.export_state``), and the merge is EXACT:

- counters merge by summation (totals and 1-minute rates);
- histograms merge losslessly — every process buckets with the same
  log-spaced edges (utils/stats._Histogram), so a cross-replica merge
  is a per-bucket vector add (``merge_histogram_states``), and fleet
  percentiles carry exactly the per-replica bucket resolution (~9%),
  never resampling error on top;
- gauges keep per-replica identity and aggregate per shard (max lag is
  a max, not a mean — the rebalancer cares about the worst replica).

The aggregate feeds ``/cluster_stats`` and the macro-bench artifact:
per-shard hot-spot ranking by read/write rate, per-shard max
replication lag / ack-window occupancy / compaction debt, and fleet
p50/p99 per op class — the input shape the per-shard-load rebalancer
and the workload-adaptive compaction scheduler (ROADMAP) consume.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, Iterable, List, Optional, Tuple

from ..rpc.errors import RpcError
from ..utils.retry_policy import RetryPolicy, retry_call, seeded_rng
from ..utils.segment_utils import segment_to_db_name
from ..utils.stats import (Stats, histogram_state_percentile,
                           merge_histogram_states, split_tagged)

log = logging.getLogger(__name__)

Endpoint = Tuple[str, int]

# one quick retry per endpoint per scrape pass: a node mid-restart is
# skipped (and counted) rather than stalling the whole pass
_SCRAPE_RETRY = RetryPolicy(max_attempts=2, base_delay=0.1, max_delay=0.5)

# histogram families reported per op class in the fleet summary
_LATENCY_FAMILIES = ("reads.latency_ms", "writes.latency_ms")

# per-tenant admission telemetry (round 19 tail armor): served/shed
# counters and the server-side latency histogram, tagged tenant=<t>
_TENANT_FAMILY = "rpc.tenant_ms"


def endpoints_from_shard_map(shard_map: Dict) -> Tuple[
        List[Endpoint], Dict[str, List[Endpoint]]]:
    """(all replica replication endpoints, db_name -> its replicas).
    Shard-map host keys are ``host:admin_port:az:repl_port`` (the 4th
    field is the replication RPC port — config_generator.py)."""
    endpoints: List[Endpoint] = []
    seen = set()
    per_db: Dict[str, List[Endpoint]] = {}
    for segment, seg_map in (shard_map or {}).items():
        for host_key, shards in seg_map.items():
            if host_key == "num_shards":
                continue
            parts = host_key.split(":")
            if len(parts) < 4:
                continue
            ep = (parts[0], int(parts[3]))
            if ep not in seen:
                seen.add(ep)
                endpoints.append(ep)
            for entry in shards:
                shard_id = int(entry.split(":", 1)[0])
                db = segment_to_db_name(segment, shard_id)
                per_db.setdefault(db, [])
                if ep not in per_db[db]:
                    per_db[db].append(ep)
    return endpoints, per_db


class ClusterStatsAggregator:
    """Scrapes replica ``stats`` RPCs and merges them into one
    cluster-wide view. Owns no thread — the Spectator's scrape loop (or
    a bench doing a one-shot pull) drives it."""

    def __init__(self, pool=None, ioloop=None,
                 rpc_timeout: float = 3.0):
        from ..rpc.client_pool import RpcClientPool
        from ..rpc.ioloop import IoLoop

        self._ioloop = ioloop or IoLoop.default()
        self._owns_pool = pool is None
        self._pool = pool or RpcClientPool()
        self._rpc_timeout = rpc_timeout
        self._rng = seeded_rng()
        self._stats = Stats.get()

    def close(self) -> None:
        """Release the scrape connections — only when this aggregator
        created its own pool (callers sharing a pool keep theirs)."""
        if not self._owns_pool:
            return
        try:
            self._ioloop.run_sync(self._pool.close(), timeout=5)
        except Exception:  # pragma: no cover - teardown best-effort
            log.debug("aggregator pool close failed", exc_info=True)

    # -- scrape -----------------------------------------------------------

    def scrape(self, endpoints: Iterable[Endpoint]
               ) -> Dict[str, Dict]:
        """Pull ``stats`` from every endpoint; unreachable nodes are
        skipped and counted (``spectator.scrape_errors``). Returns
        ``{"host:port": export_state}`` for the nodes that answered."""
        out: Dict[str, Dict] = {}
        for host, port in endpoints:
            key = f"{host}:{port}"
            try:
                out[key] = retry_call(
                    lambda h=host, p=port: self._scrape_one(h, p),
                    policy=_SCRAPE_RETRY,
                    classify=lambda e: isinstance(e, (RpcError, OSError,
                                                      TimeoutError)),
                    op="spectator.scrape",
                    rng=self._rng,
                )
                self._stats.incr("spectator.scrapes")
            except Exception as e:
                self._stats.incr("spectator.scrape_errors")
                log.warning("stats scrape of %s failed: %r", key, e)
        return out

    def _scrape_one(self, host: str, port: int) -> Dict:
        async def go():
            return await self._pool.call(host, port, "stats", {},
                                         timeout=self._rpc_timeout)

        return self._ioloop.run_sync(go(), timeout=self._rpc_timeout + 2)

    # -- merge ------------------------------------------------------------

    @staticmethod
    def aggregate(per_endpoint: Dict[str, Dict],
                  per_db_endpoints: Optional[Dict[str, List[Endpoint]]]
                  = None,
                  hot_limit: int = 16) -> Dict:
        """Merge scraped states into the `/cluster_stats` document."""
        shard: Dict[str, Dict] = {}

        def shard_rec(db: str) -> Dict:
            return shard.setdefault(db, {
                "read_rate_1m": 0.0, "write_rate_1m": 0.0,
                "reads_total": 0.0, "writes_total": 0.0,
                "max_applied_seq_lag": 0.0, "ack_window_depth": 0.0,
                "compaction_debt_bytes": 0.0,
                "compaction_peak_bytes_materialized": 0.0,
                "replicas_reporting": 0,
                "roles": {},
            })

        hist_by_family_op: Dict[Tuple[str, str], List[Dict]] = {}
        counters_total: Dict[str, float] = {}
        debt_by_ep_db: Dict[Tuple[str, str], float] = {}
        tenants: Dict[str, Dict] = {}
        tenant_hists: Dict[str, List[Dict]] = {}

        def tenant_rec(t: str) -> Dict:
            return tenants.setdefault(t, {
                "served_total": 0.0, "served_rate_1m": 0.0,
                "shed_total": 0.0, "shed_rate_1m": 0.0,
            })

        # In-process topologies (chaos/cluster tests) colocate several
        # replicators in ONE process sharing ONE Stats registry: two
        # endpoints of the same pid export identical registries, so the
        # registry-wide parts (counters/gauges/metrics) are consumed
        # once per process; the per-endpoint shard_roles — each
        # replicator's OWN db map — are consumed per endpoint. Cross-
        # process deployments have one endpoint per pid and are
        # unaffected.
        seen_processes = set()
        for ep in sorted(per_endpoint):
            state = per_endpoint[ep]
            proc = state.get("process") or ep
            dup_registry = proc in seen_processes
            seen_processes.add(proc)
            for db, role in (state.get("shard_roles") or {}).items():
                shard_rec(db)["roles"][role] = (
                    shard_rec(db)["roles"].get(role, 0) + 1)
            if dup_registry:
                continue
            for name, c in (state.get("counters") or {}).items():
                base, tags = split_tagged(name)
                counters_total[base] = (counters_total.get(base, 0.0)
                                        + float(c.get("total", 0.0)))
                db = tags.get("db")
                if db and base == "replicator.shard_reads":
                    rec = shard_rec(db)
                    rec["read_rate_1m"] += float(c.get("rate_1m", 0.0))
                    rec["reads_total"] += float(c.get("total", 0.0))
                elif db and base == "replicator.shard_writes":
                    rec = shard_rec(db)
                    rec["write_rate_1m"] += float(c.get("rate_1m", 0.0))
                    rec["writes_total"] += float(c.get("total", 0.0))
                elif base == "rpc.tenant_served" and tags.get("tenant"):
                    rec = tenant_rec(tags["tenant"])
                    rec["served_total"] += float(c.get("total", 0.0))
                    rec["served_rate_1m"] += float(c.get("rate_1m", 0.0))
                elif base == "rpc.tenant_shed" and tags.get("tenant"):
                    rec = tenant_rec(tags["tenant"])
                    rec["shed_total"] += float(c.get("total", 0.0))
                    rec["shed_rate_1m"] += float(c.get("rate_1m", 0.0))
            for name, value in (state.get("gauges") or {}).items():
                base, tags = split_tagged(name)
                db = tags.get("db")
                if not db:
                    continue
                if base == "replicator.applied_seq_lag":
                    rec = shard_rec(db)
                    rec["max_applied_seq_lag"] = max(
                        rec["max_applied_seq_lag"], float(value))
                    rec["replicas_reporting"] += 1
                elif base == "replicator.ack_window_depth":
                    shard_rec(db)["ack_window_depth"] = max(
                        shard_rec(db)["ack_window_depth"], float(value))
                elif base == "storage.compaction_debt_bytes":
                    k = (ep, db)
                    debt_by_ep_db[k] = (debt_by_ep_db.get(k, 0.0)
                                        + float(value))
                elif base == "compaction.peak_bytes_materialized":
                    # worst replica's compaction memory high-water —
                    # the fleet view of the streaming-merge ceiling
                    rec = shard_rec(db)
                    rec["compaction_peak_bytes_materialized"] = max(
                        rec["compaction_peak_bytes_materialized"],
                        float(value))
            for name, st in (state.get("metrics") or {}).items():
                base, tags = split_tagged(name)
                if base in _LATENCY_FAMILIES:
                    op = tags.get("op", "?")
                    hist_by_family_op.setdefault((base, op), []).append(st)
                elif base == _TENANT_FAMILY and tags.get("tenant"):
                    tenant_hists.setdefault(
                        tags["tenant"], []).append(st)

        # worst-replica compaction debt per shard (summed over levels
        # within one replica, max across replicas)
        for (ep, db), debt in debt_by_ep_db.items():
            shard_rec(db)["compaction_debt_bytes"] = max(
                shard_rec(db)["compaction_debt_bytes"], debt)

        # shard-map view of how many replicas SHOULD be reporting — a
        # shard whose reporting count falls short names its gap here
        if per_db_endpoints:
            for db, eps in per_db_endpoints.items():
                shard_rec(db)["replicas_expected"] = len(eps)

        fleet_latency: Dict[str, Dict] = {}
        for (family, op), states in sorted(hist_by_family_op.items()):
            merged = merge_histogram_states(states)
            if not merged["count"]:
                continue
            fleet_latency.setdefault(family, {})[op] = {
                "count": merged["count"],
                "mean_ms": round(merged["sum"] / merged["count"], 3),
                "p50_ms": round(
                    histogram_state_percentile(merged, 50), 3),
                "p99_ms": round(
                    histogram_state_percentile(merged, 99), 3),
            }

        # per-tenant fleet view (round 19): served/shed rollups plus the
        # same exact log-bucket latency merge the op-class families get
        for t, states in sorted(tenant_hists.items()):
            merged = merge_histogram_states(states)
            if not merged["count"]:
                continue
            tenant_rec(t)["latency_ms"] = {
                "count": merged["count"],
                "p50_ms": round(
                    histogram_state_percentile(merged, 50), 3),
                "p99_ms": round(
                    histogram_state_percentile(merged, 99), 3),
                "p999_ms": round(
                    histogram_state_percentile(merged, 99.9), 3),
            }

        hot = sorted(
            shard.items(),
            key=lambda kv: kv[1]["read_rate_1m"] + kv[1]["write_rate_1m"],
            reverse=True,
        )
        return {
            "time": time.time(),
            "replicas_scraped": len(per_endpoint),
            "replicas": sorted(per_endpoint),
            "per_shard": shard,
            "hot_shards": [
                {"db": db,
                 "read_rate_1m": round(rec["read_rate_1m"], 1),
                 "write_rate_1m": round(rec["write_rate_1m"], 1)}
                for db, rec in hot[:hot_limit]
            ],
            "max_replication_lag": max(
                (rec["max_applied_seq_lag"] for rec in shard.values()),
                default=0.0),
            "fleet_latency_ms": fleet_latency,
            "per_tenant": tenants,
            "counters_total": {
                k: v for k, v in sorted(counters_total.items())
                if k.startswith(("replicator.", "reads.", "storage.",
                                 "rpc."))
            },
            "histogram_merge": "exact-log-bucket",
        }

    def scrape_and_aggregate(self, endpoints: Iterable[Endpoint],
                             per_db_endpoints: Optional[
                                 Dict[str, List[Endpoint]]] = None) -> Dict:
        states = self.scrape(endpoints)
        agg = self.aggregate(states, per_db_endpoints)
        agg["scrape_errors_total"] = self._stats.get_counter(
            "spectator.scrape_errors")
        return agg
