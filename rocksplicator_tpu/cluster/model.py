"""Cluster data model: coordinator path layout + JSON payloads.

Path conventions (the ZK tree equivalent):

    /clusters/<cluster>/instances/<instance_id>        ephemeral instance info
    /clusters/<cluster>/resources/<segment>            resource definition
    /clusters/<cluster>/assignments/<instance_id>      controller → participant
    /clusters/<cluster>/currentstates/<instance_id>    participant → world
    /clusters/<cluster>/partitionstate/<partition>     leader seq checkpoints
    /clusters/<cluster>/epochs/<partition>             fencing epoch ledger
    /clusters/<cluster>/locks/partitions/<partition>   per-partition mutex
    /clusters/<cluster>/controller                     leader election
    /clusters/<cluster>/events/<partition>             leader-handoff history
    /clusters/<cluster>/config/<segment>               resource configs
    /clusters/<cluster>/tasks/queue, /tasks/results    task framework
    /clusters/<cluster>/placements/<partition>         placement pins (moves)
    /clusters/<cluster>/moves/<partition>              live shard-move ledger
    /clusters/<cluster>/moves_summary                  move counters (spectator)
    /clusters/<cluster>/splits/<partition>             shard-split ledger (routing truth once active)
    /clusters/<cluster>/splits_summary                 split counters (spectator)
    /clusters/<cluster>/rebalancer                     rebalancer pause flag + status
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

# states (LeaderFollower model; MasterSlave aliases map onto these)
OFFLINE = "OFFLINE"
FOLLOWER = "FOLLOWER"
LEADER = "LEADER"
ONLINE = "ONLINE"      # OnlineOffline / Cache models
STANDBY = "STANDBY"    # CdcLeaderStandby
DROPPED = "DROPPED"
ERROR = "ERROR"


def cluster_path(cluster: str, *parts: str) -> str:
    return "/".join(["", "clusters", cluster, *parts])


@dataclass
class InstanceInfo:
    instance_id: str
    host: str
    admin_port: int
    repl_port: int
    az: str = ""

    def encode(self) -> bytes:
        return json.dumps(asdict(self)).encode()

    @classmethod
    def decode(cls, raw: bytes) -> "InstanceInfo":
        return cls(**json.loads(bytes(raw).decode()))


@dataclass
class ResourceDef:
    segment: str
    num_shards: int
    replicas: int = 3
    state_model: str = "LeaderFollower"

    def encode(self) -> bytes:
        return json.dumps(asdict(self)).encode()

    @classmethod
    def decode(cls, raw: bytes) -> "ResourceDef":
        return cls(**json.loads(bytes(raw).decode()))


@dataclass
class PlacementPin:
    """One partition's pinned placement — the live-resharding override
    over rendezvous hashing.

    A shard move (cluster/shard_move.py) flips placement by writing a
    pin: ``replicas`` is the exact instance list that should host the
    partition, ``preferred_leader`` (optional) names which of them the
    controller should drive leadership to — through the SAME two-phase
    demote-then-promote + epoch-mint machinery a failover uses, so a
    pinned flip is epoch-stamped and fencing-safe by construction.
    Dead pinned instances are filtered at assignment time; an entirely
    dead pin falls back to rendezvous placement so a pin can never
    un-serve a partition. ``move_id`` records which move wrote it (audit
    trail; stale-pin sweeps)."""

    replicas: List[str]
    preferred_leader: Optional[str] = None
    move_id: str = ""

    def encode(self) -> bytes:
        return json.dumps(asdict(self)).encode()

    @classmethod
    def decode(cls, raw: Optional[bytes]) -> Optional["PlacementPin"]:
        if not raw:
            return None
        try:
            d = json.loads(bytes(raw).decode())
        except (ValueError, UnicodeDecodeError):
            return None
        return cls(replicas=list(d.get("replicas") or []),
                   preferred_leader=d.get("preferred_leader"),
                   move_id=d.get("move_id", ""))


@dataclass
class SplitRecord:
    """One hot shard's range split — durable at
    ``/clusters/<cluster>/splits/<parent_partition>``.

    A split carves a parent hash slot into two range-partitioned VIRTUAL
    child shards: the hash map (``num_shards``) is untouched, so every
    existing key still hashes to the parent slot; routers then resolve
    key → child by comparing the key against ``split_key`` (children may
    split again — resolution chases records transitively). Child shard
    ids are allocated ABOVE the resource's hash range so they can never
    collide with a hashed slot.

    Like a move record, the split is a resumable step machine: ``phase``
    is written BEFORE the phase's side effects run, so a crashed driver
    resumes idempotently. Phases mirror the move ledger
    (planned → snapshot → restore → catchup → cutover) and terminate at
    ``active`` — unlike a move record, an ACTIVE split record is never
    deleted: it IS the routing truth the shard map's ``__splits__``
    section and the controller's child-partition enumeration are
    generated from. Abort is legal strictly pre-cutover (children are
    invisible until the cutover publishes them).

    ``low_shard`` serves keys < ``split_key``; ``high_shard`` serves
    keys >= ``split_key``. ``split_key`` is hex-encoded (keys are
    arbitrary bytes; JSON can't carry them raw). ``epoch`` is the
    children's starting fencing epoch (parent epoch + 1), minted at
    cutover so a deposed parent leader can never ack into a child's
    lineage."""

    segment: str
    parent_shard: int
    split_key: str  # hex-encoded boundary key
    low_shard: int
    high_shard: int
    phase: str = "planned"
    split_id: str = ""
    epoch: int = 0
    # the copied-out child: which child shard moved away and where its
    # leader landed; the low child stays on the parent's replica set
    moved_child: int = -1
    target_instance: str = ""
    # step-machine bookkeeping (same shape as MoveRecord; the routing
    # consumers above ignore these)
    store_uri: str = ""
    snapshot_prefix: str = ""
    snapshot_seq: int = 0
    catchup_lag: int = -1
    started_ms: int = 0
    updated_ms: int = 0

    PHASES = ("planned", "snapshot", "restore", "catchup", "cutover",
              "active")

    @property
    def split_key_bytes(self) -> bytes:
        return bytes.fromhex(self.split_key)

    def child_shards(self) -> List[int]:
        return [self.low_shard, self.high_shard]

    def encode(self) -> bytes:
        return json.dumps(asdict(self)).encode()

    @classmethod
    def decode(cls, raw: Optional[bytes]) -> Optional["SplitRecord"]:
        if not raw:
            return None
        try:
            d = json.loads(bytes(raw).decode())
        except (ValueError, UnicodeDecodeError):
            return None
        try:
            return cls(**d)
        except TypeError:
            return None


@dataclass
class PartitionAssignment:
    """One partition's target on one instance.

    ``epoch`` is the partition's monotonic fencing epoch: the controller
    bumps it exactly when leadership moves (see controller.py's epoch
    ledger at ``/clusters/<cluster>/epochs/<partition>``) and stamps it
    on every assignment. Participants thread it into the data plane
    (``change_db_role_and_upstream``/``add_db``), where the ReplicatedDB
    attaches it to every replicate/ack frame — followers and the ack
    path reject stale-epoch traffic, so a deposed leader can never ack a
    write after the new leader's epoch is visible (the no-split-brain
    invariant the chaos harness holds)."""

    state: str
    upstream: Optional[str] = None  # "host:repl_port" of the leader
    epoch: int = 0

    def to_json(self) -> dict:
        return {"state": self.state, "upstream": self.upstream,
                "epoch": self.epoch}


def encode_assignments(assignments: Dict[str, PartitionAssignment]) -> bytes:
    return json.dumps({p: a.to_json() for p, a in assignments.items()}).encode()


def decode_assignments(raw: bytes) -> Dict[str, PartitionAssignment]:
    if not raw:
        return {}
    d = json.loads(bytes(raw).decode())
    return {p: PartitionAssignment(**v) for p, v in d.items()}


def encode_states(states: Dict[str, str]) -> bytes:
    return json.dumps(states).encode()


def decode_states(raw: Optional[bytes]) -> Dict[str, str]:
    if not raw:
        return {}
    return json.loads(bytes(raw).decode())
