"""State-model factories (reference: cluster_management state models)."""

from .base import StateModel, StateModelFactory, TransitionError
from .leader_follower import LeaderFollowerStateModelFactory
from .master_slave import MasterSlaveStateModelFactory
from .online_offline import OnlineOfflineStateModelFactory
from .cache import CacheStateModelFactory
from .bootstrap import BootstrapStateModelFactory
from .cdc_leader_standby import CdcLeaderStandbyStateModelFactory

FACTORIES = {
    "LeaderFollower": LeaderFollowerStateModelFactory,
    "MasterSlave": MasterSlaveStateModelFactory,
    "OnlineOffline": OnlineOfflineStateModelFactory,
    "Cache": CacheStateModelFactory,
    "Bootstrap": BootstrapStateModelFactory,
    "CdcLeaderStandby": CdcLeaderStandbyStateModelFactory,
}

__all__ = [
    "StateModel", "StateModelFactory", "TransitionError", "FACTORIES",
    "LeaderFollowerStateModelFactory", "MasterSlaveStateModelFactory",
    "OnlineOfflineStateModelFactory", "CacheStateModelFactory",
    "BootstrapStateModelFactory", "CdcLeaderStandbyStateModelFactory",
]
