"""Bootstrap — Online/Offline variant that bootstraps data via message
ingestion.

Reference: BootstrapStateModelFactory.java:277 — Offline→Online opens the
db and starts message ingestion (startMessageIngestion) from the resource's
configured topic; Online→Offline stops ingestion and closes.
"""

from __future__ import annotations

import logging

from ...utils.segment_utils import (
    db_name_to_segment,
    partition_name_to_db_name,
)
from ..model import DROPPED, OFFLINE, ONLINE
from .base import StateModel, StateModelFactory

log = logging.getLogger(__name__)


class BootstrapStateModel(StateModel):
    edges = [
        (OFFLINE, ONLINE),
        (ONLINE, OFFLINE),
        (OFFLINE, DROPPED),
    ]

    @property
    def db_name(self) -> str:
        return partition_name_to_db_name(self.partition)

    def on_become_online_from_offline(self) -> None:
        ctx = self.ctx
        ctx.admin.add_db(ctx.local_admin_addr, self.db_name, "NOOP")
        cfg = ctx.resource_config(db_name_to_segment(self.db_name))
        topic = cfg.get("kafka_topic")
        broker_path = cfg.get("kafka_broker_serverset_path", "")
        if topic:
            ctx.admin.call(
                ctx.local_admin_addr, "start_message_ingestion",
                db_name=self.db_name, topic_name=topic,
                kafka_broker_serverset_path=broker_path,
            )

    def on_become_offline_from_online(self) -> None:
        ctx = self.ctx
        try:
            ctx.admin.call(
                ctx.local_admin_addr, "stop_message_ingestion",
                db_name=self.db_name,
            )
        except Exception:
            log.debug("%s: no ingestion to stop", self.db_name)
        ctx.admin.close_db(ctx.local_admin_addr, self.db_name)

    def on_become_dropped_from_offline(self) -> None:
        try:
            self.ctx.admin.add_db(self.ctx.local_admin_addr, self.db_name, "NOOP")
        except Exception:
            pass
        self.ctx.admin.clear_db(
            self.ctx.local_admin_addr, self.db_name, reopen=False
        )


class BootstrapStateModelFactory(StateModelFactory):
    model_class = BootstrapStateModel
    name = "Bootstrap"
