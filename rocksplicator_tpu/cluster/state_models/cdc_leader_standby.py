"""CdcLeaderStandby — observer management for CDC nodes.

Reference: CdcLeaderStandbyStateModelFactory.java + CdcUtils.java:56-84 —
a LeaderStandby machine where becoming LEADER calls CdcAdmin addObserver
(pointing at the partition's current data-plane leader) and leaving calls
removeObserver. The CDC service itself is cdc_admin (admin/cdc.py here).
"""

from __future__ import annotations

import logging
from typing import Optional, Tuple

from ...utils.segment_utils import partition_name_to_db_name
from ..model import DROPPED, LEADER, OFFLINE, STANDBY
from .base import StateModel, StateModelFactory, TransitionError

log = logging.getLogger(__name__)


class CdcLeaderStandbyStateModel(StateModel):
    edges = [
        (OFFLINE, STANDBY),
        (STANDBY, LEADER),
        (LEADER, STANDBY),
        (STANDBY, OFFLINE),
        (OFFLINE, DROPPED),
    ]

    @property
    def db_name(self) -> str:
        return partition_name_to_db_name(self.partition)

    def _data_leader(self) -> Optional[Tuple[str, int]]:
        view = self.ctx.external_view(self.partition)
        instances = self.ctx.live_instances()
        for iid, state in view.items():
            if state in ("LEADER", "MASTER") and iid in instances:
                info = instances[iid]
                return (info.host, info.repl_port)
        return None

    def on_become_standby_from_offline(self) -> None:
        pass  # standby holds no observer

    def on_become_leader_from_standby(self) -> None:
        upstream = self._data_leader()
        if upstream is None:
            raise TransitionError(f"{self.partition}: no data-plane leader")
        self.ctx.admin.call(
            self.ctx.local_admin_addr, "add_observer",
            db_name=self.db_name,
            upstream_ip=upstream[0], upstream_port=upstream[1],
        )

    def on_become_standby_from_leader(self) -> None:
        try:
            self.ctx.admin.call(
                self.ctx.local_admin_addr, "remove_observer",
                db_name=self.db_name,
            )
        except Exception:
            log.debug("%s: no observer to remove", self.db_name)

    def on_become_offline_from_standby(self) -> None:
        pass

    def on_become_dropped_from_offline(self) -> None:
        pass


class CdcLeaderStandbyStateModelFactory(StateModelFactory):
    model_class = CdcLeaderStandbyStateModel
    name = "CdcLeaderStandby"
