"""Cache — trivial online/offline for cache nodes (no storage ops).

Reference: CacheStateModelFactory.java:99 — transitions are no-ops beyond
membership; the router simply includes/excludes the host.
"""

from __future__ import annotations

from ..model import DROPPED, OFFLINE, ONLINE
from .base import StateModel, StateModelFactory


class CacheStateModel(StateModel):
    edges = [
        (OFFLINE, ONLINE),
        (ONLINE, OFFLINE),
        (OFFLINE, DROPPED),
    ]

    def on_become_online_from_offline(self) -> None:
        pass

    def on_become_offline_from_online(self) -> None:
        pass

    def on_become_dropped_from_offline(self) -> None:
        pass


class CacheStateModelFactory(StateModelFactory):
    model_class = CacheStateModel
    name = "Cache"
