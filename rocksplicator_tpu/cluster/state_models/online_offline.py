"""OnlineOffline — read-only serving clusters.

Reference: OnlineOfflineStateModelFactory.java:168 — Offline→Online opens
the db standalone (no replication), Online→Offline closes it.
"""

from __future__ import annotations

from ...utils.segment_utils import partition_name_to_db_name
from ..model import DROPPED, OFFLINE, ONLINE
from .base import StateModel, StateModelFactory


class OnlineOfflineStateModel(StateModel):
    edges = [
        (OFFLINE, ONLINE),
        (ONLINE, OFFLINE),
        (OFFLINE, DROPPED),
    ]

    @property
    def db_name(self) -> str:
        return partition_name_to_db_name(self.partition)

    def on_become_online_from_offline(self) -> None:
        self.ctx.admin.add_db(self.ctx.local_admin_addr, self.db_name, "NOOP")

    def on_become_offline_from_online(self) -> None:
        self.ctx.admin.close_db(self.ctx.local_admin_addr, self.db_name)

    def on_become_dropped_from_offline(self) -> None:
        try:
            self.ctx.admin.add_db(self.ctx.local_admin_addr, self.db_name, "NOOP")
        except Exception:
            pass
        self.ctx.admin.clear_db(
            self.ctx.local_admin_addr, self.db_name, reopen=False
        )


class OnlineOfflineStateModelFactory(StateModelFactory):
    model_class = OnlineOfflineStateModel
    name = "OnlineOffline"
