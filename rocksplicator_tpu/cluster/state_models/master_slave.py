"""MasterSlave — the older naming of the LeaderFollower machine.

Reference: MasterSlaveStateModelFactory.java (669 LoC) — same algorithm
with MASTER/SLAVE state names. The admin plane accepts both role namings,
so this subclasses the LeaderFollower transitions under aliased states.
"""

from __future__ import annotations

from ..model import DROPPED, OFFLINE
from .base import StateModelFactory
from .leader_follower import LeaderFollowerStateModel

MASTER = "MASTER"
SLAVE = "SLAVE"


class MasterSlaveStateModel(LeaderFollowerStateModel):
    edges = [
        (OFFLINE, SLAVE),
        (SLAVE, MASTER),
        (MASTER, SLAVE),
        (SLAVE, OFFLINE),
        (OFFLINE, DROPPED),
    ]

    # aliases onto the LeaderFollower transition bodies
    def on_become_slave_from_offline(self):
        self.on_become_follower_from_offline()

    def on_become_master_from_slave(self):
        self.on_become_leader_from_follower()

    def on_become_slave_from_master(self):
        self.on_become_follower_from_leader()

    def on_become_offline_from_slave(self):
        self.on_become_offline_from_follower()


class MasterSlaveStateModelFactory(StateModelFactory):
    model_class = MasterSlaveStateModel
    name = "MasterSlave"
