"""LeaderFollower — the core orchestration state machine.

Reference: LeaderFollowerStateModelFactory.java:51-96 (state diagram) and
per-transition algorithms:

- Offline→Follower (:434-568): per-partition lock → addDB FOLLOWER →
  needRebuildDB (WAL-age / seq-gap heuristic vs live replicas) → if stale,
  backup-from-peer + restore → catch-up loop → repoint to the true leader
  → apply resource configs from the coordinator.
- Follower→Leader (:230-303): lock → verify no live leader in the external
  view → find the replica with the highest seq; if someone is ahead, catch
  up via a temporary upstream → 3-node-failure guard vs the persisted last
  leader seq → promote self → checkpoint the leader seq.
- Leader→Follower, Follower→Offline, Offline→Dropped: demote / closeDB /
  clearDB.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, Optional, Tuple

from ...rpc.errors import RpcApplicationError
from ...utils.segment_utils import (
    db_name_to_segment,
    partition_name_to_db_name,
)
from ..model import DROPPED, FOLLOWER, LEADER, OFFLINE
from .base import StateModel, StateModelFactory, TransitionError

log = logging.getLogger(__name__)

# if a replica is this many seqs behind the best peer, rebuild from a
# snapshot rather than WAL catch-up (needRebuildDB analog)
REBUILD_SEQ_GAP = 100_000
CATCH_UP_MARGIN = 10

# current-state values that mean "is the leader" — the MasterSlave subclass
# publishes MASTER, so every external-view comparison must accept both
LEADERLIKE = {"LEADER", "MASTER"}


class LeaderFollowerStateModel(StateModel):
    edges = [
        (OFFLINE, FOLLOWER),
        (FOLLOWER, LEADER),
        (LEADER, FOLLOWER),
        (FOLLOWER, OFFLINE),
        (OFFLINE, DROPPED),
    ]

    # -- helpers -----------------------------------------------------------

    @property
    def db_name(self) -> str:
        return partition_name_to_db_name(self.partition)

    def _live_replicas(self) -> Dict[str, Tuple]:
        """instance_id -> (info, state, seq) for live hosts of my partition."""
        ctx = self.ctx
        out = {}
        view = ctx.external_view(self.partition)
        instances = ctx.live_instances()
        for iid, state in view.items():
            info = instances.get(iid)
            if info is None:
                continue
            seq = ctx.admin.get_sequence_number(
                (info.host, info.admin_port), self.db_name
            )
            out[iid] = (info, state, seq)
        return out

    def _current_leader_addr(self) -> Optional[Tuple[str, int]]:
        for iid, (info, state, _seq) in self._live_replicas().items():
            if state in LEADERLIKE and iid != self.ctx.instance.instance_id:
                return (info.host, info.repl_port)
        return None

    def _catch_up(self, target_addr: Tuple[str, int], deadline: float,
                  margin: int = CATCH_UP_MARGIN) -> bool:
        """Wait until local seq is within ``margin`` of the target's
        (catch-up loop, LeaderFollowerStateModelFactory.java:570-599).
        margin=0 demands exact catch-up — right for promotion, where the
        peer has no leader and its seq is static."""
        ctx = self.ctx
        admin_target = target_addr
        while time.monotonic() < deadline:
            local = ctx.admin.get_sequence_number(
                ctx.local_admin_addr, self.db_name
            )
            remote = ctx.admin.get_sequence_number(admin_target, self.db_name)
            if local is None or remote is None:
                return False
            if local + margin >= remote:
                return True
            time.sleep(0.1)
        return False

    def _apply_resource_configs(self) -> None:
        """reference :500-525 — reapply per-resource db options from the
        coordinator after (re)adding the db."""
        segment = db_name_to_segment(self.db_name)
        cfg = self.ctx.resource_config(segment)
        options = cfg.get("db_options")
        if options:
            try:
                self.ctx.admin.set_db_options(
                    self.ctx.local_admin_addr, self.db_name, options
                )
            except Exception:
                log.warning("%s: applying resource configs failed", self.db_name)

    # -- transitions -------------------------------------------------------

    def on_become_follower_from_offline(self) -> None:
        ctx = self.ctx
        ctx.log_event(self.partition, "offline_to_follower_init")
        lock = ctx.partition_lock(self.partition)
        if lock is None:
            raise TransitionError(f"{self.partition}: partition lock timeout")
        try:
            replicas = self._live_replicas()
            leader = None
            best_seq = -1
            best_addr = None
            for iid, (info, state, seq) in replicas.items():
                if iid == ctx.instance.instance_id:
                    continue
                if state in LEADERLIKE:
                    leader = info
                if seq is not None and seq > best_seq:
                    best_seq = seq
                    best_addr = info
            upstream = (
                (leader.host, leader.repl_port) if leader
                else (best_addr.host, best_addr.repl_port) if best_addr
                else ctx.local_repl_addr  # bootstrap: self-upstream, no-op
            )
            epoch = ctx.partition_epoch(self.partition)
            try:
                ctx.admin.add_db(
                    ctx.local_admin_addr, self.db_name, "FOLLOWER", upstream,
                    epoch=epoch,
                )
            except RpcApplicationError as e:
                if e.code != "DB_ALREADY_EXISTS":
                    raise
                # ERROR-recovery replan lands here with the db still open
                # (e.g. a failed promotion retries via OFFLINE): converge
                # role/upstream instead of failing the whole transition
                ctx.admin.change_db_role_and_upstream(
                    ctx.local_admin_addr, self.db_name, "FOLLOWER", upstream,
                    epoch=epoch,
                )
            # needRebuildDB: far behind the best replica -> snapshot
            # rebuild; ALSO rebuild when the donor's WAL no longer
            # reaches back to our seq — the serve path would raise
            # "WAL gap … puller must rebuild" forever and plain
            # catch-up can never terminate (the reference checks WAL
            # availability, not just the seq gap; found by the reshard
            # chaos harness: a deposed-resync'd replica rejoining from
            # seq 0 wedged behind a donor whose WAL was purged)
            local = ctx.admin.get_sequence_number(
                ctx.local_admin_addr, self.db_name
            ) or 0
            need_rebuild = best_seq - local > REBUILD_SEQ_GAP
            # probe the node the puller will ACTUALLY pull from — the
            # leader when one exists, not the max-seq replica: a
            # tie-broken probe of a sibling whose WAL reaches back
            # fine passes the check while the real upstream's WAL is
            # purged past us, and the follower wedges at its old seq
            # through every heal replan (found by the rebalance chaos
            # harness: a split-child follower stuck at 0 behind a
            # child leader whose WAL began at the cutover snapshot)
            probe = leader if leader is not None else best_addr
            if (not need_rebuild and probe is not None
                    and best_seq > local):
                donor = ctx.admin.check_db(
                    (probe.host, probe.admin_port), self.db_name)
                if donor is not None:
                    oldest = donor.get("oldest_wal_seq")
                    # an empty donor WAL (oldest None) serves NO
                    # history: with the donor ahead of us that is a
                    # gap too, not a pass
                    if oldest is None or local + 1 < int(oldest):
                        need_rebuild = True
            if (
                need_rebuild
                and ctx.backup_store_uri
                and best_addr is not None
            ):
                ctx.log_event(self.partition, "rebuild_from_peer_init")
                peer = (best_addr.host, best_addr.admin_port)
                backup_path = f"rebuilds/{self.db_name}"
                ctx.admin.backup_db_to_store(
                    peer, self.db_name, ctx.backup_store_uri, backup_path
                )
                ctx.admin.restore_db_from_store(
                    ctx.local_admin_addr, self.db_name,
                    ctx.backup_store_uri, backup_path, upstream,
                )
                ctx.log_event(self.partition, "rebuild_from_peer_success")
            if best_addr is not None:
                self._catch_up(
                    (best_addr.host, best_addr.admin_port),
                    time.monotonic() + ctx.catch_up_timeout,
                )
            self._apply_resource_configs()
            ctx.log_event(self.partition, "offline_to_follower_success")
        except Exception:
            ctx.log_event(self.partition, "offline_to_follower_failure")
            raise
        finally:
            ctx.release_partition_lock(lock)

    def on_become_leader_from_follower(self) -> None:
        ctx = self.ctx
        ctx.log_event(self.partition, "follower_to_leader_init")
        lock = ctx.partition_lock(self.partition)
        if lock is None:
            raise TransitionError(f"{self.partition}: partition lock timeout")
        try:
            replicas = self._live_replicas()
            # no-live-leader check (reference :230-260)
            for iid, (info, state, _seq) in replicas.items():
                if state in LEADERLIKE and iid != ctx.instance.instance_id:
                    raise TransitionError(
                        f"{self.partition}: {iid} is still {state}"
                    )
            local = ctx.admin.get_sequence_number(
                ctx.local_admin_addr, self.db_name
            ) or 0
            # highest-seq election: catch up from any replica ahead of us
            best_iid, best_seq, best_info = None, local, None
            for iid, (info, _state, seq) in replicas.items():
                if iid == ctx.instance.instance_id or seq is None:
                    continue
                if seq > best_seq:
                    best_iid, best_seq, best_info = iid, seq, info
            if best_info is not None:
                ctx.log_event(self.partition, "catch_up_via_peer",
                              f"peer={best_iid} seq={best_seq}")
                ctx.admin.change_db_role_and_upstream(
                    ctx.local_admin_addr, self.db_name, "FOLLOWER",
                    (best_info.host, best_info.repl_port),
                    epoch=ctx.partition_epoch(self.partition),
                )
                # margin=0: the peer has no leader feeding it, so its seq
                # is static and exact catch-up terminates. Promoting even
                # a few seqs short would strand writes that exist only on
                # the peer (it can never hand them to the new leader) and
                # leave the replica set divergent until enough fresh
                # writes paper over the seq gap — with none, forever
                # (reference :230-303 promotes the caught-up candidate).
                if not self._catch_up(
                    (best_info.host, best_info.admin_port),
                    time.monotonic() + ctx.catch_up_timeout,
                    margin=0,
                ):
                    raise TransitionError(
                        f"{self.partition}: catch-up from {best_iid} "
                        f"(seq {best_seq}) incomplete; retrying promotion"
                    )
            # 3-node-failure guard (reference :291-303): refuse promotion if
            # we're far behind the last known leader seq in the coordinator.
            # Slack is ctx.promotion_seq_slack (default = REBUILD_SEQ_GAP):
            # chaos-sized clusters tighten it so a data-poor candidate can
            # never be promoted past a checkpointed lineage it hasn't
            # caught up to.
            persisted = ctx.get_partition_seq(self.partition)
            local = ctx.admin.get_sequence_number(
                ctx.local_admin_addr, self.db_name
            ) or 0
            if persisted is not None and \
                    local + ctx.promotion_seq_slack < persisted:
                raise TransitionError(
                    f"{self.partition}: local seq {local} too far behind "
                    f"last leader seq {persisted}; refusing promotion"
                )
            # the promotion carries the controller-minted epoch: every
            # ack this leader hands out is stamped with it, and any
            # deposed predecessor seeing it on a follower frame fences
            ctx.admin.change_db_role_and_upstream(
                ctx.local_admin_addr, self.db_name, "LEADER",
                epoch=ctx.partition_epoch(self.partition),
            )
            ctx.set_partition_seq(self.partition, local)
            ctx.log_event(self.partition, "follower_to_leader_success")
        except Exception:
            ctx.log_event(self.partition, "follower_to_leader_failure")
            raise
        finally:
            ctx.release_partition_lock(lock)

    def on_become_follower_from_leader(self) -> None:
        ctx = self.ctx
        ctx.log_event(self.partition, "leader_to_follower_init")
        # checkpoint the final leader seq before demotion
        seq = ctx.admin.get_sequence_number(ctx.local_admin_addr, self.db_name)
        if seq is not None:
            ctx.set_partition_seq(self.partition, seq)
        other_leader = self._current_leader_addr()
        if other_leader is not None:
            # DEPOSED demote: another leader is already serving, so this
            # is not the demote phase of a two-phase handoff (which runs
            # with no live leader) — we were deposed while unreachable.
            # Any locally-committed un-acked suffix may diverge from the
            # new lineage, and sequence arithmetic cannot prove it safe
            # (the new leader's seq can overtake ours while histories
            # differ). Resync from scratch: clear the storage and rejoin
            # through the Offline→Follower path, which rebuilds from a
            # peer snapshot or WAL catch-up.
            ctx.log_event(self.partition, "deposed_resync_init",
                          f"local_seq={seq}")
            try:
                ctx.admin.clear_db(ctx.local_admin_addr, self.db_name,
                                   reopen=False)
            except RpcApplicationError as e:
                if e.code != "DB_NOT_FOUND":
                    raise
            self.on_become_follower_from_offline()
            ctx.log_event(self.partition, "deposed_resync_success")
            return
        upstream = ctx.local_repl_addr
        try:
            ctx.admin.change_db_role_and_upstream(
                ctx.local_admin_addr, self.db_name, "FOLLOWER", upstream,
                epoch=ctx.partition_epoch(self.partition),
            )
        except RpcApplicationError as e:
            if e.code != "DB_NOT_FOUND":
                raise
            # the db vanished under us — a split cutover renamed it to a
            # child lineage. The demote's goal (this replica no longer
            # acks as leader) is already met more strongly than a role
            # flip could: there is nothing here to ack.
            ctx.log_event(self.partition, "leader_to_follower_db_gone")
        ctx.log_event(self.partition, "leader_to_follower_success")

    def on_become_offline_from_follower(self) -> None:
        try:
            self.ctx.admin.close_db(self.ctx.local_admin_addr, self.db_name)
        except RpcApplicationError as e:
            if e.code != "DB_NOT_FOUND":
                raise
            # renamed away by a split cutover: already as offline as it gets

    def on_become_dropped_from_offline(self) -> None:
        # destroy local data (reference: Offline→Dropped removes the db)
        try:
            self.ctx.admin.add_db(
                self.ctx.local_admin_addr, self.db_name, "NOOP"
            )
        except Exception:
            pass
        self.ctx.admin.clear_db(
            self.ctx.local_admin_addr, self.db_name, reopen=False
        )


class LeaderFollowerStateModelFactory(StateModelFactory):
    model_class = LeaderFollowerStateModel
    name = "LeaderFollower"
