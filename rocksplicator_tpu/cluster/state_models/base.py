"""State-model plumbing.

Reference: Helix state models — a per-partition object whose
``on_become_X_from_Y`` callbacks execute the transition work; a factory
creates one per partition (Participant.java:348-396 registers factories by
state-model name).
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional, Tuple

from ..model import DROPPED, ERROR, OFFLINE

log = logging.getLogger(__name__)


class TransitionError(Exception):
    pass


class StateModel:
    """Per-partition transition executor. Subclasses define
    ``transition_paths`` (state graph edges) and ``on_become_X_from_Y``
    methods."""

    # edges: (from, to) pairs the model supports directly
    edges: List[Tuple[str, str]] = []
    initial_state = OFFLINE

    def __init__(self, partition: str, ctx: "ClusterContext"):
        self.partition = partition
        self.ctx = ctx

    def transition(self, from_state: str, to_state: str) -> None:
        method = getattr(
            self,
            f"on_become_{to_state.lower()}_from_{from_state.lower()}",
            None,
        )
        if method is None:
            raise TransitionError(
                f"{type(self).__name__}: no transition {from_state}->{to_state}"
            )
        method()

    def plan(self, from_state: str, to_state: str) -> List[Tuple[str, str]]:
        """Shortest edge path from→to (BFS over the model's edges)."""
        if from_state == to_state:
            return []
        frontier = [(from_state, [])]
        seen = {from_state}
        while frontier:
            state, path = frontier.pop(0)
            for a, b in self.edges:
                if a == state and b not in seen:
                    new_path = path + [(a, b)]
                    if b == to_state:
                        return new_path
                    seen.add(b)
                    frontier.append((b, new_path))
        raise TransitionError(
            f"{type(self).__name__}: no path {from_state}->{to_state}"
        )


class StateModelFactory:
    model_class = StateModel
    name = "Base"

    def __init__(self, ctx: "ClusterContext"):
        self.ctx = ctx
        self._models: Dict[str, StateModel] = {}

    def get(self, partition: str) -> StateModel:
        model = self._models.get(partition)
        if model is None:
            model = self.model_class(partition, self.ctx)
            self._models[partition] = model
        return model


class ClusterContext:
    """Everything a transition needs: coordinator, admin client, identity,
    and cluster views (reference: the Helix manager + Utils)."""

    def __init__(self, coord, admin, cluster: str, instance,
                 backup_store_uri: Optional[str] = None,
                 catch_up_timeout: float = 60.0,
                 view_cluster: Optional[str] = None,
                 promotion_seq_slack: Optional[int] = None):
        from ..model import cluster_path

        self.coord = coord            # CoordinatorClient
        self.admin = admin            # AdminClient
        self.cluster = cluster
        # 3-node-failure promotion guard slack: refuse promotion when
        # the candidate is more than this many seqs behind the last
        # checkpointed leader seq. Defaults to the rebuild gap
        # (reference behavior); chaos-sized clusters tighten it so an
        # empty replica can never be promoted over a transiently-
        # invisible data-rich peer (found by the reshard harness: an
        # absolute 100k slack is scale-blind at small workloads).
        from .leader_follower import REBUILD_SEQ_GAP as _GAP

        self.promotion_seq_slack = (
            int(promotion_seq_slack) if promotion_seq_slack is not None
            else _GAP)
        # The cluster whose topology (instances / current states) the
        # state models observe. Differs from ``cluster`` for CDC
        # participants, which join their own cluster but watch the DATA
        # cluster's leaders (reference: CdcUtils reads the data cluster's
        # external view).
        self.view_cluster = view_cluster or cluster
        self.instance = instance      # InstanceInfo (me)
        self.backup_store_uri = backup_store_uri
        self.catch_up_timeout = catch_up_timeout
        self._path = lambda *p: cluster_path(cluster, *p)
        self._view_path = lambda *p: cluster_path(self.view_cluster, *p)
        # controller-stamped fencing epochs, noted by the participant on
        # every assignment update; state models thread them into the
        # data plane (add_db / change_db_role_and_upstream)
        self._partition_epochs: Dict[str, int] = {}

    # -- fencing epochs ----------------------------------------------------

    def note_partition_epoch(self, partition: str, epoch: int) -> None:
        """Epochs are monotonic: never regress a noted value."""
        epoch = int(epoch or 0)
        if epoch > self._partition_epochs.get(partition, 0):
            self._partition_epochs[partition] = epoch

    def partition_epoch(self, partition: str) -> int:
        return self._partition_epochs.get(partition, 0)

    # -- identity ----------------------------------------------------------

    @property
    def local_admin_addr(self) -> Tuple[str, int]:
        return (self.instance.host, self.instance.admin_port)

    @property
    def local_repl_addr(self) -> Tuple[str, int]:
        return (self.instance.host, self.instance.repl_port)

    # -- cluster views -----------------------------------------------------

    def live_instances(self) -> Dict[str, "InstanceInfo"]:
        from ..model import InstanceInfo

        out = {}
        for iid in self.coord.list(self._view_path("instances")):
            raw = self.coord.get_or_none(self._view_path("instances", iid))
            if raw:
                out[iid] = InstanceInfo.decode(raw)
        return out

    def external_view(self, partition: str) -> Dict[str, str]:
        """instance_id -> state for one partition, from currentstates."""
        from ..model import decode_states

        out = {}
        for iid in self.coord.list(self._view_path("currentstates")):
            states = decode_states(
                self.coord.get_or_none(self._view_path("currentstates", iid))
            )
            if partition in states:
                out[iid] = states[partition]
        return out

    def instance_info(self, instance_id: str):
        from ..model import InstanceInfo

        raw = self.coord.get_or_none(self._view_path("instances", instance_id))
        return InstanceInfo.decode(raw) if raw else None

    # -- per-partition lock (reference: zk InterProcessMutex) -------------

    def partition_lock(self, partition: str, timeout: float = 60.0):
        return self.coord.acquire_lock(
            self._path("locks", "partitions", partition), timeout
        )

    def release_partition_lock(self, node: str) -> None:
        self.coord.release_lock(node)

    # -- partition state checkpoints (3-node-failure guard) ---------------

    def get_partition_seq(self, partition: str) -> Optional[int]:
        import json

        raw = self.coord.get_or_none(self._path("partitionstate", partition))
        if raw is None:
            return None
        return int(json.loads(bytes(raw).decode()).get("last_leader_seq", 0))

    def set_partition_seq(self, partition: str, seq: int) -> None:
        import json
        import time as _time

        self.coord.put(
            self._path("partitionstate", partition),
            json.dumps(
                {"last_leader_seq": seq, "updated_ms": int(_time.time() * 1000)}
            ).encode(),
        )

    # -- resource configs applied on transitions --------------------------

    def resource_config(self, segment: str) -> Dict:
        import json

        raw = self.coord.get_or_none(self._path("config", segment))
        return json.loads(bytes(raw).decode()) if raw else {}

    # -- event history (reference eventstore/) ----------------------------

    def log_event(self, partition: str, event_type: str, detail: str = "") -> None:
        from ..eventstore import append_event

        try:
            append_event(self.coord, self.cluster, partition, event_type,
                         self.instance.instance_id, detail)
        except Exception:
            log.exception("event log failed (non-fatal)")
