"""Live hot-shard range splitting: one hash slot → two range-partitioned
virtual child shards, as a resumable step machine.

Reference: the reference fleet delegates reshaping to Helix's rebalancer
+ ConfigGenerator (PAPER.md L4); when a SINGLE partition outgrows every
placement, operators there re-shard the whole resource (shard-count
doubling with a bulk copy). Here the split is surgical and live: the
hash map (``num_shards``) is untouched — every key still hashes to the
parent slot — and a durable :class:`~.model.SplitRecord` teaches
routers/the controller to resolve key → child by RANGE under that slot
(``rpc/router.py`` chases records transitively, so children can split
again).

Mechanics reuse the fault-proven shard-move machinery piecewise:

- both children start life as FULL COPIES of the parent. The **low**
  child (keys < split_key) is the parent's own replica set, flipped in
  place by the new ``rename_db`` admin primitive (zero data movement);
  the **high** child is seeded by snapshot → hidden-OBSERVER restore →
  WAL-tail catch-up onto the target instance, exactly like a move's
  destination (restored under the PARENT's name so the tail pull
  addresses match).
- out-of-range keys inside a child are harmless garbage: the router
  routes strictly by range, so they are never read or written again
  (space is reclaimed by a later manual compact/trim — an honest
  residual, see PARITY.md).
- **cutover** (failpoint ``split.cutover``) runs under the parent
  leader's auto-expiring write pause: drain the high seed to exact
  equality, write the children's fencing-epoch ledger records
  (parent epoch + 1) and placement pins, then rename leader-first —
  the instant the parent leader's db closes, no writer can ack into
  the parent lineage, so a crash mid-sequence leaves the shard
  temporarily leaderless (resume finishes it), never forked.
- the record's ``active`` phase is terminal and PERMANENT: it is the
  routing truth the shard map's ``__splits__`` section and the
  controller's child-partition enumeration are generated from. The
  controller then treats each child like any partition — pins top the
  high child up to full replication through the ordinary
  rebuild-from-peer path, and the parent's stale assignments retire
  through Offline→Dropped.

Every phase is written to ``/clusters/<c>/splits/<parent_partition>``
BEFORE its side effects run; a driver killed at any seam resumes
idempotently (``ShardSplit.resume``) or, strictly pre-cutover, aborts
(``ShardSplit.abort`` — sweep the hidden seed + snapshot, delete the
record; children were never visible).
"""

from __future__ import annotations

import json
import logging
import time
import uuid
from typing import Dict, List, Optional, Tuple

from ..rpc.errors import RpcApplicationError, RpcError
from ..testing import failpoints as fp
from ..utils.objectstore import build_object_store
from ..utils.segment_utils import (
    db_name_to_partition_name,
    segment_to_db_name,
)
from ..utils.stats import Stats
from .coordinator import CoordinatorClient
from .helix_utils import AdminClient
from .model import (InstanceInfo, PlacementPin, ResourceDef, SplitRecord,
                    cluster_path, decode_states)
from .shard_move import MoveFlags, list_active_moves

log = logging.getLogger(__name__)

_LEADERLIKE = {"LEADER", "MASTER"}
_SERVING = _LEADERLIKE | {"FOLLOWER", "SLAVE"}


class SplitError(RuntimeError):
    """A phase failed in a way the driver cannot ride through. The
    split record stays durable; resume or abort it explicitly."""


class SplitInFlightError(SplitError):
    """A split for this partition is already recorded."""


def list_splits(coord: CoordinatorClient,
                cluster: str) -> List[SplitRecord]:
    """Every recorded split (any phase), newest-path order."""
    out: List[SplitRecord] = []
    for p in coord.list(cluster_path(cluster, "splits")):
        rec = SplitRecord.decode(
            coord.get_or_none(cluster_path(cluster, "splits", p)))
        if rec is not None:
            out.append(rec)
    return out


def active_splits(coord: CoordinatorClient,
                  cluster: str) -> List[SplitRecord]:
    """The routing truth: splits whose children are live."""
    return [r for r in list_splits(coord, cluster) if r.phase == "active"]


def choose_split_key(admin: AdminClient, repl_addr: Tuple[str, int],
                     db_name: str, sample: int = 257) -> Optional[bytes]:
    """Median key of a bounded leader scan — the default range boundary
    when the caller (rebalancer / CLI) doesn't name one. A scan-based
    median splits the OBSERVED keyspace evenly; with a skewed range the
    halves are still both strictly smaller than the parent, which is
    all a split needs to make progress."""
    try:
        r = admin.call(repl_addr, "read", db_name=db_name, op="scan",
                       start=b"", count=int(sample), timeout=10.0)
    except (RpcError, RpcApplicationError):
        return None
    keys = []
    for row in (r or {}).get("values") or []:
        if isinstance(row, (list, tuple)) and row:
            keys.append(bytes(row[0]))
    if len(keys) < 2:
        return None
    keys.sort()
    mid = keys[len(keys) // 2]
    return mid if mid != keys[0] else None


class ShardSplit:
    """Coordinator-backed splitter for one partition. Construct via
    :meth:`start` (new split) or :meth:`resume`; :meth:`run` executes to
    the terminal ``active`` phase; :meth:`abort` unwinds pre-cutover."""

    def __init__(self, coord: CoordinatorClient, cluster: str,
                 record: SplitRecord,
                 admin: Optional[AdminClient] = None,
                 flags: Optional[MoveFlags] = None):
        self.coord = coord
        self.cluster = cluster
        self.rec = record
        self.flags = flags or MoveFlags()
        self.admin = admin or AdminClient()
        self._owns_admin = admin is None
        self._path = lambda *p: cluster_path(cluster, *p)
        self._stats = Stats.get()
        self._last_record_put = 0.0

    # -- construction ----------------------------------------------------

    @classmethod
    def start(cls, coord: CoordinatorClient, cluster: str, segment: str,
              parent_shard: int, split_key: bytes, target: str,
              store_uri: str, admin: Optional[AdminClient] = None,
              flags: Optional[MoveFlags] = None) -> "ShardSplit":
        """Record and return a NEW split (phase ``planned``). Child
        shard ids are allocated ABOVE the resource's hash range (and
        above every child any recorded split already claimed), so a
        child id can never collide with a hashed slot."""
        if not split_key:
            raise SplitError("empty split key")
        raw = coord.get_or_none(cluster_path(cluster, "resources",
                                             segment))
        if raw is None:
            raise SplitError(f"unknown segment {segment!r}")
        resource = ResourceDef.decode(raw)
        if not (0 <= parent_shard < resource.num_shards or any(
                parent_shard in r.child_shards()
                for r in list_splits(coord, cluster)
                if r.segment == segment)):
            raise SplitError(
                f"{segment}: shard {parent_shard} is neither a hash "
                f"slot nor a live child")
        next_id = resource.num_shards
        for r in list_splits(coord, cluster):
            if r.segment == segment:
                next_id = max(next_id, r.low_shard + 1, r.high_shard + 1)
        db_name = segment_to_db_name(segment, parent_shard)
        partition = db_name_to_partition_name(db_name)
        if any(m.partition == partition
               for m in list_active_moves(coord, cluster)):
            raise SplitError(
                f"{partition}: a shard move is in flight — splitting "
                f"under it would race the placement pin")
        rec = SplitRecord(
            segment=segment, parent_shard=parent_shard,
            split_key=bytes(split_key).hex(),
            low_shard=next_id, high_shard=next_id + 1,
            split_id=uuid.uuid4().hex[:12],
            moved_child=next_id + 1, target_instance=target,
            store_uri=store_uri,
            snapshot_prefix=f"splits/{db_name}/{uuid.uuid4().hex[:12]}",
            started_ms=int(time.time() * 1000),
        )
        sp = cls(coord, cluster, rec, admin=admin, flags=flags)
        try:
            sp._validate_plan()
            sp.coord.create(sp._record_path(), rec.encode())
        except RpcApplicationError as e:
            sp.close()
            if e.code == "NODE_EXISTS":
                raise SplitInFlightError(
                    f"{partition}: a split is already recorded — resume "
                    f"or abort it first") from e
            raise
        except BaseException:
            sp.close()
            raise
        sp._stats.incr("shard_splits.started")
        sp._bump_summary("started")
        return sp

    @classmethod
    def resume(cls, coord: CoordinatorClient, cluster: str,
               partition: str, admin: Optional[AdminClient] = None,
               flags: Optional[MoveFlags] = None) -> "ShardSplit":
        raw = coord.get_or_none(cluster_path(cluster, "splits",
                                             partition))
        rec = SplitRecord.decode(raw)
        if rec is None:
            raise SplitError(f"{partition}: no split recorded")
        if rec.phase == "active":
            raise SplitError(f"{partition}: split already active")
        sp = cls(coord, cluster, rec, admin=admin, flags=flags)
        sp._stats.incr("shard_splits.resumed")
        sp._bump_summary("resumed")
        return sp

    # -- plumbing --------------------------------------------------------

    @property
    def parent_db(self) -> str:
        return segment_to_db_name(self.rec.segment, self.rec.parent_shard)

    @property
    def parent_partition(self) -> str:
        return db_name_to_partition_name(self.parent_db)

    def _child_db(self, shard: int) -> str:
        return segment_to_db_name(self.rec.segment, shard)

    def _child_partition(self, shard: int) -> str:
        return db_name_to_partition_name(self._child_db(shard))

    def _record_path(self) -> str:
        return self._path("splits", self.parent_partition)

    def _save(self, phase: Optional[str] = None,
              force: bool = True) -> None:
        now = time.monotonic()
        if phase is not None:
            self.rec.phase = phase
        elif not force and (now - self._last_record_put
                            < self.flags.record_update_interval):
            return
        self.rec.updated_ms = int(time.time() * 1000)
        self.coord.put(self._record_path(), self.rec.encode())
        self._last_record_put = now

    def _bump_summary(self, key: str) -> None:
        path = self._path("splits_summary")
        try:
            raw = self.coord.get_or_none(path)
            d = json.loads(bytes(raw).decode()) if raw else {}
            d[key] = int(d.get(key, 0)) + 1
            self.coord.put(path, json.dumps(d).encode())
        except Exception:
            log.debug("splits_summary bump failed", exc_info=True)

    def _instances(self) -> Dict[str, InstanceInfo]:
        out: Dict[str, InstanceInfo] = {}
        for iid in self.coord.list(self._path("instances")):
            raw = self.coord.get_or_none(self._path("instances", iid))
            if raw:
                out[iid] = InstanceInfo.decode(raw)
        return out

    def _states(self, partition: Optional[str] = None) -> Dict[str, str]:
        partition = partition or self.parent_partition
        out: Dict[str, str] = {}
        for iid in self.coord.list(self._path("currentstates")):
            st = decode_states(self.coord.get_or_none(
                self._path("currentstates", iid))).get(partition)
            if st:
                out[iid] = st
        return out

    def _admin_addr(self, info: InstanceInfo) -> Tuple[str, int]:
        return (info.host, info.admin_port)

    def _seq(self, info: InstanceInfo, db: Optional[str] = None
             ) -> Optional[int]:
        return self.admin.get_sequence_number(
            self._admin_addr(info), db or self.parent_db)

    def _leader(self) -> Optional[Tuple[str, InstanceInfo]]:
        instances = self._instances()
        for iid, st in self._states().items():
            if st in _LEADERLIKE and iid in instances:
                return (iid, instances[iid])
        return None

    def _target_info(self) -> InstanceInfo:
        info = self._instances().get(self.rec.target_instance)
        if info is None:
            raise SplitError(
                f"{self.parent_partition}: target "
                f"{self.rec.target_instance} is not a live instance")
        return info

    def _validate_plan(self) -> None:
        instances = self._instances()
        states = self._states()
        if self.rec.target_instance not in instances:
            raise SplitError(
                f"target {self.rec.target_instance} is not live")
        if not any(st in _LEADERLIKE for st in states.values()):
            raise SplitError(
                f"{self.parent_partition}: no live leader to split")
        if self.rec.target_instance in states:
            raise SplitError(
                f"target {self.rec.target_instance} already serves "
                f"{self.parent_partition} — pick a non-hosting instance")
        if self._seq(instances[self.rec.target_instance]) is not None:
            raise SplitError(
                f"target {self.rec.target_instance} already holds a "
                f"{self.parent_db} replica (leftover?) — sweep it first")

    # -- the step machine ------------------------------------------------

    def run(self) -> SplitRecord:
        order = {p: i for i, p in enumerate(SplitRecord.PHASES)}
        start_at = order.get(self.rec.phase, 0)
        try:
            if start_at <= order["snapshot"]:
                self._save("snapshot")
                self._phase_snapshot()
            if start_at <= order["restore"]:
                self._save("restore")
                self._phase_restore()
            if start_at <= order["catchup"]:
                self._save("catchup")
                self._phase_catchup()
            if start_at <= order["cutover"]:
                self._save("cutover")
                self._phase_cutover()
            self._finish()
            self.close()
            return self.rec
        finally:
            pass

    def close(self) -> None:
        if self._owns_admin:
            self.admin.close()
            self._owns_admin = False

    def _phase_snapshot(self) -> None:
        rec = self.rec
        led = self._leader()
        if led is None:
            raise SplitError(f"{self.parent_partition}: no live leader "
                             f"to snapshot")
        r = self.admin.backup_db_to_store(
            self._admin_addr(led[1]), self.parent_db, rec.store_uri,
            rec.snapshot_prefix)
        rec.snapshot_seq = int(r.get("seq") or 0)
        self._save()

    def _phase_restore(self) -> None:
        rec = self.rec
        target = self._target_info()
        existing = self._seq(target)
        if existing is not None and existing >= rec.snapshot_seq > 0:
            return  # resumed past the restore
        led = self._leader()
        if led is None:
            raise SplitError(f"{self.parent_partition}: no live leader "
                             f"to tail from after restore")
        # hidden OBSERVER under the PARENT's name: the WAL-tail pull
        # addresses by db name, and observer pulls never count toward
        # semi-sync acks (an aborted split sweeps this replica — it must
        # never have been an acker)
        self.admin.restore_db_from_store(
            self._admin_addr(target), self.parent_db, rec.store_uri,
            rec.snapshot_prefix,
            upstream=(led[1].host, led[1].repl_port), role="OBSERVER")
        self._save()

    def _lag(self) -> Optional[int]:
        led = self._leader()
        if led is None:
            return None
        target = self._instances().get(self.rec.target_instance)
        if target is None:
            raise SplitError(f"{self.parent_partition}: target died "
                             f"during catch-up")
        lseq = self._seq(led[1])
        tseq = self._seq(target)
        if lseq is None or tseq is None:
            return None
        return max(0, lseq - tseq)

    def _phase_catchup(self) -> None:
        rec, flags = self.rec, self.flags
        deadline = time.monotonic() + flags.catchup_timeout
        while True:
            lag = self._lag()
            if lag is not None:
                rec.catchup_lag = lag
                self._save(force=False)
                if lag <= flags.catchup_lag_threshold:
                    self._save()
                    return
            if time.monotonic() > deadline:
                raise SplitError(
                    f"{self.parent_partition}: split catch-up lag "
                    f"{rec.catchup_lag} never reached threshold within "
                    f"{flags.catchup_timeout}s")
            time.sleep(flags.poll_interval)

    def _put_epoch_record(self, partition: str, leader_iid: str,
                          epoch: int) -> None:
        """Seed a child's fencing-epoch ledger record, max-merging
        against anything already there (a resumed cutover re-puts; the
        controller only writes child records AFTER the split activates,
        so pre-active this driver is the only writer)."""
        path = self._path("epochs", partition)
        raw = self.coord.get_or_none(path)
        if raw:
            try:
                existing = json.loads(bytes(raw).decode())
                if int(existing.get("epoch", 0)) >= epoch:
                    return
            except (ValueError, UnicodeDecodeError):
                pass
        self.coord.put(path, json.dumps(
            {"epoch": int(epoch), "leader": leader_iid}).encode())

    def _phase_cutover(self) -> None:
        """The fenced flip: pause → drain-to-0 → child ledgers/pins →
        rename LEADER-FIRST → children live. Leader-first is the loss
        guard (and what the chaos harness's ``split_cutover`` tooth
        breaks): the instant the parent leader's db closes, nothing can
        ack into the parent lineage, so post-pause stragglers are
        refused rather than stranded on a copy a child never sees."""
        fp.hit("split.cutover")
        rec = self.rec
        instances = self._instances()
        states = self._states()
        target = instances.get(rec.target_instance)
        if target is None:
            raise SplitError(f"{self.parent_partition}: target "
                             f"{rec.target_instance} died at cutover")
        led = self._leader()
        low_db = self._child_db(rec.low_shard)
        high_db = self._child_db(rec.high_shard)
        leader_iid: Optional[str] = None
        hosting = [iid for iid, st in states.items() if st in _SERVING]
        if led is not None and self._seq(led[1]) is not None:
            # the parent still exists: drain the high seed to EXACT
            # equality under the write pause, then mint the children's
            # epoch from the live parent epoch
            leader_iid, leader = led
            if self._seq(target) is None:
                raise SplitError(
                    f"{self.parent_partition}: target no longer holds "
                    f"the {self.parent_db} seed at cutover")
            self._cutover_drain(leader)
            info = self.admin.check_db(self._admin_addr(leader),
                                       self.parent_db)
            live_epoch = int((info or {}).get("epoch") or 0)
            ledger = self.coord.get_or_none(
                self._path("epochs", self.parent_partition))
            rec_epoch = 0
            if ledger:
                try:
                    rec_epoch = int(json.loads(
                        bytes(ledger).decode()).get("epoch", 0))
                except (ValueError, UnicodeDecodeError):
                    pass
            rec.epoch = max(live_epoch, rec_epoch) + 1
            self._save()
        elif rec.epoch <= 0:
            raise SplitError(
                f"{self.parent_partition}: parent gone but no child "
                f"epoch recorded — cannot resume this cutover")
        # resumed cutovers must re-derive who the low child's replicas
        # are even when the parent claims are already gone
        if leader_iid is None:
            prior = self.coord.get_or_none(
                self._path("placements",
                           self._child_partition(rec.low_shard)))
            pin = PlacementPin.decode(prior)
            hosting = list(pin.replicas) if pin else hosting
            leader_iid = pin.preferred_leader if pin else None
        low_replicas = sorted(set(hosting) - {rec.target_instance}) \
            or [leader_iid for leader_iid in [leader_iid] if leader_iid]
        # children's durable identity BEFORE any rename: ledger records
        # (epoch, leader) + placement pins. The controller reads both
        # the moment the split activates, so its first child assignments
        # already match the renamed reality (sticky recorded leader, no
        # second epoch mint).
        low_part = self._child_partition(rec.low_shard)
        high_part = self._child_partition(rec.high_shard)
        self._put_epoch_record(low_part, leader_iid or "", rec.epoch)
        self._put_epoch_record(high_part, rec.target_instance, rec.epoch)
        self.coord.put(self._path("placements", low_part), PlacementPin(
            replicas=low_replicas, preferred_leader=leader_iid,
            move_id=rec.split_id).encode())
        self.coord.put(self._path("placements", high_part), PlacementPin(
            replicas=[rec.target_instance],
            preferred_leader=rec.target_instance,
            move_id=rec.split_id).encode())
        # renames: LEADER FIRST (closes the parent lineage to writers),
        # then the high seed (already at exact equality), then the
        # parent followers in place. Each rename is idempotent on
        # resume (done = no-op inside the handler).
        # each child's rename carries its retained half of the key
        # range ([lo, hi) in split_key hex): durable trim metadata, so
        # the child's first scheduled compaction drops the other half's
        # bytes instead of hauling the full parent copy forever
        if led is not None and leader_iid in instances:
            self.admin.rename_db(
                self._admin_addr(instances[leader_iid]), self.parent_db,
                low_db, new_role="LEADER", epoch=rec.epoch,
                retain_hi=rec.split_key)
        self.admin.rename_db(
            self._admin_addr(target), self.parent_db, high_db,
            new_role="LEADER", epoch=rec.epoch, retain_lo=rec.split_key)
        leader_info = instances.get(leader_iid or "")
        for iid in low_replicas:
            if iid == leader_iid:
                continue
            info = instances.get(iid)
            if info is None:
                continue
            try:
                self.admin.rename_db(
                    self._admin_addr(info), self.parent_db, low_db,
                    new_role="FOLLOWER",
                    upstream=((leader_info.host, leader_info.repl_port)
                              if leader_info else None),
                    epoch=rec.epoch, retain_hi=rec.split_key)
            except (RpcError, RpcApplicationError) as e:
                # a follower that raced away (dead / already renamed /
                # never hosted) self-heals through the controller's
                # child assignment — the leader rename above is the
                # only rename correctness depends on
                log.warning("%s: follower rename on %s failed: %r",
                            self.parent_partition, iid, e)

    def _cutover_drain(self, leader: InstanceInfo) -> None:
        flags = self.flags
        last_lag = None
        for _attempt in range(flags.cutover_attempts):
            try:
                self.admin.pause_db_writes(
                    self._admin_addr(leader), self.parent_db,
                    flags.cutover_pause_ms)
            except (RpcError, RpcApplicationError):
                continue
            pause_deadline = (time.monotonic()
                              + flags.cutover_pause_ms / 1000.0)
            while time.monotonic() < pause_deadline:
                lag = self._lag()
                if lag is not None:
                    last_lag = lag
                    self.rec.catchup_lag = lag
                    if lag == 0:
                        return
                time.sleep(flags.poll_interval)
        raise SplitError(
            f"{self.parent_partition}: high seed never drained to 0 "
            f"across {flags.cutover_attempts} pause windows (last lag "
            f"{last_lag})")

    def _finish(self) -> None:
        rec = self.rec
        # the activation IS the publish: spectator emits __splits__,
        # routers resolve by range, the controller enumerates children
        # and retires the parent's assignments
        self._save("active")
        self.coord.delete_if_exists(
            self._path("placements", self.parent_partition))
        self._await_children()
        self._sweep_snapshot()
        self._stats.incr("shard_splits.completed")
        self._bump_summary("completed")
        log.info("%s: split %s active (low=%d high=%d @ %s)",
                 self.parent_partition, rec.split_id, rec.low_shard,
                 rec.high_shard, rec.split_key)

    def _await_children(self) -> None:
        """Wait for both children to have a leaderlike claim in the
        published current states — the moment the shard map serves them
        and the harness can declare the split live."""
        flags = self.flags
        deadline = time.monotonic() + flags.flip_timeout
        wanted = [self._child_partition(self.rec.low_shard),
                  self._child_partition(self.rec.high_shard)]
        while time.monotonic() < deadline:
            if all(any(st in _LEADERLIKE
                       for st in self._states(p).values())
                   for p in wanted):
                return
            time.sleep(flags.poll_interval)
        raise SplitError(
            f"{self.parent_partition}: children never reached a leader "
            f"claim within {flags.flip_timeout}s")

    def _sweep_snapshot(self) -> None:
        try:
            store = build_object_store(self.rec.store_uri)
            for key in store.list_objects(
                    self.rec.snapshot_prefix.rstrip("/") + "/"):
                store.delete_object(key)
        except Exception:
            log.warning("%s: split snapshot sweep failed",
                        self.parent_partition, exc_info=True)

    # -- abort -----------------------------------------------------------

    def abort(self) -> None:
        """Unwind a strictly PRE-cutover split: sweep the hidden high
        seed and the snapshot, delete the record. At or past cutover the
        children's identity is being published — the only safe direction
        is forward (resume)."""
        rec = self.rec
        order = {p: i for i, p in enumerate(SplitRecord.PHASES)}
        if order.get(rec.phase, 0) >= order["cutover"]:
            raise SplitError(
                f"{self.parent_partition}: split already at {rec.phase}"
                f" — past the point of no return; resume it instead")
        target = self._instances().get(rec.target_instance)
        if target is not None:
            try:
                self.admin.clear_db(self._admin_addr(target),
                                    self.parent_db, reopen=False)
            except (RpcError, RpcApplicationError) as e:
                if getattr(e, "code", None) != "DB_NOT_FOUND":
                    raise SplitError(
                        f"{self.parent_partition}: abort could not "
                        f"sweep the seed on {rec.target_instance} "
                        f"({e!r}) — record kept, retry") from e
        try:
            self._sweep_snapshot()
        finally:
            self.coord.delete_if_exists(self._record_path())
            self._stats.incr("shard_splits.aborted")
            self._bump_summary("aborted")
            self.close()
        log.info("%s: split %s aborted at phase %s",
                 self.parent_partition, rec.split_id, rec.phase)
