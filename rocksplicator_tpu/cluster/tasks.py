"""Task framework: Backup / Restore / Ingest / Dedup jobs.

Reference: cluster_management task/ — Helix Task framework factories
(BackupTask backs one partition to cloud, RestoreTask, IngestTask calling
ingestFromS3, DedupTask) with job configs carrying store path, version,
rate limits. Here: a coordinator-queued job model; workers claim jobs with
a lock, execute against the owning instance's Admin service, and record
results.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from .coordinator import CoordinatorClient
from .helix_utils import AdminClient
from .model import InstanceInfo, cluster_path, decode_states

log = logging.getLogger(__name__)

_LEADERLIKE = {"LEADER", "MASTER"}


class TaskRunner:
    """Executes one task type against a partition's owning instance."""

    name = "base"

    def run(self, worker: "TaskWorker", job: Dict) -> Dict:
        raise NotImplementedError

    # -- shared helpers ----------------------------------------------------

    @staticmethod
    def _find_owner(worker: "TaskWorker", partition: str):
        """The partition's live leader if any, else any live replica."""
        path = worker._path
        coord = worker.coord
        instances: Dict[str, InstanceInfo] = {}
        for iid in coord.list(path("instances")):
            raw = coord.get_or_none(path("instances", iid))
            if raw:
                instances[iid] = InstanceInfo.decode(raw)
        fallback = None
        for iid, info in instances.items():
            states = decode_states(
                coord.get_or_none(path("currentstates", iid))
            )
            state = states.get(partition)
            if state is None:
                continue
            if state in _LEADERLIKE:
                return info
            fallback = fallback or info
        return fallback


class BackupTask(TaskRunner):
    """task/BackupTask.java:1-60 — back one partition up to the store."""

    name = "Backup"

    def run(self, worker, job):
        from ..utils.segment_utils import partition_name_to_db_name

        partition = job["partition"]
        db_name = partition_name_to_db_name(partition)
        owner = self._find_owner(worker, partition)
        if owner is None:
            raise RuntimeError(f"no live owner for {partition}")
        version = job.get("version") or time.strftime("%Y%m%d-%H%M%S")
        backup_path = f"{job.get('store_path', 'backups')}/{db_name}/{version}"
        r = worker.admin.backup_db_to_store(
            (owner.host, owner.admin_port), db_name,
            job["store_uri"], backup_path,
        )
        return {"backup_path": backup_path, "seq": r.get("seq")}


class RestoreTask(TaskRunner):
    name = "Restore"

    def run(self, worker, job):
        from ..utils.segment_utils import partition_name_to_db_name

        partition = job["partition"]
        db_name = partition_name_to_db_name(partition)
        owner = self._find_owner(worker, partition)
        if owner is None:
            raise RuntimeError(f"no live owner for {partition}")
        r = worker.admin.restore_db_from_store(
            (owner.host, owner.admin_port), db_name,
            job["store_uri"], job["backup_path"],
        )
        return {"seq": r.get("seq")}


class IngestTask(TaskRunner):
    """task/IngestTask.java — calls the SST bulk-ingest RPC."""

    name = "Ingest"

    def run(self, worker, job):
        from ..utils.segment_utils import partition_name_to_db_name

        partition = job["partition"]
        db_name = partition_name_to_db_name(partition)
        owner = self._find_owner(worker, partition)
        if owner is None:
            raise RuntimeError(f"no live owner for {partition}")
        r = worker.admin.ingest_from_store(
            (owner.host, owner.admin_port), db_name,
            job["store_uri"], job["sst_path"],
            ingest_behind=job.get("ingest_behind", False),
            allow_overlapping_keys=job.get("allow_overlapping_keys", True),
            compact_db_after_load=job.get("compact_after", False),
        )
        return dict(r)


class DedupTask(TaskRunner):
    """task/DedupTask.java — full compaction deduplicates a partition."""

    name = "Dedup"

    def run(self, worker, job):
        from ..utils.segment_utils import partition_name_to_db_name

        partition = job["partition"]
        db_name = partition_name_to_db_name(partition)
        owner = self._find_owner(worker, partition)
        if owner is None:
            raise RuntimeError(f"no live owner for {partition}")
        worker.admin.compact_db((owner.host, owner.admin_port), db_name)
        return {}


class MoveShardTask(TaskRunner):
    """Live shard move through the resumable step machine
    (cluster/shard_move.py) — the queued-job face of the reference's
    Helix Bootstrap/backup+restore task flows. The job names the
    partition, donor and destination instance ids, and the snapshot
    store; ``resume: true`` continues a recorded in-flight move
    instead of starting a new one."""

    name = "MoveShard"

    def run(self, worker, job):
        from .shard_move import ShardMove

        partition = job["partition"]
        if job.get("resume"):
            mv = ShardMove.resume(worker.coord, worker.cluster,
                                  partition, admin=worker.admin)
        else:
            mv = ShardMove.start(
                worker.coord, worker.cluster, partition,
                job["source"], job["target"], job["store_uri"],
                admin=worker.admin,
            )
        rec = mv.run()
        return {"move_id": rec.move_id, "source": rec.source,
                "target": rec.target,
                "bytes_ingested": rec.bytes_ingested}


TASK_RUNNERS: Dict[str, TaskRunner] = {
    t.name: t() for t in (BackupTask, RestoreTask, IngestTask, DedupTask,
                          MoveShardTask)
}


def submit_task(coord: CoordinatorClient, cluster: str, task_type: str,
                job: Dict) -> str:
    """Enqueue a job; returns the task id."""
    task_id = f"{task_type.lower()}-{uuid.uuid4().hex[:8]}"
    payload = {"task_id": task_id, "type": task_type, "job": job,
               "submitted_ms": int(time.time() * 1000)}
    coord.put(
        cluster_path(cluster, "tasks", "queue", task_id),
        json.dumps(payload).encode(),
    )
    return task_id


def task_result(coord: CoordinatorClient, cluster: str, task_id: str,
                timeout: float = 0.0) -> Optional[Dict]:
    path = cluster_path(cluster, "tasks", "results", task_id)
    deadline = time.monotonic() + timeout
    while True:
        raw = coord.get_or_none(path)
        if raw is not None:
            return json.loads(bytes(raw).decode())
        if time.monotonic() >= deadline:
            return None
        time.sleep(0.1)


class TaskWorker:
    """Claims queued tasks (coordinator lock per task) and runs them."""

    def __init__(self, coord_host: str, coord_port: int, cluster: str,
                 worker_id: str = "worker",
                 runners: Optional[Dict[str, TaskRunner]] = None,
                 coord_fallbacks: Optional[List[Tuple[str, int]]] = None):
        self.cluster = cluster
        self.worker_id = worker_id
        self.coord = CoordinatorClient(coord_host, coord_port,
                                       fallbacks=coord_fallbacks)
        self.admin = AdminClient()
        self.runners = runners or TASK_RUNNERS
        self._path = lambda *p: cluster_path(cluster, *p)
        self._stop = threading.Event()
        self._kick = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"task-worker-{worker_id}", daemon=True
        )
        self._thread.start()
        self._watch_stop = self.coord.watch(
            self._path("tasks", "queue"), lambda _s: self._kick.set()
        )

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._drain()
            except Exception:
                log.exception("task worker error")
            self._kick.wait(1.0)
            self._kick.clear()

    def _drain(self) -> None:
        for task_id in self.coord.list(self._path("tasks", "queue")):
            if self._stop.is_set():
                return
            lock = self.coord.acquire_lock(
                self._path("tasks", "locks", task_id), timeout=0.5
            )
            if lock is None:
                continue
            try:
                raw = self.coord.get_or_none(
                    self._path("tasks", "queue", task_id)
                )
                if raw is None:
                    continue  # another worker finished it
                payload = json.loads(bytes(raw).decode())
                result = self._execute(payload)
                self.coord.put(
                    self._path("tasks", "results", task_id),
                    json.dumps(result).encode(),
                )
                self.coord.delete_if_exists(
                    self._path("tasks", "queue", task_id)
                )
            finally:
                self.coord.release_lock(lock)

    def _execute(self, payload: Dict) -> Dict:
        task_type = payload.get("type", "")
        runner = self.runners.get(task_type)
        base = {
            "task_id": payload.get("task_id"),
            "type": task_type,
            "worker": self.worker_id,
            "finished_ms": int(time.time() * 1000),
        }
        if runner is None:
            return {**base, "ok": False, "error": f"unknown task {task_type}"}
        try:
            out = runner.run(self, payload.get("job", {}))
            return {**base, "ok": True, "result": out}
        except Exception as e:
            log.exception("task %s failed", payload.get("task_id"))
            return {**base, "ok": False, "error": repr(e)}

    def stop(self) -> None:
        self._stop.set()
        self._kick.set()
        self._watch_stop.set()
        self._thread.join(timeout=5.0)
        self.coord.close()
        self.admin.close()
