"""Shard-map agents: coordinator-published maps → local files for clients.

Reference: cluster_management shardmapagent/ + ClientShardMapAgent — agents
subscribing to ZK shard maps and materializing per-cluster local files that
client-side routers watch.
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Dict, List, Optional, Tuple

from ..utils.misc import write_file_atomic
from ..utils.retry_policy import RetryPolicy, retry_call, seeded_rng
from .coordinator import CoordinatorClient
from .model import cluster_path

log = logging.getLogger(__name__)

# the materialize-to-disk write retried like any other transient I/O:
# bounded, growing, jittered, deterministic under RSTPU_RETRY_SEED, and
# visible as retry.attempts op=shardmap.write on /stats (the refresh loop
# itself — the coordinator watch — retries via the client's own policy)
_WRITE_RETRY = RetryPolicy(max_attempts=4, base_delay=0.05, max_delay=0.5)


class ShardMapAgent:
    """Syncs one cluster's published shard map to a local file."""

    def __init__(self, coord_host: str, coord_port: int, cluster: str,
                 target_path: str,
                 coord_fallbacks: Optional[List[Tuple[str, int]]] = None):
        self.cluster = cluster
        self.target_path = target_path
        self._rng = seeded_rng()
        self.coord = CoordinatorClient(coord_host, coord_port,
                                       fallbacks=coord_fallbacks)
        self._watch_stop = self.coord.watch(
            cluster_path(cluster, "shardmap"), self._on_map
        )

    def _on_map(self, snap: dict) -> None:
        if not snap.get("exists"):
            return
        value = bytes(snap["value"])
        try:
            retry_call(
                lambda: write_file_atomic(self.target_path, value),
                policy=_WRITE_RETRY,
                classify=lambda e: isinstance(e, OSError),
                op="shardmap.write",
                rng=self._rng,
            )
        except Exception:
            log.exception("shard map agent write failed")

    def stop(self) -> None:
        self._watch_stop.set()
        self.coord.close()


class ClientShardMapAgent:
    """Multi-cluster variant: one agent process materializing a file per
    cluster under a directory (ClientShardMapAgent)."""

    def __init__(self, coord_host: str, coord_port: int,
                 clusters: List[str], target_dir: str):
        import os

        os.makedirs(target_dir, exist_ok=True)
        self._agents = [
            ShardMapAgent(
                coord_host, coord_port, c,
                f"{target_dir.rstrip('/')}/{c}.json",
            )
            for c in clusters
        ]

    def stop(self) -> None:
        for a in self._agents:
            a.stop()
