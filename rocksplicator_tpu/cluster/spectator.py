"""Spectator: watches the external view and regenerates shard maps.

Reference: Spectator.java:55-426 / DistributedSpectatorMain — a
leader-standby-elected process running ConfigGenerator on EXTERNAL_VIEW
changes; the embedded variant rides inside the participant process
(HelixCustomCodeRunner, Participant.java:449-466). Here both modes are one
class: standalone=True elects a leader among spectators so only one
publishes.
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional, Tuple

from .config_generator import generate_shard_map
from .coordinator import CoordinatorClient
from .model import cluster_path
from .publishers import DedupPublisher, ParallelPublisher, ShardMapPublisher

log = logging.getLogger(__name__)


class Spectator:
    def __init__(
        self,
        coord_host: str,
        coord_port: int,
        cluster: str,
        publishers: List[ShardMapPublisher],
        spectator_id: str = "spectator",
        standalone: bool = True,
        coord_fallbacks: Optional[List[Tuple[str, int]]] = None,
    ):
        self.cluster = cluster
        self.spectator_id = spectator_id
        self._standalone = standalone
        self.coord = CoordinatorClient(coord_host, coord_port,
                                       fallbacks=coord_fallbacks)
        self._publisher = DedupPublisher(ParallelPublisher(publishers))
        self._path = lambda *p: cluster_path(cluster, *p)
        self._kick = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"spectator-{spectator_id}", daemon=True
        )
        self._thread.start()
        self._watches = [
            self.coord.watch(self._path("currentstates"), self._on_change),
            self.coord.watch(self._path("instances"), self._on_change),
        ]

    def _on_change(self, _snap) -> None:
        self._kick.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                if self._standalone:
                    is_leader = (
                        self.coord.elect_leader(
                            self._path("spectator_election"), self.spectator_id
                        )
                        or self.coord.current_leader(
                            self._path("spectator_election")
                        ) == self.spectator_id
                    )
                    if not is_leader:
                        self._kick.wait(1.0)
                        self._kick.clear()
                        continue
                self.publish_once()
            except Exception:
                log.exception("spectator loop error")
            self._kick.wait(1.0)
            self._kick.clear()

    def publish_once(self) -> dict:
        shard_map = generate_shard_map(self.coord, self.cluster)
        self._publisher.publish(shard_map)
        return shard_map

    def stop(self) -> None:
        self._stop.set()
        self._kick.set()
        for w in self._watches:
            w.set()
        self._thread.join(timeout=5.0)
        self.coord.close()
