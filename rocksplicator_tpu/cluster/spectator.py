"""Spectator: watches the external view and regenerates shard maps.

Reference: Spectator.java:55-426 / DistributedSpectatorMain — a
leader-standby-elected process running ConfigGenerator on EXTERNAL_VIEW
changes; the embedded variant rides inside the participant process
(HelixCustomCodeRunner, Participant.java:449-466). Here both modes are one
class: standalone=True elects a leader among spectators so only one
publishes.
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional, Tuple

from ..testing import failpoints as fp
from ..utils.retry_policy import RetryPolicy, backoff_step, seeded_rng
from .config_generator import generate_shard_map
from .coordinator import CoordinatorClient
from .model import cluster_path
from .publishers import DedupPublisher, ParallelPublisher, ShardMapPublisher

log = logging.getLogger(__name__)

# control-plane refresh retry: growing jittered backoff, deterministic
# under RSTPU_RETRY_SEED (same contract as the follower pull loop)
_REFRESH_RETRY = RetryPolicy(max_attempts=1 << 30, base_delay=0.2,
                             max_delay=2.0, floor=0.1)


class Spectator:
    def __init__(
        self,
        coord_host: str,
        coord_port: int,
        cluster: str,
        publishers: List[ShardMapPublisher],
        spectator_id: str = "spectator",
        standalone: bool = True,
        coord_fallbacks: Optional[List[Tuple[str, int]]] = None,
        scrape_interval: float = 0.0,
    ):
        self.cluster = cluster
        self.spectator_id = spectator_id
        self._standalone = standalone
        self.coord = CoordinatorClient(coord_host, coord_port,
                                       fallbacks=coord_fallbacks)
        self._publisher = DedupPublisher(ParallelPublisher(publishers))
        self._path = lambda *p: cluster_path(cluster, *p)
        self._kick = threading.Event()
        self._stop = threading.Event()
        # cluster-wide stats plane (round 14): the latest published
        # shard map names every replica's replication endpoint, so the
        # spectator — already the fleet's external-view watcher — owns
        # the scrape loop. 0 = off (existing callers unchanged).
        self._last_shard_map: Optional[dict] = None
        self.cluster_stats: dict = {}
        self._scrape_interval = float(scrape_interval)
        self._aggregator = None
        self._scrape_thread: Optional[threading.Thread] = None
        self._thread = threading.Thread(
            target=self._run, name=f"spectator-{spectator_id}", daemon=True
        )
        self._thread.start()
        if self._scrape_interval > 0:
            self._scrape_thread = threading.Thread(
                target=self._scrape_loop,
                name=f"spectator-scrape-{spectator_id}", daemon=True)
            self._scrape_thread.start()
        self._watches = [
            self.coord.watch(self._path("currentstates"), self._on_change),
            self.coord.watch(self._path("instances"), self._on_change),
        ]

    def _on_change(self, _snap) -> None:
        self._kick.set()

    def _run(self) -> None:
        rng = seeded_rng()
        attempt = 0
        while not self._stop.is_set():
            try:
                if self._standalone:
                    is_leader = (
                        self.coord.elect_leader(
                            self._path("spectator_election"), self.spectator_id
                        )
                        or self.coord.current_leader(
                            self._path("spectator_election")
                        ) == self.spectator_id
                    )
                    if not is_leader:
                        self._kick.wait(1.0)
                        self._kick.clear()
                        continue
                self.publish_once()
                attempt = 0
            except Exception:
                log.exception("spectator loop error")
                # growing jittered backoff instead of the flat 1 s wait:
                # a wedged publisher/coordinator is retried politely and
                # visibly (retry.attempts op=spectator.publish on /stats)
                backoff_step(_REFRESH_RETRY, attempt,
                             op="spectator.publish", rng=rng)
                attempt += 1
            self._kick.wait(1.0)
            self._kick.clear()

    def publish_once(self) -> dict:
        # control plane touching durable state (the shard-map file /
        # coordinator node every router reads): a tripped fault here is
        # absorbed by the loop's retry backoff
        fp.hit("shardmap.publish")
        shard_map = generate_shard_map(self.coord, self.cluster)
        self._publisher.publish(shard_map)
        self._last_shard_map = shard_map
        return shard_map

    # -- cluster-wide stats scrape (round 14) ---------------------------

    def _scrape_loop(self) -> None:
        from ..utils.status_server import StatusServer
        from .stats_aggregator import (ClusterStatsAggregator,
                                       endpoints_from_shard_map)

        rng = seeded_rng()
        attempt = 0
        endpoint_registered = False
        while not self._stop.wait(self._scrape_interval):
            shard_map = self._last_shard_map
            if not shard_map:
                continue
            try:
                if self._aggregator is None:
                    self._aggregator = ClusterStatsAggregator()
                endpoints, per_db = endpoints_from_shard_map(shard_map)
                if endpoints:
                    stats = self._aggregator.scrape_and_aggregate(
                        endpoints, per_db)
                    # live shard moves (round 15): the movers write
                    # phase/bytes/lag progress into the coordinator's
                    # move ledger — surfacing it here is what lets an
                    # operator watch a move from /cluster_stats
                    stats["shard_moves"] = self._shard_moves()
                    # disaggregated compaction tier (round 18): live
                    # job ledger state — which shards have a published/
                    # claimed job, which worker holds it, heartbeat age
                    stats["remote_compactions"] = \
                        self._remote_compactions()
                    # hot-shard range splits + the rebalancer's own
                    # pause/decision status (round 20): the operator's
                    # one-stop view of WHY placement is changing
                    stats["shard_splits"] = self._shard_splits()
                    stats["rebalancer"] = self._rebalancer_status()
                    self.cluster_stats = stats
                if not endpoint_registered:
                    # serve /cluster_stats off this process's status
                    # server when one is running (never start one here —
                    # the embedding service owns that decision)
                    server = StatusServer._instance
                    if server is not None:
                        server.register_endpoint(
                            "/cluster_stats", self.cluster_stats_json)
                        endpoint_registered = True
                attempt = 0
            except Exception:
                log.exception("spectator stats scrape error")
                backoff_step(_REFRESH_RETRY, attempt,
                             op="spectator.scrape", rng=rng)
                attempt += 1

    def _shard_moves(self) -> dict:
        """Per-move progress (phase, bytes ingested, catch-up lag) from
        the coordinator move ledger (one scan implementation:
        shard_move.list_active_moves), plus the cluster-lifetime
        started/completed/aborted/resumed counters."""
        import json as _json

        from .shard_move import list_active_moves

        active = {
            rec.partition: {
                "move_id": rec.move_id, "phase": rec.phase,
                "source": rec.source, "target": rec.target,
                "bytes_ingested": rec.bytes_ingested,
                "catchup_lag": rec.catchup_lag,
                "updated_ms": rec.updated_ms,
            }
            for rec in list_active_moves(self.coord, self.cluster)
        }
        counters = {}
        raw = self.coord.get_or_none(self._path("moves_summary"))
        if raw:
            try:
                counters = _json.loads(bytes(raw).decode())
            except (ValueError, UnicodeDecodeError):
                counters = {}
        return {"active": active, "counters": counters}

    def _shard_splits(self) -> dict:
        """Split-ledger view: in-flight splits with phase/lag progress,
        ACTIVE splits as the permanent routing records they are, plus
        the cluster-lifetime started/completed/aborted/resumed
        counters (splits_summary) — the _shard_moves shape applied to
        the round-20 splitter."""
        import json as _json

        from ..utils.segment_utils import (db_name_to_partition_name,
                                           segment_to_db_name)
        from .shard_split import list_splits

        in_flight, active = {}, {}
        for rec in list_splits(self.coord, self.cluster):
            partition = db_name_to_partition_name(
                segment_to_db_name(rec.segment, rec.parent_shard))
            doc = {
                "split_id": rec.split_id, "phase": rec.phase,
                "split_key": rec.split_key,
                "low_shard": rec.low_shard, "high_shard": rec.high_shard,
                "target": rec.target_instance, "epoch": rec.epoch,
                "catchup_lag": rec.catchup_lag,
                "updated_ms": rec.updated_ms,
            }
            (active if rec.phase == "active" else in_flight)[partition] \
                = doc
        counters = {}
        raw = self.coord.get_or_none(self._path("splits_summary"))
        if raw:
            try:
                counters = _json.loads(bytes(raw).decode())
            except (ValueError, UnicodeDecodeError):
                counters = {}
        return {"in_flight": in_flight, "active": active,
                "counters": counters}

    def _rebalancer_status(self) -> dict:
        """The rebalancer's durable status document (pause flag, last
        decisions, per-shard EWMA snapshot) verbatim."""
        import json as _json

        raw = self.coord.get_or_none(self._path("rebalancer"))
        if raw:
            try:
                return _json.loads(bytes(raw).decode())
            except (ValueError, UnicodeDecodeError):
                pass
        return {}

    def _remote_compactions(self) -> dict:
        """Per-db remote compaction job state from the job ledger
        (jobs published/claimed + worker liveness) plus the cluster-
        lifetime published/claimed/installed/failed_over/fenced/reaped
        counters — the operator's /cluster_stats view of the
        disaggregated worker tier."""
        from ..compaction_remote.queue import CompactionJobQueue

        queue = CompactionJobQueue(self.coord)
        try:
            active = queue.active_jobs()
        except Exception:
            log.debug("remote-compaction ledger scan failed",
                      exc_info=True)
            active = {}
        return {"active": active, "counters": queue.read_summary()}

    def cluster_stats_json(self) -> str:
        import json

        return json.dumps(self.cluster_stats, indent=1, default=str)

    def stop(self) -> None:
        self._stop.set()
        self._kick.set()
        for w in self._watches:
            w.set()
        self._thread.join(timeout=5.0)
        if self._scrape_thread is not None:
            self._scrape_thread.join(timeout=5.0)
        if self._aggregator is not None:
            self._aggregator.close()  # drop the per-replica scrape sockets
            self._aggregator = None
        self.coord.close()
