"""Admin RPC helpers for the control plane — the Utils.java equivalent.

Reference: cluster_management Utils.java:132-606 — thrift client helpers to
the local/remote Admin service (addDB, closeDB, clearDB,
changeDBRoleAndUpStream, getLatestSequenceNumber, checkDB, backupDB(ToS3),
restoreDB(FromS3), ingestFromS3, compactDB, setDBOptions).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional, Tuple

from ..rpc.client_pool import RpcClientPool
from ..rpc.errors import RpcApplicationError, RpcError
from ..rpc.ioloop import IoLoop

log = logging.getLogger(__name__)


class AdminClient:
    """Sync helpers over the async RPC pool (one per control-plane actor)."""

    def __init__(self, ioloop: Optional[IoLoop] = None):
        self._ioloop = ioloop or IoLoop.default()
        self._pool = RpcClientPool()

    def call(self, addr: Tuple[str, int], method: str, timeout: float = 60.0,
             **args) -> Any:
        async def go():
            return await self._pool.call(
                addr[0], addr[1], method, args, timeout=timeout
            )

        return self._ioloop.run_sync(go(), timeout=timeout + 10)

    def close(self) -> None:
        self._ioloop.run_sync(self._pool.close())

    # -- Utils.java surface ------------------------------------------------

    def ping(self, addr) -> bool:
        try:
            return bool(self.call(addr, "ping", timeout=5.0).get("ok"))
        except (RpcError, RpcApplicationError):
            return False

    def add_db(self, addr, db_name: str, role: str = "FOLLOWER",
               upstream: Optional[Tuple[str, int]] = None,
               overwrite: bool = False, epoch: int = 0) -> None:
        args: Dict[str, Any] = {
            "db_name": db_name, "role": role, "overwrite": overwrite,
            "epoch": int(epoch),
        }
        if upstream:
            args["upstream_ip"], args["upstream_port"] = upstream
        self.call(addr, "add_db", **args)

    def close_db(self, addr, db_name: str) -> None:
        self.call(addr, "close_db", db_name=db_name)

    def clear_db(self, addr, db_name: str, reopen: bool = True) -> None:
        self.call(addr, "clear_db", db_name=db_name, reopen_db=reopen)

    def change_db_role_and_upstream(
        self, addr, db_name: str, new_role: str,
        upstream: Optional[Tuple[str, int]] = None,
        epoch: int = 0,
    ) -> None:
        args: Dict[str, Any] = {"db_name": db_name, "new_role": new_role,
                                "epoch": int(epoch)}
        if upstream:
            args["upstream_ip"], args["upstream_port"] = upstream
        self.call(addr, "change_db_role_and_upstream", **args)

    def check_pull_stall(self, addr, db_name: str) -> Optional[dict]:
        """Flags-only stall probe (no disk I/O server-side) for the
        participant's periodic heal loop."""
        try:
            return self.call(addr, "check_pull_stall", db_name=db_name,
                             timeout=5.0)
        except (RpcError, RpcApplicationError):
            return None

    def pause_db_writes(self, addr, db_name: str,
                        duration_ms: float) -> bool:
        """Arm (duration_ms>0) or clear (<=0) the shard's auto-expiring
        cutover write pause (live shard moves)."""
        return bool(self.call(addr, "pause_db_writes", db_name=db_name,
                              duration_ms=float(duration_ms),
                              timeout=10.0).get("paused"))

    def set_db_epoch(self, addr, db_name: str, epoch: int) -> None:
        """Raise the db's fencing epoch without a role transition (the
        sticky-leader adoption path)."""
        self.call(addr, "set_db_epoch", db_name=db_name, epoch=int(epoch),
                  timeout=10.0)

    def get_sequence_number(self, addr, db_name: str) -> Optional[int]:
        try:
            return int(self.call(addr, "get_sequence_number",
                                 db_name=db_name, timeout=10.0)["seq_num"])
        except (RpcError, RpcApplicationError):
            return None

    def check_db(self, addr, db_name: str) -> Optional[dict]:
        try:
            return self.call(addr, "check_db", db_name=db_name, timeout=10.0)
        except (RpcError, RpcApplicationError):
            return None

    def backup_db_to_store(self, addr, db_name: str, store_uri: str,
                           backup_path: str) -> dict:
        return self.call(addr, "backup_db_to_s3", db_name=db_name,
                         s3_bucket=store_uri, s3_backup_dir=backup_path,
                         timeout=600.0)

    def restore_db_from_store(
        self, addr, db_name: str, store_uri: str, backup_path: str,
        upstream: Optional[Tuple[str, int]] = None,
        to_seq: int = 0, role: str = "",
    ) -> dict:
        """``to_seq > 0`` = point-in-time restore: replay the backup's
        WAL archive over the newest checkpoint <= to_seq. ``role``
        overrides the post-restore registration role (shard moves
        restore their target as an ack-invisible OBSERVER)."""
        args: Dict[str, Any] = {
            "db_name": db_name, "s3_bucket": store_uri,
            "s3_backup_dir": backup_path,
        }
        if upstream:
            args["upstream_ip"], args["upstream_port"] = upstream
        if to_seq:
            args["to_seq"] = int(to_seq)
        if role:
            args["role"] = role
        return self.call(addr, "restore_db_from_s3", timeout=600.0, **args)

    def ingest_from_store(self, addr, db_name: str, store_uri: str,
                          sst_path: str, **kw) -> dict:
        return self.call(addr, "add_s3_sst_files_to_db", db_name=db_name,
                         s3_bucket=store_uri, s3_path=sst_path,
                         timeout=600.0, **kw)

    def rename_db(self, addr, db_name: str, new_db_name: str,
                  new_role: str = "",
                  upstream: Optional[Tuple[str, int]] = None,
                  epoch: int = 0, retain_lo: str = "",
                  retain_hi: str = "") -> None:
        """Flip a local full-copy to its child identity (shard-split
        cutover primitive): close → rename storage dir → reopen under
        the new name with the given role/upstream/epoch.
        ``retain_lo``/``retain_hi`` (hex, [lo, hi)) durably record the
        child's key range so its compactions trim the other half."""
        args: Dict[str, Any] = {"db_name": db_name,
                                "new_db_name": new_db_name,
                                "new_role": new_role, "epoch": int(epoch),
                                "retain_lo": retain_lo,
                                "retain_hi": retain_hi}
        if upstream:
            args["upstream_ip"], args["upstream_port"] = upstream
        self.call(addr, "rename_db", timeout=60.0, **args)

    def set_tenant_quota(self, addr, tenant: str, ops_per_sec: float,
                         bytes_per_sec: float) -> dict:
        """Override one tenant's admission quota on one node, live."""
        return self.call(addr, "set_tenant_quota", tenant=tenant,
                         ops_per_sec=float(ops_per_sec),
                         bytes_per_sec=float(bytes_per_sec), timeout=10.0)

    def compact_db(self, addr, db_name: str) -> None:
        self.call(addr, "compact_db", db_name=db_name, timeout=600.0)

    def set_db_options(self, addr, db_name: str, options: Dict) -> None:
        self.call(addr, "set_db_options", db_name=db_name, options=options)
