"""Live elastic shard moves: snapshot → bulk-ingest → WAL-tail catch-up
→ epoch-bumped flip, as a resumable step machine.

Reference: the Helix Bootstrap / backup+restore task flows plus the
ConfigGenerator shard-map publisher (PAPER.md L4) — the reference
relocates partitions on LIVE clusters by snapshotting a donor,
restoring the snapshot on the destination, catching the destination up,
and flipping the published shard map. This module composes the pieces
this repo already fault-proved into that operation:

- **snapshot** — the round-12 narrowed ``backup_db`` path (checkpoint
  under the per-db lock only; upload off the immutable hardlinked set);
- **bulk-ingest** — ``restore_db_from_s3`` on the target, whose bulk
  download rides the round-7 :class:`IngestGate` admission gate (a
  drain-node moving N shards pipelines transfers boundedly) and whose
  destroy→rename→reopen flip holds the per-db lock only briefly;
- **WAL-tail catch-up** — the target reopens as a *hidden* FOLLOWER of
  the live leader (registered on the data plane only — its participant
  publishes nothing, so the shard map never shows a half-built
  replica) and drains the tail through the leader's cached
  :class:`~rocksplicator_tpu.storage.wal.WalTailCursor` serve path;
- **cutover** — a brief auto-expiring write pause bounds the tail on a
  hot shard (``ReplicatedDB.pause_writes``), then a
  :class:`~rocksplicator_tpu.cluster.model.PlacementPin` steers the
  controller's OWN two-phase handoff at the target: demote →
  no-live-leader → epoch mint in the controller's durable ledger →
  promote → spectator/config_generator republish. The flip is therefore
  epoch-stamped end to end, and a source that was wedged through it
  demotes via the round-11 deposed-resync path when it heals;
- **retire** — a second pin drops the source replica; its participant
  runs Follower→Offline→Dropped and the move's snapshot garbage is
  swept from the store.

Every phase entry is recorded in a durable coordinator ledger
(``/clusters/<c>/moves/<partition>``) BEFORE the phase runs, so a mover
killed at any seam leaves the move either cleanly abortable (target
garbage swept, pin restored) or resumable (``ShardMove.resume``) —
never a half-flipped map. Failpoint seams (``move.record``,
``move.snapshot``, ``move.restore``, ``move.catchup``, ``move.flip``,
``move.retire``) let the chaos harness (``tools/chaos_soak.py
--reshard``) kill the mover at every phase and prove the sixth standing
invariant: exactly one serving lineage per shard, zero acked-write loss
across the move, bounded convergence.

:class:`DirectShardMove` is the coordinator-less variant (pure admin
RPCs against a static cluster) used by the macro-bench's mid-bench move
and by script-driven deployments without a control plane: same
snapshot/restore/catch-up phases, but the cutover mints the epoch from
the shard's live one and performs the promote/repoint/demote RPCs
itself.
"""

from __future__ import annotations

import json
import logging
import time
import uuid
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Tuple

from ..rpc.errors import RpcApplicationError, RpcError
from ..testing import failpoints as fp
from ..utils.objectstore import build_object_store
from ..utils.segment_utils import partition_name_to_db_name
from ..utils.stats import Stats, tagged
from .coordinator import CoordinatorClient
from .helix_utils import AdminClient
from .model import (InstanceInfo, PlacementPin, cluster_path,
                    decode_states)

log = logging.getLogger(__name__)

_LEADERLIKE = {"LEADER", "MASTER"}
_SERVING = _LEADERLIKE | {"FOLLOWER", "SLAVE"}

# phase order — the durable record's ``phase`` field always names the
# phase being (re)executed, written BEFORE the phase body runs
PHASES = ("planned", "snapshot", "restore", "catchup", "cutover",
          "retire")


class MoveError(RuntimeError):
    """A phase failed in a way the mover cannot ride through. The move
    record stays in the coordinator: the operator (or chaos harness)
    resumes or aborts it explicitly."""


class MoveInFlightError(MoveError):
    """A move for this partition is already recorded. Resume or abort
    the existing one; two movers on one partition are never allowed."""


@dataclass
class MoveRecord:
    """The durable move ledger entry — one per in-flight move, at
    ``/clusters/<cluster>/moves/<partition>``. Also what the Spectator
    surfaces on ``/cluster_stats`` (phase / bytes / lag progress)."""

    move_id: str
    partition: str
    db_name: str
    source: str                      # instance_id donating the replica
    target: str                      # instance_id receiving it
    store_uri: str
    snapshot_prefix: str
    phase: str = "planned"
    moving_leader: Optional[bool] = None  # decided at first cutover entry
    pin_before: Optional[str] = None      # raw pin JSON to restore on abort
    snapshot_seq: int = 0
    bytes_ingested: int = 0
    catchup_lag: int = -1
    started_ms: int = 0
    updated_ms: int = 0

    def encode(self) -> bytes:
        return json.dumps(asdict(self)).encode()

    @classmethod
    def decode(cls, raw: bytes) -> "MoveRecord":
        return cls(**json.loads(bytes(raw).decode()))


@dataclass
class MoveFlags:
    """Knobs; defaults sized for production-ish pacing, overridden small
    by the chaos harness and tests."""

    catchup_lag_threshold: int = 64     # enter cutover at lag <= this
    catchup_timeout: float = 120.0
    cutover_pause_ms: float = 3000.0    # the write pause bounding the tail
    cutover_attempts: int = 3           # pause windows tried before failing
    flip_timeout: float = 30.0          # pin write -> map flipped
    retire_timeout: float = 30.0
    poll_interval: float = 0.1
    record_update_interval: float = 0.5  # progress put pacing (catch-up)


def _phase_index(phase: str) -> int:
    return PHASES.index(phase) if phase in PHASES else -1


class ShardMove:
    """Coordinator-backed mover: drives one partition's replica from
    ``source`` to ``target`` under live traffic. Construct via
    :meth:`start` (new move) or :meth:`resume` (continue a recorded
    one); then :meth:`run` executes to completion. :meth:`abort` cleans
    up a pre-cutover move."""

    def __init__(self, coord: CoordinatorClient, cluster: str,
                 record: MoveRecord,
                 admin: Optional[AdminClient] = None,
                 flags: Optional[MoveFlags] = None):
        self.coord = coord
        self.cluster = cluster
        self.rec = record
        self.flags = flags or MoveFlags()
        self.admin = admin or AdminClient()
        self._owns_admin = admin is None
        self._path = lambda *p: cluster_path(cluster, *p)
        self._stats = Stats.get()
        self._gauge_names: List[str] = []
        self._last_record_put = 0.0
        self._resumed = False
        # (expiry, value) caches for the leader/target resolutions the
        # catch-up poll loop re-reads 10-20x/s — without them every poll
        # is an O(cluster) sweep of coordinator list+get round-trips
        # during the most latency-sensitive window of the move
        self._leader_cache: Tuple[float, Optional[Tuple[str,
                                                        InstanceInfo]]] \
            = (0.0, None)
        self._target_cache: Tuple[float, Optional[InstanceInfo]] \
            = (0.0, None)

    # -- construction ----------------------------------------------------

    @classmethod
    def start(cls, coord: CoordinatorClient, cluster: str, partition: str,
              source: str, target: str, store_uri: str,
              admin: Optional[AdminClient] = None,
              flags: Optional[MoveFlags] = None) -> "ShardMove":
        """Record and return a NEW move (phase ``planned``). Validates
        the endpoints against the live cluster and claims the
        partition's move slot — a second concurrent mover gets
        :class:`MoveInFlightError` from the create, never a second
        record."""
        move_id = uuid.uuid4().hex[:12]
        db_name = partition_name_to_db_name(partition)
        rec = MoveRecord(
            move_id=move_id, partition=partition, db_name=db_name,
            source=source, target=target, store_uri=store_uri,
            snapshot_prefix=f"moves/{db_name}/{move_id}",
            started_ms=int(time.time() * 1000),
        )
        mv = cls(coord, cluster, rec, admin=admin, flags=flags)
        try:
            mv._validate_plan()
            pin_raw = coord.get_or_none(
                mv._path("placements", partition))
            if pin_raw is not None:
                rec.pin_before = bytes(pin_raw).decode()
            fp.hit("move.record")
            coord.create(mv._record_path(), rec.encode())
        except RpcApplicationError as e:
            mv.close()
            if e.code == "NODE_EXISTS":
                raise MoveInFlightError(
                    f"{partition}: a move is already recorded — resume "
                    f"or abort it first") from e
            raise
        except BaseException:
            mv.close()
            raise
        mv._stats.incr("shard_moves.started")
        mv._bump_summary("started")
        return mv

    @classmethod
    def resume(cls, coord: CoordinatorClient, cluster: str,
               partition: str, admin: Optional[AdminClient] = None,
               flags: Optional[MoveFlags] = None) -> "ShardMove":
        """Load the recorded move for ``partition`` and return a mover
        that will continue from the recorded phase (the phase itself
        restarts from its top — every phase body is idempotent)."""
        raw = coord.get_or_none(
            cluster_path(cluster, "moves", partition))
        if raw is None:
            raise MoveError(f"{partition}: no move recorded")
        mv = cls(coord, cluster, MoveRecord.decode(raw), admin=admin,
                 flags=flags)
        # counted when run() actually continues the move — an operator
        # loading the record just to abort() is not a resume
        mv._resumed = True
        return mv

    # -- plumbing --------------------------------------------------------

    def _record_path(self) -> str:
        return self._path("moves", self.rec.partition)

    def _save(self, phase: Optional[str] = None, force: bool = True) -> None:
        """Write-ahead the move record. Phase transitions always write;
        in-phase progress updates (catch-up lag) are paced by
        ``record_update_interval``."""
        now = time.monotonic()
        if phase is not None:
            self.rec.phase = phase
        elif not force and (now - self._last_record_put
                            < self.flags.record_update_interval):
            return
        self.rec.updated_ms = int(time.time() * 1000)
        fp.hit("move.record")
        self.coord.put(self._record_path(), self.rec.encode())
        self._last_record_put = now

    def _bump_summary(self, key: str) -> None:
        """Cluster-wide move counters the Spectator surfaces. Best
        effort (read-modify-write; one mover per partition, and a lost
        increment is a cosmetic stat, never a correctness input)."""
        path = self._path("moves_summary")
        try:
            raw = self.coord.get_or_none(path)
            d = json.loads(bytes(raw).decode()) if raw else {}
            d[key] = int(d.get(key, 0)) + 1
            self.coord.put(path, json.dumps(d).encode())
        except Exception:
            log.debug("moves_summary bump failed", exc_info=True)

    def _instances(self) -> Dict[str, InstanceInfo]:
        out: Dict[str, InstanceInfo] = {}
        for iid in self.coord.list(self._path("instances")):
            raw = self.coord.get_or_none(self._path("instances", iid))
            if raw:
                out[iid] = InstanceInfo.decode(raw)
        return out

    def _states(self) -> Dict[str, str]:
        """instance_id -> current state for THIS partition."""
        out: Dict[str, str] = {}
        for iid in self.coord.list(self._path("currentstates")):
            st = decode_states(self.coord.get_or_none(
                self._path("currentstates", iid))).get(self.rec.partition)
            if st:
                out[iid] = st
        return out

    def _leader(self, cached: bool = False
                ) -> Optional[Tuple[str, InstanceInfo]]:
        """(iid, info) of the partition's live leader. Leadership can
        move mid-move (that is the point of the chaos schedules), so
        every use re-resolves — but the catch-up POLL loops pass
        ``cached`` to reuse a ~1s-old answer instead of sweeping every
        coordinator node 10-20x/s for the whole drain window (a None
        answer is never cached, so failover discovery stays prompt)."""
        now = time.monotonic()
        if cached and now < self._leader_cache[0]:
            return self._leader_cache[1]
        instances = self._instances()
        result = None
        for iid, st in self._states().items():
            if st in _LEADERLIKE and iid in instances:
                result = (iid, instances[iid])
                break
        if result is not None:
            self._leader_cache = (now + 1.0, result)
        return result

    def _admin_addr(self, info: InstanceInfo) -> Tuple[str, int]:
        return (info.host, info.admin_port)

    def _seq(self, info: InstanceInfo) -> Optional[int]:
        return self.admin.get_sequence_number(
            self._admin_addr(info), self.rec.db_name)

    def _target_info(self) -> InstanceInfo:
        info = self._instances().get(self.rec.target)
        if info is None:
            raise MoveError(
                f"{self.rec.partition}: target {self.rec.target} is not "
                f"a live instance")
        return info

    def _validate_plan(self) -> None:
        instances = self._instances()
        states = self._states()
        if self.rec.source not in instances:
            raise MoveError(f"source {self.rec.source} is not live")
        if self.rec.target not in instances:
            raise MoveError(f"target {self.rec.target} is not live")
        if states.get(self.rec.source) not in _SERVING:
            raise MoveError(
                f"source {self.rec.source} does not serve "
                f"{self.rec.partition} (state {states.get(self.rec.source)})")
        if self.rec.target in states:
            raise MoveError(
                f"target {self.rec.target} already serves "
                f"{self.rec.partition}")
        # also probe the target's ADMIN plane: a hidden (currentstate-
        # invisible) replica left by an interrupted earlier move must
        # never be silently adopted as this move's restore — its data
        # could be a stale diverged lineage
        if self._seq(instances[self.rec.target]) is not None:
            raise MoveError(
                f"target {self.rec.target} already holds a "
                f"{self.rec.db_name} replica (leftover from an earlier "
                f"move?) — sweep it first (clear_db)")

    def _register_gauges(self) -> None:
        stats = self._stats
        db = self.rec.db_name
        for name, fn in (
            (tagged("shard_move.phase", db=db),
             lambda: float(_phase_index(self.rec.phase))),
            (tagged("shard_move.bytes_ingested", db=db),
             lambda: float(self.rec.bytes_ingested)),
            (tagged("shard_move.catchup_lag", db=db),
             lambda: float(self.rec.catchup_lag)),
        ):
            stats.add_gauge(name, fn)
            self._gauge_names.append(name)

    def _unregister_gauges(self) -> None:
        for name in self._gauge_names:
            self._stats.remove_gauge(name)
        self._gauge_names = []

    # -- the step machine ------------------------------------------------

    def run(self) -> MoveRecord:
        """Execute (or continue) the move to DONE. Raises MoveError on
        an unrecoverable phase failure — the record stays durable and a
        later resume()/abort() picks it up."""
        order = {p: i for i, p in enumerate(PHASES)}
        start_at = order.get(self.rec.phase, 0)
        if self._resumed:
            self._resumed = False
            self._stats.incr("shard_moves.resumed")
            self._bump_summary("resumed")
        self._register_gauges()
        try:
            if start_at <= order["snapshot"]:
                self._save("snapshot")
                self._phase_snapshot()
            if start_at <= order["restore"]:
                self._save("restore")
                self._phase_restore()
            if start_at <= order["catchup"]:
                self._save("catchup")
                self._phase_catchup()
            if start_at <= order["cutover"]:
                self._save("cutover")
                self._phase_cutover()
            self._save("retire")
            self._phase_retire()
            self._finish()
            self.close()
            return self.rec
        finally:
            # NOTE: an owned admin client is NOT closed on a failed run
            # — the record is still live and abort()/retries on this
            # instance must keep a working client; close() runs on
            # the success path and at abort.
            self._unregister_gauges()

    def close(self) -> None:
        if self._owns_admin:
            self.admin.close()
            self._owns_admin = False

    # each phase is idempotent: resume() re-enters the recorded phase
    # from its top, and every step either re-checks before acting or is
    # naturally repeatable (incremental backup, pin put, state waits)

    def _phase_snapshot(self) -> None:
        fp.hit("move.snapshot")
        rec = self.rec
        source = self._instances().get(rec.source)
        donor = source
        if donor is None:
            # the donor died mid-move: snapshot from the live leader
            # instead (any replica is a valid checkpoint donor)
            led = self._leader()
            if led is None:
                raise MoveError(f"{rec.partition}: no live donor for "
                                f"snapshot (source dead, no leader)")
            donor = led[1]
        r = self.admin.backup_db_to_store(
            self._admin_addr(donor), rec.db_name, rec.store_uri,
            rec.snapshot_prefix)
        rec.snapshot_seq = int(r.get("seq") or 0)
        self._save()

    def _phase_restore(self) -> None:
        fp.hit("move.restore")
        rec = self.rec
        target = self._target_info()
        existing = self._seq(target)
        if existing is not None and existing >= rec.snapshot_seq > 0:
            # resume: the restore already materialized (we crashed after
            # the flip-and-register step) — don't destroy the catch-up
            log.info("%s: target already at seq %d >= snapshot %d; "
                     "restore skipped", rec.partition, existing,
                     rec.snapshot_seq)
            return
        led = self._leader()
        if led is None:
            raise MoveError(f"{rec.partition}: no live leader to tail "
                            f"from after restore")
        _iid, leader = led
        # upstream = the LIVE LEADER: the hidden replica's WAL-tail
        # catch-up pulls straight from the lineage head (the leader's
        # serve path streams from its cached WalTailCursor), and the
        # round-13 leader resolver repoints it if leadership moves.
        # Role OBSERVER: catch-up pulls must NOT count toward semi-sync
        # acks — a write acked solely by a half-built replica that an
        # aborted move then sweeps would be an acked-write loss.
        self.admin.restore_db_from_store(
            self._admin_addr(target), rec.db_name, rec.store_uri,
            rec.snapshot_prefix,
            upstream=(leader.host, leader.repl_port), role="OBSERVER")
        info = self.admin.check_db(self._admin_addr(target), rec.db_name)
        if info:
            rec.bytes_ingested = int(info.get("db_size_bytes") or 0)
        self._save()

    def _catchup_lag(self) -> Optional[int]:
        """leader_seq - target_seq, or None when either side is
        unreadable this instant. Polled 10-20x/s: resolutions ride the
        ~1s caches; a seq-read failure drops them so the next poll
        re-resolves (leadership moved / target bounced)."""
        led = self._leader(cached=True)
        if led is None:
            return None
        now = time.monotonic()
        if now < self._target_cache[0]:
            target = self._target_cache[1]
        else:
            target = self._instances().get(self.rec.target)
            if target is not None:
                self._target_cache = (now + 1.0, target)
        if target is None:
            raise MoveError(f"{self.rec.partition}: target died during "
                            f"catch-up")
        lseq = self._seq(led[1])
        tseq = self._seq(target)
        if lseq is None or tseq is None:
            self._leader_cache = (0.0, None)
            self._target_cache = (0.0, None)
            return None
        return max(0, lseq - tseq)

    def _phase_catchup(self) -> None:
        fp.hit("move.catchup")
        rec, flags = self.rec, self.flags
        deadline = time.monotonic() + flags.catchup_timeout
        while True:
            lag = self._catchup_lag()
            if lag is not None:
                rec.catchup_lag = lag
                self._save(force=False)
                if lag <= flags.catchup_lag_threshold:
                    self._save()
                    return
            if time.monotonic() > deadline:
                raise MoveError(
                    f"{rec.partition}: catch-up lag {rec.catchup_lag} "
                    f"never reached threshold "
                    f"{flags.catchup_lag_threshold} within "
                    f"{flags.catchup_timeout}s")
            time.sleep(flags.poll_interval)

    def _current_pin(self) -> Optional[PlacementPin]:
        return PlacementPin.decode(self.coord.get_or_none(
            self._path("placements", self.rec.partition)))

    def _put_pin(self, pin: PlacementPin) -> None:
        self.coord.put(self._path("placements", self.rec.partition),
                       pin.encode())

    def _phase_cutover(self) -> None:
        """The fenced flip. With the tail bounded by the write pause,
        pin the placement at the target: the controller's own two-phase
        handoff demotes the source, mints the epoch bump in its durable
        ledger, promotes the target (whose Follower→Leader transition
        re-verifies exact catch-up at margin=0), and the spectator's
        config_generator republishes the map — every stamp and guard a
        failover gets, because it IS the failover machinery."""
        fp.hit("move.flip")
        rec = self.rec
        if rec.moving_leader is None:
            states = self._states()
            rec.moving_leader = states.get(rec.source) in _LEADERLIKE
            self._save()
        target = self._target_info()
        if self._seq(target) is None:
            raise MoveError(f"{rec.partition}: target no longer hosts "
                            f"{rec.db_name} at cutover")
        if rec.moving_leader:
            self._cutover_drain()
        hosting = [iid for iid, st in self._states().items()
                   if st in _SERVING]
        replicas = sorted(set(hosting) | {rec.target})
        self._put_pin(PlacementPin(
            replicas=replicas,
            preferred_leader=rec.target if rec.moving_leader else None,
            move_id=rec.move_id))
        self._await_flip()

    def _cutover_drain(self) -> None:
        """Pause source-side ingress and drain the WAL tail to exact
        equality — the guard that makes the flip lossless-by-
        construction on a hot shard (and the one the chaos harness's
        ``move_flip`` tooth breaks to prove it is load-bearing)."""
        rec, flags = self.rec, self.flags
        last_lag = None
        for attempt in range(flags.cutover_attempts):
            led = self._leader()
            if led is None:
                # mid-failover: no acking leader, nothing to drain — the
                # promotion machinery will finish the catch-up exactly
                return
            _iid, leader = led
            try:
                self.admin.pause_db_writes(
                    self._admin_addr(leader), rec.db_name,
                    flags.cutover_pause_ms)
            except (RpcError, RpcApplicationError):
                continue  # leader moved/unreachable: re-resolve and retry
            pause_deadline = (time.monotonic()
                              + flags.cutover_pause_ms / 1000.0)
            while time.monotonic() < pause_deadline:
                lag = self._catchup_lag()
                if lag is not None:
                    last_lag = lag
                    rec.catchup_lag = lag
                    if lag == 0:
                        return  # tail drained; pause expires on its own
                time.sleep(flags.poll_interval)
        raise MoveError(
            f"{rec.partition}: WAL tail never drained to 0 across "
            f"{flags.cutover_attempts} pause windows (last lag "
            f"{last_lag})")

    def _await_flip(self) -> None:
        rec, flags = self.rec, self.flags
        deadline = time.monotonic() + flags.flip_timeout
        while time.monotonic() < deadline:
            states = self._states()
            st = states.get(rec.target)
            if rec.moving_leader:
                if st in _LEADERLIKE:
                    return
            elif st in _SERVING:
                return
            time.sleep(flags.poll_interval)
        raise MoveError(
            f"{rec.partition}: map never flipped to {rec.target} "
            f"within {flags.flip_timeout}s (states {self._states()})")

    def _phase_retire(self) -> None:
        fp.hit("move.retire")
        rec, flags = self.rec, self.flags
        pin = self._current_pin()
        replicas = (pin.replicas if pin is not None
                    else []) or [rec.target]
        if rec.source in replicas:
            replicas = [iid for iid in replicas if iid != rec.source]
            self._put_pin(PlacementPin(
                replicas=replicas,
                preferred_leader=(rec.target if rec.moving_leader
                                  else None),
                move_id=rec.move_id))
        deadline = time.monotonic() + flags.retire_timeout
        while time.monotonic() < deadline:
            if rec.source not in self._instances():
                return  # dead source: it will drop on rejoin (DROPPED
                # assignment); the map already excludes it
            if self._states().get(rec.source) is None:
                return
            time.sleep(flags.poll_interval)
        raise MoveError(
            f"{rec.partition}: source {rec.source} never dropped the "
            f"partition within {flags.retire_timeout}s")

    def _finish(self) -> None:
        # release the leadership preference: it existed only to drive
        # the flip. Leaving it standing would steer every LATER failover
        # back toward this target — including one that has since lost
        # its data (observed cascading in the reshard chaos). The
        # replica-set pin itself stays: that IS the placement now.
        pin = self._current_pin()
        if pin is not None and pin.preferred_leader is not None:
            self._put_pin(PlacementPin(replicas=pin.replicas,
                                       preferred_leader=None,
                                       move_id=self.rec.move_id))
        self._sweep_snapshot()
        fp.hit("move.record")
        self.coord.delete_if_exists(self._record_path())
        self._stats.incr("shard_moves.completed")
        self._bump_summary("completed")
        log.info("%s: move %s complete (%s -> %s)", self.rec.partition,
                 self.rec.move_id, self.rec.source, self.rec.target)

    def _sweep_snapshot(self) -> None:
        """Delete the move's snapshot objects — the garbage sweep that
        keeps repeated/aborted moves from filling the store (same
        hygiene as the admin handler's staging-dir sweep)."""
        try:
            store = build_object_store(self.rec.store_uri)
            for key in store.list_objects(
                    self.rec.snapshot_prefix.rstrip("/") + "/"):
                store.delete_object(key)
        except Exception:
            log.warning("%s: snapshot sweep failed (prefix %s)",
                        self.rec.partition, self.rec.snapshot_prefix,
                        exc_info=True)

    # -- abort -----------------------------------------------------------

    def abort(self) -> None:
        """Cleanly unwind a PRE-cutover move: the target's half-built
        replica is closed and destroyed, the snapshot prefix swept, the
        pre-move pin restored, and the move record deleted. A move at
        or past cutover has already asked the controller to flip — the
        only safe direction is forward (resume)."""
        rec = self.rec
        if _phase_index(rec.phase) >= _phase_index("cutover"):
            raise MoveError(
                f"{rec.partition}: move already at {rec.phase} — past "
                f"the point of no return; resume it instead")
        # target garbage FIRST, and the record is only deleted once the
        # sweep succeeded: deleting it past a failed sweep would destroy
        # the only resume/abort handle to a still-registered hidden
        # OBSERVER (the stranded-replica state the sixth invariant
        # forbids). A LIVE-but-unreachable target keeps the record — the
        # operator retries the abort; a DEAD target cannot be swept by
        # anyone, so the abort proceeds (its half-built replica is disk
        # state only: nothing re-registers it when the node returns).
        target = self._instances().get(rec.target)
        if target is not None:
            try:
                self.admin.clear_db(self._admin_addr(target),
                                    rec.db_name, reopen=False)
            except (RpcError, RpcApplicationError) as e:
                if getattr(e, "code", None) != "DB_NOT_FOUND":
                    raise MoveError(
                        f"{rec.partition}: abort could not sweep the "
                        f"target replica on {rec.target} ({e!r}) — "
                        f"record kept, retry the abort") from e
        else:
            log.warning("%s: abort with target %s not live — its "
                        "half-built replica is unreachable and will "
                        "remain as disk state", rec.partition, rec.target)
        try:
            self._sweep_snapshot()
            if rec.pin_before is not None:
                self.coord.put(self._path("placements", rec.partition),
                               rec.pin_before.encode())
            else:
                self.coord.delete_if_exists(
                    self._path("placements", rec.partition))
        finally:
            fp.hit("move.record")
            self.coord.delete_if_exists(self._record_path())
            self._stats.incr("shard_moves.aborted")
            self._bump_summary("aborted")
            self.close()
        log.info("%s: move %s aborted at phase %s", rec.partition,
                 rec.move_id, rec.phase)


# ---------------------------------------------------------------------------
# drain-node: move every replica off one instance
# ---------------------------------------------------------------------------


def list_active_moves(coord: CoordinatorClient,
                      cluster: str) -> List[MoveRecord]:
    out: List[MoveRecord] = []
    for p in coord.list(cluster_path(cluster, "moves")):
        raw = coord.get_or_none(cluster_path(cluster, "moves", p))
        if raw:
            try:
                out.append(MoveRecord.decode(raw))
            except (ValueError, TypeError, UnicodeDecodeError):
                continue
    return out


def _scraped_shard_stats(coord: CoordinatorClient,
                         cluster: str) -> Optional[Dict[str, dict]]:
    """db_name -> the full aggregated per-shard stats record (1-minute
    read/write rates, ``max_applied_seq_lag``, worst-replica
    ``compaction_debt_bytes``, ...) from a one-shot ``/cluster_stats``
    scrape of every replica named by the PUBLISHED shard map
    (coordinator ``shardmap`` node, the spectator's output). None when
    no map is published, no replica answers, or the scrape faults —
    callers fall back to shard counts. This is the round-14 hot-spot
    sensor feeding both drain-node target ranking and the rebalancer's
    composite score."""
    raw = coord.get_or_none(cluster_path(cluster, "shardmap"))
    if not raw:
        return None
    try:
        shard_map = json.loads(raw.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return None
    from .stats_aggregator import (ClusterStatsAggregator,
                                   endpoints_from_shard_map)

    endpoints, per_db = endpoints_from_shard_map(shard_map)
    if not endpoints:
        return None
    agg = ClusterStatsAggregator()
    try:
        doc = agg.scrape_and_aggregate(endpoints, per_db)
    except Exception:
        log.warning("drain: cluster-stats scrape failed; falling back "
                    "to shard counts", exc_info=True)
        return None
    finally:
        agg.close()
    if not doc.get("replicas_scraped"):
        return None
    return dict(doc.get("per_shard") or {})


def _scraped_shard_load(coord: CoordinatorClient,
                        cluster: str) -> Optional[Dict[str, float]]:
    """db_name -> (read+write) 1-minute rate — the rate-only fold of
    ``_scraped_shard_stats`` (drain-node's ranking signal)."""
    per_shard = _scraped_shard_stats(coord, cluster)
    if per_shard is None:
        return None
    return {db: (float(rec.get("read_rate_1m", 0.0))
                 + float(rec.get("write_rate_1m", 0.0)))
            for db, rec in per_shard.items()}


def drain_node(coord: CoordinatorClient, cluster: str, node: str,
               store_uri: str, admin: Optional[AdminClient] = None,
               flags: Optional[MoveFlags] = None,
               log_fn=log.info) -> List[MoveRecord]:
    """Move every partition ``node`` serves to other live instances —
    the minimal whole-node evacuation built on move-shard. Targets are
    chosen least-LOADED-first (round 19): candidates are ranked by the
    scraped per-shard serving load they already carry (the round-14
    ``/cluster_stats`` read/write hot-spot ranking), with shard count
    as the tie-break and as the fallback whenever the map or the
    scrape is unavailable. Sequential by design: an evacuation should
    trickle, not trample serving traffic — the per-move IngestGate and
    write-pause bounds apply to each step."""
    path = lambda *p: cluster_path(cluster, *p)  # noqa: E731
    states_of = {}
    for iid in coord.list(path("currentstates")):
        states_of[iid] = decode_states(
            coord.get_or_none(path("currentstates", iid)))
    instances = set()
    for iid in coord.list(path("instances")):
        if coord.get_or_none(path("instances", iid)) is not None:
            instances.add(iid)
    partitions = [p for p, st in states_of.get(node, {}).items()
                  if st in _SERVING]
    if not partitions:
        log_fn(f"drain {node}: nothing to move")
        return []
    db_load = _scraped_shard_load(coord, cluster)
    if db_load is not None:
        log_fn(f"drain {node}: ranking targets by scraped per-shard "
               f"load ({len(db_load)} shard(s) reporting)")
    done: List[MoveRecord] = []
    for partition in sorted(partitions):
        hosting = {iid for iid, st in states_of.items()
                   if st.get(partition) in _SERVING}
        candidates = [iid for iid in instances
                      if iid != node and iid not in hosting]
        if not candidates:
            raise MoveError(
                f"drain {node}: no candidate instance for {partition} "
                f"(every live node already hosts it)")
        counts = {iid: sum(1 for st in states_of.get(iid, {}).values()
                           if st in _SERVING) for iid in candidates}
        if db_load is not None:
            # an instance's load = the scraped 1m read+write rate summed
            # over the partitions it currently SERVES; rounding absorbs
            # scrape noise so near-equal instances fall through to the
            # shard-count tie-break instead of thrashing on jitter
            served = {iid: round(sum(
                db_load.get(partition_name_to_db_name(p), 0.0)
                for p, st in states_of.get(iid, {}).items()
                if st in _SERVING), 1) for iid in candidates}
            target = min(candidates,
                         key=lambda iid: (served[iid], counts[iid], iid))
        else:
            target = min(candidates,
                         key=lambda iid: (counts[iid], iid))
        log_fn(f"drain {node}: moving {partition} -> {target}")
        mv = ShardMove.start(coord, cluster, partition, node, target,
                             store_uri, admin=admin, flags=flags)
        done.append(mv.run())
        # refresh state: the completed move changed hosting + load
        for iid in (node, target):
            states_of[iid] = decode_states(
                coord.get_or_none(path("currentstates", iid)))
    log_fn(f"drain {node}: {len(done)} partition(s) moved")
    return done


# ---------------------------------------------------------------------------
# DirectShardMove: coordinator-less variant (macro-bench / static clusters)
# ---------------------------------------------------------------------------


@dataclass
class DirectNode:
    host: str
    admin_port: int
    repl_port: int

    @property
    def admin_addr(self) -> Tuple[str, int]:
        return (self.host, self.admin_port)


@dataclass
class DirectMovePlan:
    db_name: str
    source: DirectNode            # node donating the replica
    target: DirectNode            # node receiving it
    leader: DirectNode            # current leader (== source for a
    # leader move)
    followers: List[DirectNode] = field(default_factory=list)  # other
    # replicas to repoint on a leader flip (excluding source/target)
    store_uri: str = ""
    snapshot_prefix: str = ""


class DirectShardMove:
    """The same snapshot → restore → catch-up → flip sequence driven by
    plain admin RPCs against a static (coordinator-less) cluster: the
    macro-bench's mid-bench move and script-driven deployments. The
    cutover here mints the epoch bump itself (live epoch + 1, stamped
    on every promote/repoint/demote RPC) since there is no controller
    ledger to do it; the write pause plays the same tail-bounding role.
    """

    def __init__(self, plan: DirectMovePlan,
                 admin: Optional[AdminClient] = None,
                 flags: Optional[MoveFlags] = None):
        self.plan = plan
        self.flags = flags or MoveFlags()
        self.admin = admin or AdminClient()
        self._owns_admin = admin is None
        if not self.plan.snapshot_prefix:
            self.plan.snapshot_prefix = (
                f"moves/{plan.db_name}/{uuid.uuid4().hex[:12]}")
        self.timings_ms: Dict[str, float] = {}

    def _timed(self, name: str, fn) -> None:
        t0 = time.monotonic()
        fn()
        self.timings_ms[name] = round((time.monotonic() - t0) * 1e3, 1)

    def run(self) -> Dict[str, float]:
        try:
            self._timed("snapshot", self._snapshot)
            self._timed("restore", self._restore)
            self._timed("catchup", self._catchup)
            self._timed("cutover", self._cutover)
            self._timed("retire", self._retire)
            return dict(self.timings_ms)
        finally:
            if self._owns_admin:
                self.admin.close()
                self._owns_admin = False

    def _snapshot(self) -> None:
        fp.hit("move.snapshot")
        p = self.plan
        self.admin.backup_db_to_store(
            p.source.admin_addr, p.db_name, p.store_uri,
            p.snapshot_prefix)

    def _restore(self) -> None:
        fp.hit("move.restore")
        p = self.plan
        self.admin.restore_db_from_store(
            p.target.admin_addr, p.db_name, p.store_uri,
            p.snapshot_prefix, upstream=(p.leader.host,
                                         p.leader.repl_port),
            role="OBSERVER")

    def _lag(self) -> Optional[int]:
        p = self.plan
        lseq = self.admin.get_sequence_number(p.leader.admin_addr,
                                              p.db_name)
        tseq = self.admin.get_sequence_number(p.target.admin_addr,
                                              p.db_name)
        if lseq is None or tseq is None:
            return None
        return max(0, lseq - tseq)

    def _catchup(self) -> None:
        fp.hit("move.catchup")
        flags = self.flags
        deadline = time.monotonic() + flags.catchup_timeout
        while True:
            lag = self._lag()
            if lag is not None and lag <= flags.catchup_lag_threshold:
                return
            if time.monotonic() > deadline:
                raise MoveError(
                    f"{self.plan.db_name}: direct catch-up stuck at lag "
                    f"{lag} past {flags.catchup_timeout}s")
            time.sleep(flags.poll_interval)

    def _cutover(self) -> None:
        fp.hit("move.flip")
        p, flags = self.plan, self.flags
        moving_leader = (p.source.admin_addr == p.leader.admin_addr)
        if moving_leader:
            # pause, drain to exact equality, then promote under a
            # bumped epoch — the deposed source fences on the first
            # stale frame it sees
            drained = False
            for _attempt in range(flags.cutover_attempts):
                self.admin.pause_db_writes(
                    p.leader.admin_addr, p.db_name,
                    flags.cutover_pause_ms)
                pause_deadline = (time.monotonic()
                                  + flags.cutover_pause_ms / 1000.0)
                while time.monotonic() < pause_deadline:
                    if self._lag() == 0:
                        drained = True
                        break
                    time.sleep(flags.poll_interval)
                if drained:
                    break
            if not drained:
                raise MoveError(f"{p.db_name}: direct cutover never "
                                f"drained the tail")
            info = self.admin.check_db(p.leader.admin_addr, p.db_name)
            epoch = int((info or {}).get("epoch") or 0) + 1
            # FAIL-STOP ordering: demote the source BEFORE promoting
            # the target. A mover that dies (or an RPC that fails)
            # anywhere in this sequence then leaves the shard
            # LEADERLESS — writes refused until an operator re-promotes
            # — never with two live leaders. (The old promote-first
            # order claimed the source would end up fenced, but a
            # demote-RPC failure left it an unfenced LEADER whose pause
            # simply expired: nothing ever delivers the new epoch to a
            # leader nobody pulls from.)
            self.admin.change_db_role_and_upstream(
                p.source.admin_addr, p.db_name, "FOLLOWER",
                (p.target.host, p.target.repl_port), epoch=epoch)
            self.admin.change_db_role_and_upstream(
                p.target.admin_addr, p.db_name, "LEADER", epoch=epoch)
            for fol in p.followers:
                self.admin.change_db_role_and_upstream(
                    fol.admin_addr, p.db_name, "FOLLOWER",
                    (p.target.host, p.target.repl_port), epoch=epoch)
        else:
            # follower move: no leadership flip — the target just joins
            # the ack set (OBSERVER -> FOLLOWER) before the source
            # retires, so replication strength never dips
            self.admin.change_db_role_and_upstream(
                p.target.admin_addr, p.db_name, "FOLLOWER",
                (p.leader.host, p.leader.repl_port))

    def _retire(self) -> None:
        fp.hit("move.retire")
        p = self.plan
        try:
            self.admin.clear_db(p.source.admin_addr, p.db_name,
                                reopen=False)
        except (RpcError, RpcApplicationError) as e:
            if getattr(e, "code", None) != "DB_NOT_FOUND":
                raise
        try:
            store = build_object_store(p.store_uri)
            for key in store.list_objects(
                    p.snapshot_prefix.rstrip("/") + "/"):
                store.delete_object(key)
        except Exception:
            log.warning("%s: direct move snapshot sweep failed",
                        p.db_name, exc_info=True)
