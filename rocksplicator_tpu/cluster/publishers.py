"""Shard-map publishers.

Reference: cluster_management publisher/ — local file dump, HTTP post,
dedup wrapper, parallel fan-out, ZK per-resource publisher. Here: local
file (what data-plane routers watch), coordinator node, callback, dedup
and parallel combinators.
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Callable, Dict, List

from ..utils.misc import write_file_atomic
from .model import cluster_path

log = logging.getLogger(__name__)


class ShardMapPublisher:
    def publish(self, shard_map: Dict) -> None:
        raise NotImplementedError


class LocalFilePublisher(ShardMapPublisher):
    """Writes the JSON map to a file — routers hot-reload it (the reference
    shard-map-file contract)."""

    def __init__(self, path: str):
        self._path = path

    def publish(self, shard_map: Dict) -> None:
        write_file_atomic(
            self._path, json.dumps(shard_map, sort_keys=True).encode()
        )


class CoordinatorNodePublisher(ShardMapPublisher):
    """Publishes into the coordinator tree (the ZK-publisher analog) for
    shard-map agents to sync down."""

    def __init__(self, coord, cluster: str):
        self._coord = coord
        self._cluster = cluster

    def publish(self, shard_map: Dict) -> None:
        self._coord.put(
            cluster_path(self._cluster, "shardmap"),
            json.dumps(shard_map, sort_keys=True).encode(),
        )


class CallbackPublisher(ShardMapPublisher):
    def __init__(self, fn: Callable[[Dict], None]):
        self._fn = fn

    def publish(self, shard_map: Dict) -> None:
        self._fn(shard_map)


class DedupPublisher(ShardMapPublisher):
    """Suppresses republishing identical maps (dedup wrapper)."""

    def __init__(self, inner: ShardMapPublisher):
        self._inner = inner
        self._last: str = ""
        self._lock = threading.Lock()

    def publish(self, shard_map: Dict) -> None:
        encoded = json.dumps(shard_map, sort_keys=True)
        with self._lock:
            if encoded == self._last:
                return
            self._last = encoded
        self._inner.publish(shard_map)


class ParallelPublisher(ShardMapPublisher):
    """Fan-out to several publishers (parallel publisher)."""

    def __init__(self, publishers: List[ShardMapPublisher]):
        self._publishers = publishers

    def publish(self, shard_map: Dict) -> None:
        threads = [
            threading.Thread(target=self._safe, args=(p, shard_map))
            for p in self._publishers
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    @staticmethod
    def _safe(p: ShardMapPublisher, shard_map: Dict) -> None:
        try:
            p.publish(shard_map)
        except Exception:
            log.exception("shard map publisher failed")
