"""Cluster management — the native control plane (reference: SURVEY §2.4,
cluster_management/ — Apache Helix on ZooKeeper via an embedded JVM).

Rebuilt without a JVM:
- ``coordinator``: a small coordination service (sessions, ephemeral nodes,
  CAS, watches, locks) standing in for ZooKeeper;
- ``controller``: leader-elected assignment computation (Helix controller
  equivalent) with highest-seq-aware leader election;
- ``participant``: joins the cluster, runs state-model transitions against
  the local Admin service;
- ``state_models``: LeaderFollower / MasterSlave / Bootstrap /
  OnlineOffline / Cache / CdcLeaderStandby;
- ``spectator`` + ``config_generator`` + ``publishers``: external-view →
  shard-map JSON fan-out;
- ``tasks``: Backup/Restore/Ingest/Dedup task framework;
- ``eventstore``: leader-handoff event history.
"""
