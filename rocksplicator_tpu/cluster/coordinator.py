"""Coordination service — the ZooKeeper equivalent.

Reference dependency: the entire Java control plane sits on ZK (sessions,
ephemeral znodes, watches, InterProcessMutex locks, merged event stores).
This module provides those primitives natively over the framework's RPC
layer:

- hierarchical nodes with versioned CAS writes;
- sessions with TTL heartbeats; ephemeral nodes die with their session;
- sequential nodes (``path-0000000001``) for lock/election recipes;
- long-poll watches on data and children (the same no-thread-parked
  pattern as the replication server);
- client-side distributed lock + leader election recipes.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from ..rpc.client_pool import RpcClientPool
from ..rpc.errors import RpcApplicationError, RpcError
from ..rpc.ioloop import IoLoop
from ..rpc.server import RpcServer

log = logging.getLogger(__name__)

NO_NODE = "NO_NODE"
NODE_EXISTS = "NODE_EXISTS"
BAD_VERSION = "BAD_VERSION"
NO_SESSION = "NO_SESSION"
NOT_EMPTY = "NOT_EMPTY"

DEFAULT_SESSION_TTL = 6.0


class _Node:
    __slots__ = ("value", "version", "ephemeral_owner", "seq_counter")

    def __init__(self, value: bytes, ephemeral_owner: Optional[int]):
        self.value = value
        self.version = 0
        self.ephemeral_owner = ephemeral_owner
        self.seq_counter = itertools.count(0)


class CoordinatorServer:
    """In-memory coordination server (durability is a later-round item —
    the reference's ZK is durable; state here rebuilds from live sessions
    on restart, which the state machines tolerate)."""

    def __init__(self, port: int = 0, ioloop: Optional[IoLoop] = None,
                 session_ttl: float = DEFAULT_SESSION_TTL,
                 data_dir: Optional[str] = None):
        self._ioloop = ioloop or IoLoop.default()
        self._nodes: Dict[str, _Node] = {"/": _Node(b"", None)}
        self._sessions: Dict[int, float] = {}  # sid -> expiry deadline
        self._session_ids = itertools.count(1)
        self._lock = threading.Lock()
        self._ttl = session_ttl
        self._change_event: Dict[str, asyncio.Event] = {}
        self._global_version = 0
        # Durability (ZK is durable): persistent nodes snapshot to disk on
        # mutation (debounced) and reload on restart; ephemerals die with
        # their sessions by definition and are never persisted.
        self._data_dir = data_dir
        self._dirty = False
        if data_dir:
            self._load_snapshot()
        self._server = RpcServer(port=port, ioloop=self._ioloop)
        self._server.add_handler(self)
        self._server.start()
        self._reaper_task = self._ioloop.run_coro(self._reap_sessions())
        self._snapshot_task = (
            self._ioloop.run_coro(self._snapshot_loop()) if data_dir else None
        )

    # -- durability --------------------------------------------------------

    def _snapshot_path(self) -> str:
        import os

        return os.path.join(self._data_dir, "coordinator_state.json")

    def _load_snapshot(self) -> None:
        import json
        import os

        os.makedirs(self._data_dir, exist_ok=True)
        try:
            with open(self._snapshot_path(), "r") as f:
                raw = json.load(f)
        except (OSError, ValueError):
            return
        with self._lock:
            for path, entry in raw.get("nodes", {}).items():
                node = _Node(bytes.fromhex(entry["value"]), None)
                node.version = entry["version"]
                node.seq_counter = itertools.count(entry.get("seq", 0))
                self._nodes[path] = node

    def _write_snapshot(self) -> None:
        import json

        from ..utils.misc import write_file_atomic

        with self._lock:
            if not self._dirty:
                return
            self._dirty = False
            nodes = {
                path: {
                    "value": node.value.hex(),
                    "version": node.version,
                    # preserve sequential-node counters across restarts
                    "seq": next(node.seq_counter),
                }
                for path, node in self._nodes.items()
                if node.ephemeral_owner is None
            }
            # peeking at seq_counter consumed a value; rebuild the counters
            for path, node in self._nodes.items():
                if node.ephemeral_owner is None:
                    node.seq_counter = itertools.count(nodes[path]["seq"])
        write_file_atomic(
            self._snapshot_path(),
            json.dumps({"nodes": nodes}).encode("utf-8"),
        )

    async def _snapshot_loop(self) -> None:
        while True:
            await asyncio.sleep(1.0)
            try:
                self._write_snapshot()
            except Exception:
                log.exception("coordinator snapshot failed")

    def _mark_dirty(self) -> None:
        if self._data_dir:
            self._dirty = True

    @property
    def port(self) -> int:
        return self._server.port

    def stop(self) -> None:
        self._reaper_task.cancel()
        if self._snapshot_task is not None:
            self._snapshot_task.cancel()
            try:
                self._write_snapshot()
            except Exception:
                pass
        self._server.stop()

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------

    @staticmethod
    def _norm(path: str) -> str:
        if not path.startswith("/"):
            raise RpcApplicationError(NO_NODE, f"bad path {path!r}")
        return "/" + "/".join(p for p in path.split("/") if p)

    @staticmethod
    def _parent(path: str) -> str:
        return path.rsplit("/", 1)[0] or "/"

    def _signal_change(self, *paths: str) -> None:
        self._global_version += 1
        self._mark_dirty()
        for path in paths:
            ev = self._change_event.get(path)
            if ev is not None:
                ev.set()
                self._change_event.pop(path, None)

    async def _wait_change(self, path: str, timeout: float) -> None:
        ev = self._change_event.get(path)
        if ev is None:
            ev = asyncio.Event()
            self._change_event[path] = ev
        try:
            await asyncio.wait_for(ev.wait(), timeout)
        except asyncio.TimeoutError:
            pass

    def _check_session(self, sid: int) -> None:
        if sid and sid not in self._sessions:
            raise RpcApplicationError(NO_SESSION, str(sid))

    async def _reap_sessions(self) -> None:
        while True:
            await asyncio.sleep(self._ttl / 3)
            now = time.monotonic()
            with self._lock:
                dead = [s for s, dl in self._sessions.items() if dl < now]
                for sid in dead:
                    del self._sessions[sid]
                touched: Set[str] = set()
                if dead:
                    dead_set = set(dead)
                    for path in [
                        p for p, n in self._nodes.items()
                        if n.ephemeral_owner in dead_set
                    ]:
                        del self._nodes[path]
                        touched.add(path)
                        touched.add(self._parent(path))
            for sid in dead:
                log.info("coordinator: session %d expired", sid)
            if dead:
                self._signal_change(*touched)

    # ------------------------------------------------------------------
    # session RPCs
    # ------------------------------------------------------------------

    async def handle_create_session(self, ttl: Optional[float] = None) -> dict:
        sid = next(self._session_ids)
        with self._lock:
            self._sessions[sid] = time.monotonic() + (ttl or self._ttl)
        return {"session_id": sid, "ttl": ttl or self._ttl}

    async def handle_heartbeat(self, session_id: int = 0) -> dict:
        with self._lock:
            if session_id not in self._sessions:
                raise RpcApplicationError(NO_SESSION, str(session_id))
            self._sessions[session_id] = time.monotonic() + self._ttl
        return {}

    async def handle_close_session(self, session_id: int = 0) -> dict:
        with self._lock:
            self._sessions.pop(session_id, None)
            touched: Set[str] = set()
            for path in [
                p for p, n in self._nodes.items()
                if n.ephemeral_owner == session_id
            ]:
                del self._nodes[path]
                touched.add(path)
                touched.add(self._parent(path))
        self._signal_change(*touched)
        return {}

    # ------------------------------------------------------------------
    # node RPCs
    # ------------------------------------------------------------------

    async def handle_create(
        self, path: str = "", value: bytes = b"", ephemeral: bool = False,
        sequential: bool = False, session_id: int = 0,
        make_parents: bool = True,
    ) -> dict:
        path = self._norm(path)
        value = bytes(value)
        with self._lock:
            if ephemeral:
                self._check_session(session_id)
            parent = self._parent(path)
            if parent not in self._nodes:
                if not make_parents:
                    raise RpcApplicationError(NO_NODE, parent)
                # materialize missing ancestors (persistent)
                parts = [p for p in parent.split("/") if p]
                cur = ""
                for p in parts:
                    cur += "/" + p
                    self._nodes.setdefault(cur, _Node(b"", None))
            if sequential:
                seq = next(self._nodes[parent].seq_counter)
                path = f"{path}{seq:010d}"
            if path in self._nodes:
                raise RpcApplicationError(NODE_EXISTS, path)
            self._nodes[path] = _Node(
                value, session_id if ephemeral else None
            )
        self._signal_change(path, self._parent(path))
        return {"path": path}

    async def handle_get(self, path: str = "") -> dict:
        path = self._norm(path)
        with self._lock:
            node = self._nodes.get(path)
            if node is None:
                raise RpcApplicationError(NO_NODE, path)
            return {"value": node.value, "version": node.version}

    async def handle_exists(self, path: str = "") -> dict:
        path = self._norm(path)
        with self._lock:
            node = self._nodes.get(path)
            return {
                "exists": node is not None,
                "version": node.version if node else -1,
            }

    async def handle_set(
        self, path: str = "", value: bytes = b"", expected_version: int = -1
    ) -> dict:
        path = self._norm(path)
        value = bytes(value)
        with self._lock:
            node = self._nodes.get(path)
            if node is None:
                raise RpcApplicationError(NO_NODE, path)
            if expected_version >= 0 and node.version != expected_version:
                raise RpcApplicationError(
                    BAD_VERSION, f"{path}: {node.version} != {expected_version}"
                )
            node.value = value
            node.version += 1
            version = node.version
        self._signal_change(path)
        return {"version": version}

    async def handle_delete(
        self, path: str = "", expected_version: int = -1,
        recursive: bool = False,
    ) -> dict:
        path = self._norm(path)
        with self._lock:
            node = self._nodes.get(path)
            if node is None:
                raise RpcApplicationError(NO_NODE, path)
            if expected_version >= 0 and node.version != expected_version:
                raise RpcApplicationError(BAD_VERSION, path)
            prefix = path + "/"
            children = [p for p in self._nodes if p.startswith(prefix)]
            if children and not recursive:
                raise RpcApplicationError(NOT_EMPTY, path)
            for p in children:
                del self._nodes[p]
            del self._nodes[path]
        self._signal_change(path, self._parent(path))
        return {}

    async def handle_list(self, path: str = "") -> dict:
        path = self._norm(path)
        with self._lock:
            if path != "/" and path not in self._nodes:
                raise RpcApplicationError(NO_NODE, path)
            prefix = path if path.endswith("/") else path + "/"
            children = sorted({
                p[len(prefix):].split("/", 1)[0]
                for p in self._nodes
                if p.startswith(prefix)
            })
        return {"children": children}

    async def handle_watch(
        self, path: str = "", known_version: int = -2,
        max_wait_ms: int = 10_000,
    ) -> dict:
        """Long-poll: returns when the node (or its children) changed vs
        ``known_version`` (use the ``cversion`` from the previous call), or
        on timeout. version -1 = node absent."""
        path = self._norm(path)

        def snapshot():
            with self._lock:
                node = self._nodes.get(path)
                prefix = path if path.endswith("/") else path + "/"
                children = sorted({
                    p[len(prefix):].split("/", 1)[0]
                    for p in self._nodes if p.startswith(prefix)
                })
                version = node.version if node else -1
                cver = hash((version, tuple(children))) & 0x7FFFFFFF
                return {
                    "exists": node is not None,
                    "value": node.value if node else b"",
                    "version": version,
                    "children": children,
                    "cversion": cver,
                }

        snap = snapshot()
        if known_version != -2 and snap["cversion"] == known_version:
            await self._wait_change(path, max_wait_ms / 1000.0)
            snap = snapshot()
        return snap


class CoordinatorClient:
    """Sync client + session keepalive + watch loops + lock/election
    recipes (the Curator equivalent)."""

    def __init__(self, host: str, port: int, ioloop: Optional[IoLoop] = None,
                 session_ttl: Optional[float] = None):
        self._host, self._port = host, port
        self._ioloop = ioloop or IoLoop.default()
        self._pool = RpcClientPool()
        self._stop = threading.Event()
        r = self._call("create_session", ttl=session_ttl)
        self.session_id = r["session_id"]
        self._ttl = r["ttl"]
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, name="coord-heartbeat", daemon=True
        )
        self._hb_thread.start()
        self._watch_threads: List[threading.Thread] = []

    # -- plumbing ---------------------------------------------------------

    def _call(self, method: str, timeout: float = 30.0, **args):
        async def go():
            return await self._pool.call(
                self._host, self._port, method, args, timeout=timeout
            )

        return self._ioloop.run_sync(go(), timeout=timeout + 5)

    def _heartbeat_loop(self) -> None:
        interval = self._ttl / 3
        while not self._stop.wait(interval):
            try:
                self._call("heartbeat", session_id=self.session_id)
            except RpcError:
                pass  # reconnects on next beat; session may expire meanwhile
            except Exception:
                log.exception("coordinator heartbeat failed")

    def close(self) -> None:
        self._stop.set()
        try:
            self._call("close_session", session_id=self.session_id)
        except Exception:
            pass
        self._hb_thread.join(timeout=2.0)
        for t in self._watch_threads:
            t.join(timeout=2.0)
        self._ioloop.run_sync(self._pool.close())

    # -- node ops ---------------------------------------------------------

    def create(self, path: str, value: bytes = b"", ephemeral: bool = False,
               sequential: bool = False) -> str:
        return self._call(
            "create", path=path, value=value, ephemeral=ephemeral,
            sequential=sequential, session_id=self.session_id,
        )["path"]

    def ensure(self, path: str, value: bytes = b"") -> None:
        try:
            self.create(path, value)
        except RpcApplicationError as e:
            if e.code != NODE_EXISTS:
                raise

    def get(self, path: str) -> Tuple[bytes, int]:
        r = self._call("get", path=path)
        return bytes(r["value"]), r["version"]

    def get_or_none(self, path: str) -> Optional[bytes]:
        try:
            return self.get(path)[0]
        except RpcApplicationError as e:
            if e.code == NO_NODE:
                return None
            raise

    def set(self, path: str, value: bytes, expected_version: int = -1) -> int:
        return self._call(
            "set", path=path, value=value, expected_version=expected_version
        )["version"]

    def put(self, path: str, value: bytes) -> None:
        """create-or-set."""
        try:
            self.create(path, value)
        except RpcApplicationError as e:
            if e.code != NODE_EXISTS:
                raise
            self.set(path, value)

    def delete(self, path: str, recursive: bool = False) -> None:
        self._call("delete", path=path, recursive=recursive)

    def delete_if_exists(self, path: str, recursive: bool = False) -> None:
        try:
            self.delete(path, recursive=recursive)
        except RpcApplicationError as e:
            if e.code != NO_NODE:
                raise

    def list(self, path: str) -> List[str]:
        try:
            return self._call("list", path=path)["children"]
        except RpcApplicationError as e:
            if e.code == NO_NODE:
                return []
            raise

    def exists(self, path: str) -> bool:
        return self._call("exists", path=path)["exists"]

    # -- watches ----------------------------------------------------------

    def watch(self, path: str, callback, poll_ms: int = 5_000) -> threading.Event:
        """Fire ``callback(snapshot_dict)`` on every observed change (and
        once initially). Returns an Event; set it to stop the watch."""
        stop = threading.Event()

        def loop():
            known = -2
            while not stop.is_set() and not self._stop.is_set():
                try:
                    snap = self._call(
                        "watch", path=path, known_version=known,
                        max_wait_ms=poll_ms, timeout=poll_ms / 1000 + 10,
                    )
                except (RpcError, RpcApplicationError):
                    time.sleep(0.5)
                    continue
                except Exception:
                    log.exception("watch loop error for %s", path)
                    time.sleep(0.5)
                    continue
                if snap["cversion"] != known:
                    known = snap["cversion"]
                    try:
                        callback(snap)
                    except Exception:
                        log.exception("watch callback failed for %s", path)

        t = threading.Thread(target=loop, name=f"watch:{path}", daemon=True)
        t.start()
        self._watch_threads.append(t)
        return stop

    # -- recipes -----------------------------------------------------------

    def acquire_lock(self, lock_path: str, timeout: float = 30.0) -> Optional[str]:
        """InterProcessMutex recipe: ephemeral sequential node; lowest wins.
        Returns my node path (pass to release_lock), or None on timeout."""
        self.ensure(lock_path)
        me = self.create(f"{lock_path}/lock-", ephemeral=True, sequential=True)
        my_name = me.rsplit("/", 1)[1]
        deadline = time.monotonic() + timeout
        known = -2  # first watch returns immediately with the snapshot
        while time.monotonic() < deadline:
            remaining = max(0.05, deadline - time.monotonic())
            wait_ms = int(min(remaining, 2.0) * 1000)
            snap = self._call(
                "watch", path=lock_path, known_version=known,
                max_wait_ms=wait_ms, timeout=wait_ms / 1000 + 10,
            )
            known = snap["cversion"]
            siblings = sorted(snap["children"])
            if siblings and siblings[0] == my_name:
                return me
        self.delete_if_exists(me)
        return None

    def release_lock(self, my_node: str) -> None:
        self.delete_if_exists(my_node)

    def elect_leader(self, election_path: str, my_id: str) -> bool:
        """Simple leader election: ephemeral node claim. True if leader."""
        self.ensure(election_path)
        try:
            self.create(f"{election_path}/leader", my_id.encode(),
                        ephemeral=True)
            return True
        except RpcApplicationError as e:
            if e.code == NODE_EXISTS:
                return False
            raise

    def current_leader(self, election_path: str) -> Optional[str]:
        raw = self.get_or_none(f"{election_path}/leader")
        return raw.decode() if raw is not None else None
