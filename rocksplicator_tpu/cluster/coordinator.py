"""Coordination service — the ZooKeeper equivalent.

Reference dependency: the entire Java control plane sits on ZK (sessions,
ephemeral znodes, watches, InterProcessMutex locks, merged event stores).
This module provides those primitives natively over the framework's RPC
layer:

- hierarchical nodes with versioned CAS writes;
- sessions with TTL heartbeats; ephemeral nodes die with their session;
- sequential nodes (``path-0000000001``) for lock/election recipes;
- long-poll watches on data and children (the same no-thread-parked
  pattern as the replication server);
- client-side distributed lock + leader election recipes;
- **replication**: a standby server (``replica_of=(host, port)``) tails
  the primary's mutation stream (long-poll, resumable by index, full
  state transfer when behind), applies every mutation including
  ephemerals and session lifecycle, persists durable state to its OWN
  WAL+snapshot, and serves reads/watches. ``promote()`` turns it into
  the primary: replicated sessions get a fresh TTL grace window (the ZK
  session-re-establishment analog) so ephemeral registrations survive a
  failover as long as owners keep heartbeating. ``CoordinatorClient``
  accepts fallback endpoints and rotates on connection failure or
  NOT_PRIMARY. Failover is operator/controller-driven by default;
  ``auto_promote_after`` opts a standby into self-promotion after the
  primary has been unreachable that long (deploy at most one such
  standby — two could split-brain on a partition, the reason ZK uses
  quorum; the conservative default is manual).
- **quorum mode** (``quorum_size=N``): ZK-majority semantics for a
  3+-node ensemble. Mutations ack only after floor(N/2) standbys
  received them (no timeout-degrade: QUORUM_LOST on timeout), the
  primary refuses writes once a majority of standbys hasn't pulled
  within ``leader_lease_sec`` (a minority-partitioned primary
  self-demotes), ``promote_best()`` elects the highest-(ftoken,
  mut_index) standby and repoints the rest, and monotonic fencing
  tokens on every ack let clients reject a deposed primary they have
  already outgrown. Reference: the control plane's ZK ensemble
  (common/helix_client.cpp consumes it; quorum + fencing are what ZK
  provides it).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..observability.context import current_span
from ..rpc.client_pool import RpcClientPool
from ..rpc.errors import RpcApplicationError, RpcError
from ..rpc.ioloop import IoLoop
from ..rpc.server import RpcServer
from ..testing import failpoints as fp
from ..utils.stats import Stats

log = logging.getLogger(__name__)

NO_NODE = "NO_NODE"
NODE_EXISTS = "NODE_EXISTS"
BAD_VERSION = "BAD_VERSION"
NO_SESSION = "NO_SESSION"
NOT_EMPTY = "NOT_EMPTY"
NOT_PRIMARY = "NOT_PRIMARY"
QUORUM_LOST = "QUORUM_LOST"

DEFAULT_SESSION_TTL = 6.0
# mutation-stream ring: a standby farther behind than this does a full
# state transfer instead of an incremental catch-up
RECENT_MUTATIONS_CAP = 8192


class _Node:
    __slots__ = ("value", "version", "ephemeral_owner", "seq_counter")

    def __init__(self, value: bytes, ephemeral_owner: Optional[int]):
        self.value = value
        self.version = 0
        self.ephemeral_owner = ephemeral_owner
        self.seq_counter = 0  # next sequential-child suffix


class _Wal:
    """Group-committed append-only mutation log.

    Each record is one line ``<crc32 hex 8>:<json>\n``; replay stops at
    the first torn/corrupt line (a crash mid-append), and opening the log
    TRUNCATES that garbage so later appends are never stranded behind it.
    Records carry ABSOLUTE resulting state (versions, seq values) so
    replay over a newer snapshot is idempotent.

    Appends go through a dedicated writer thread: ``append_async``
    returns a Future resolved after the record is fsync'd. The writer
    drains the queue and fsyncs once per batch (group commit), so a write
    burst costs one fsync — and the fsync never runs on the RPC event
    loop. A failed write/fsync FENCES the log: every pending and future
    append fails, so no further mutation can be acked."""

    def __init__(self, path: str):
        import queue

        self._path = path
        valid = self._valid_prefix_len(path)
        if valid is not None:
            with open(path, "r+b") as f:
                f.truncate(valid)
        self._f = open(path, "ab")
        self._q: "queue.Queue" = queue.Queue()
        self._failed: Optional[Exception] = None
        self._thread = threading.Thread(
            target=self._writer_loop, name="coordinator-wal", daemon=True)
        self._thread.start()

    @staticmethod
    def _encode(rec: dict) -> bytes:
        import json
        import zlib

        payload = json.dumps(rec, separators=(",", ":")).encode("utf-8")
        return b"%08x:%s\n" % (zlib.crc32(payload), payload)

    def append_async(self, rec: dict):
        """Enqueue; returns a concurrent.futures.Future resolved (True)
        once the record is durable, or failed if the WAL is broken."""
        from concurrent.futures import Future

        fut: Future = Future()
        if self._failed is not None:
            fut.set_exception(self._failed)
            return fut
        self._q.put((self._encode(rec), fut))
        return fut

    def _writer_loop(self) -> None:
        import os
        import queue

        pending = None  # boundary item deferred mid-drain (preserves FIFO)
        while True:
            item = pending if pending is not None else self._q.get()
            pending = None
            if item is None:
                return
            if item[0] == "reset":
                try:
                    self._do_reset()
                    item[1].set_result(True)
                except Exception as e:
                    self._failed = e
                    item[1].set_exception(e)
                    return
                continue
            batch = [item]
            while True:  # drain whatever arrived — one fsync per batch
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is None or nxt[0] == "reset":
                    pending = nxt  # handle after this batch, in order
                    break
                batch.append(nxt)
            try:
                for line, _fut in batch:
                    # the control plane touching durable state: a tripped
                    # fail policy fences the log exactly like a real
                    # ENOSPC; a torn policy leaves a truncated record on
                    # disk (healed by _valid_prefix_len on reopen) and
                    # then fences
                    cut = fp.torn_point("coordinator.wal.append", len(line))
                    if cut is not None:
                        self._f.write(line[:cut])
                        self._f.flush()
                        raise fp.FailpointError(
                            f"coordinator.wal.append torn at {cut}")
                    fp.hit("coordinator.wal.append")
                    self._f.write(line)
                self._f.flush()
                os.fsync(self._f.fileno())
            except Exception as e:  # ENOSPC/IO error: fence the log
                self._failed = e
                for _line, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)
                log.critical("coordinator WAL failed — mutations fenced: %r", e)
                return
            for _line, fut in batch:
                if not fut.done():
                    fut.set_result(True)

    @staticmethod
    def _valid_prefix_len(path: str) -> Optional[int]:
        """Byte length of the valid record prefix, or None if no file."""
        import json
        import os
        import zlib

        if not os.path.isfile(path):
            return None
        pos = 0
        with open(path, "rb") as f:
            for line in f:
                if not line.endswith(b"\n") or len(line) < 10:
                    break
                crc_hex, _, payload = line[:-1].partition(b":")
                try:
                    if int(crc_hex, 16) != zlib.crc32(payload):
                        break
                    json.loads(payload.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    break
                pos += len(line)
        return pos

    @staticmethod
    def replay(path: str):
        import json
        import os
        import zlib

        if not os.path.isfile(path):
            return
        with open(path, "rb") as f:
            for line in f:
                if not line.endswith(b"\n") or len(line) < 10:
                    return  # torn tail
                crc_hex, _, payload = line[:-1].partition(b":")
                try:
                    if int(crc_hex, 16) != zlib.crc32(payload):
                        return
                    yield json.loads(payload.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    return

    @property
    def failed(self) -> Optional[Exception]:
        return self._failed

    def reset_async(self):
        """Truncate after a snapshot made the log's contents redundant.
        Runs on the writer thread (never races in-flight appends); caller
        must ensure no un-snapshotted record can be enqueued before this
        (it holds the server lock when the dirty flag was clear)."""
        from concurrent.futures import Future

        fut: Future = Future()
        if self._failed is not None:
            fut.set_exception(self._failed)
            return fut
        self._q.put(("reset", fut))
        return fut

    def _do_reset(self) -> None:
        import os

        self._f.close()
        self._f = open(self._path, "wb")
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        self._q.put(None)
        self._thread.join(timeout=5.0)
        self._f.close()


class CoordinatorServer:
    """Coordination server. With ``data_dir`` it is DURABLE the way ZK is:
    every acknowledged mutation is fsync'd to a WAL (group commit on a
    dedicated writer thread) before the ack, and periodic snapshots
    truncate the log (kill -9 loses nothing acked). Ephemeral nodes die
    with their sessions by definition and are never persisted; sequential
    counters ARE durable so lock/election suffixes never regress across
    restarts. A failed WAL write fences all further mutations and stops
    snapshots (readers may briefly see the last never-acked mutation in
    memory until restart — standard fail-stop WAL semantics)."""

    def __init__(self, port: int = 0, ioloop: Optional[IoLoop] = None,
                 session_ttl: float = DEFAULT_SESSION_TTL,
                 data_dir: Optional[str] = None,
                 replica_of: Optional[Tuple[str, int]] = None,
                 auto_promote_after: Optional[float] = None,
                 min_sync_standbys: int = 0,
                 ack_timeout: float = 2.0,
                 ack_degrade_after: int = 100,
                 quorum_size: int = 0,
                 leader_lease_sec: float = 6.0):
        import collections

        self._ioloop = ioloop or IoLoop.default()
        self._nodes: Dict[str, _Node] = {"/": _Node(b"", None)}
        self._sessions: Dict[int, float] = {}  # sid -> expiry deadline
        self._session_ids = itertools.count(1)
        self._max_sid_seen = 0
        self._lock = threading.Lock()
        # Serializes the read-state → atomic-write → WAL-truncate
        # snapshot sequence: the periodic snapshot job, a promote's
        # post-promote snapshot, and stop() now run on DIFFERENT threads
        # (executor offload, rstpu-check loop-blocking), and a stale
        # interleaved writer could otherwise overwrite a newer fencing
        # token and then truncate the WAL under it.
        self._snapshot_mutex = threading.Lock()  # rstpu-check: io-mutex snapshot writer — fsync + truncate-wait under it is the mechanism
        self._ttl = session_ttl
        self._change_event: Dict[str, asyncio.Event] = {}
        self._global_version = 0
        self._data_dir = data_dir
        self._dirty = False
        self._wal: Optional[_Wal] = None
        # replication: every mutation gets a stream index; a bounded ring
        # backs incremental standby catch-up. The epoch token qualifies
        # indices (the zxid-epoch analog): a restarted primary starts a
        # new epoch, so a standby resuming with stale indices is forced
        # into a full state transfer instead of silently applying a
        # divergent suffix.
        import uuid

        self._mut_index = 0
        self._epoch = uuid.uuid4().hex
        self._recent: "collections.deque" = collections.deque(
            maxlen=RECENT_MUTATIONS_CAP)
        self._stream_event = asyncio.Event()
        self._upstream = replica_of
        self._standby = replica_of is not None
        self._auto_promote_after = auto_promote_after
        self._standby_task = None
        # semi-sync replication (reference mode-1/2 semantics,
        # replicated_db.cpp:236-273): a mutation acks only once
        # min_sync_standbys standbys have RECEIVED it (their next
        # repl_updates pull implies everything before from_index). On
        # timeout the write proceeds (availability over durability —
        # same as writeWaitFollowerACK) and after ack_degrade_after
        # consecutive timeouts the wait degrades to 10 ms to fail fast,
        # recovering on the first success.
        self._min_sync_standbys = min_sync_standbys
        self._ack_timeout = ack_timeout
        self._ack_degrade_after = ack_degrade_after
        self._ack_timeouts_in_a_row = 0
        self._standby_acked: Dict[str, int] = {}
        self._ack_event = asyncio.Event()
        # Quorum mode (the ZK-majority analog; VERDICT r3 #6).
        # quorum_size = total ensemble size N (primary + standbys). When
        # > 0, a mutation ACKS only once floor(N/2) standbys received it
        # (majority including self) and there is NO timeout-degrade: on
        # timeout the client gets QUORUM_LOST. Additionally the primary
        # holds a LEASE: mutations are refused outright (NOT_PRIMARY)
        # unless a majority of standbys pulled the stream within
        # leader_lease_sec — a primary cut off from the majority
        # self-demotes for writes, bounding the split-brain window of an
        # asymmetric partition to the lease length. Keep
        # auto_promote_after > leader_lease_sec so the deposed primary
        # stops committing before any standby can take over.
        self._quorum_size = quorum_size
        self._leader_lease_sec = leader_lease_sec
        self._standby_last_pull: Dict[str, float] = {}
        self._standby_parked: Dict[str, int] = {}  # live long-polls
        self._standby_addrs: Dict[str, str] = {}  # id -> "ip:port"
        self._sync_pool: Optional[RpcClientPool] = None  # handle_sync
        # Fencing token (monotonic, the ZK-epoch analog): bumped by every
        # promote, carried on repl_state/repl_updates (standbys adopt the
        # max) and on mutation acks (clients remember the max and refuse
        # to keep talking to a lower-token — deposed — primary).
        self._fencing_token = 1
        if data_dir:
            self._load_snapshot()
            self._replay_wal()
            self._wal = _Wal(self._wal_path())
        self._server = RpcServer(port=port, ioloop=self._ioloop)
        self._server.add_handler(self)
        self._server.start()
        self._reaper_task = self._ioloop.run_coro(self._reap_sessions())
        self._snapshot_task = (
            self._ioloop.run_coro(self._snapshot_loop()) if data_dir else None
        )
        if self._standby:
            self._standby_task = self._ioloop.run_coro(self._standby_loop())

    # -- durability --------------------------------------------------------

    def _snapshot_path(self) -> str:
        import os

        return os.path.join(self._data_dir, "coordinator_state.json")

    def _wal_path(self) -> str:
        import os

        return os.path.join(self._data_dir, "coordinator_wal.log")

    def _load_snapshot(self) -> None:
        import json
        import os

        os.makedirs(self._data_dir, exist_ok=True)
        try:
            with open(self._snapshot_path(), "r") as f:
                raw = json.load(f)
        except (OSError, ValueError):
            return
        with self._lock:
            self._fencing_token = max(
                self._fencing_token, int(raw.get("ftoken", 1)))
            for path, entry in raw.get("nodes", {}).items():
                node = _Node(bytes.fromhex(entry["value"]), None)
                node.version = entry["version"]
                node.seq_counter = entry.get("seq", 0)
                self._nodes[path] = node

    def _replay_wal(self) -> None:
        """Apply WAL records on top of the snapshot. Records hold absolute
        resulting state, so re-applying ones already captured by the
        snapshot is harmless. Ephemeral creates are skipped — those
        sessions died with the process (standby APPLY differs: see
        _apply_record_locked)."""
        with self._lock:
            for rec in _Wal.replay(self._wal_path()):
                self._apply_record_locked(rec, include_ephemeral=False)

    def _record(self, rec: dict, durable: bool = True):
        """Called under self._lock for EVERY state mutation. Appends the
        record to the replication stream ring (standbys tail it — session
        lifecycle and ephemerals included), and, when ``durable``, to the
        WAL. Returns a durability future (or None); the handler must
        await it BEFORE acknowledging. Setting _dirty here — under the
        lock, atomically with the enqueue — is what makes snapshot
        truncation safe: the snapshot loop only truncates when the flag
        was clear under the same lock, which implies no un-snapshotted
        record exists."""
        self._mut_index += 1
        self._recent.append((self._mut_index, rec))
        if not durable or self._wal is None:
            return None
        self._dirty = True
        return self._wal.append_async(rec)

    def _signal_stream(self) -> None:
        """Wake parked repl_updates long-polls (ioloop thread only)."""
        self._stream_event.set()
        self._stream_event = asyncio.Event()

    async def _await_standby_ack(self, idx: int) -> None:
        """Semi-sync wait: block the ack until min_sync_standbys have
        pulled past ``idx`` (or the — possibly degraded — timeout).
        Quorum mode instead requires floor(N/2) standby acks and FAILS
        the mutation on timeout (QUORUM_LOST) — availability is
        sacrificed, majority durability is not."""
        quorum = self._quorum_size > 0
        need = self._quorum_size // 2 if quorum else self._min_sync_standbys
        if need <= 0 or self._standby:
            return
        timeout = (
            0.01 if not quorum
            and self._ack_timeouts_in_a_row >= self._ack_degrade_after
            else self._ack_timeout
        )
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                have = sum(
                    1 for v in self._standby_acked.values() if v >= idx
                )
                if have >= need:
                    self._ack_timeouts_in_a_row = 0
                    return
                ev = self._ack_event
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self._on_ack_timeout(quorum, idx)
                return
            try:
                await asyncio.wait_for(ev.wait(), remaining)
            except asyncio.TimeoutError:
                self._on_ack_timeout(quorum, idx)
                return

    def _on_ack_timeout(self, quorum: bool, idx: int) -> None:
        self._ack_timeouts_in_a_row += 1
        Stats.get().incr("coordinator.sync_ack_timeouts")
        if quorum:
            # The mutation is applied + WAL'd locally but NOT majority-
            # replicated; the client must treat it as failed (it may
            # still surface after a failover — same contract as a ZK
            # proposal the leader logged but never committed).
            raise RpcApplicationError(
                QUORUM_LOST,
                f"mutation {idx} not acked by "
                f"{self._quorum_size // 2} standbys")

    def _check_quorum_lease(self) -> None:
        """Quorum mode only: refuse mutations unless a majority of
        standbys pulled the stream within the lease — the fencing that
        stops a deposed primary from committing during an asymmetric
        partition (VERDICT r3 'what's weak' #3)."""
        if self._quorum_size <= 0:
            return
        need = self._quorum_size // 2
        now = time.monotonic()
        with self._lock:
            live = sum(
                1 for sid, t in self._standby_last_pull.items()
                if now - t <= self._leader_lease_sec
                or self._standby_parked.get(sid, 0) > 0
            )
        if live < need:
            raise RpcApplicationError(
                NOT_PRIMARY,
                f"quorum lease lost: {live}/{need} standbys in contact")

    @staticmethod
    async def _await_durable(futs: list) -> None:
        """Block the ack on WAL fsync; translate failure to an RPC error.
        The writer resolves batches in FIFO order, so awaiting each
        future (usually just one) is cheap."""
        for fut in futs:
            if fut is None:
                continue
            try:
                await asyncio.wrap_future(fut)
            except Exception as e:
                raise RpcApplicationError(
                    "WAL_ERROR", f"mutation not durable: {e!r}")

    def _write_snapshot(self) -> None:
        # one writer end to end: a second snapshotter parks here until
        # the first finishes its write+truncate, then re-reads fresh
        # state (or sees _dirty clear and no-ops)
        with self._snapshot_mutex:
            self._write_snapshot_locked()

    def _write_snapshot_locked(self) -> None:
        import json

        from ..utils.misc import write_file_atomic

        if self._wal is not None and self._wal.failed is not None:
            return  # fenced: memory may hold never-acked state
        with self._lock:
            if not self._dirty:
                return
            self._dirty = False
            nodes = {
                path: {
                    "value": node.value.hex(),
                    "version": node.version,
                    # preserve sequential-node counters across restarts
                    "seq": node.seq_counter,
                }
                for path, node in self._nodes.items()
                if node.ephemeral_owner is None
            }
            ftoken = self._fencing_token
        write_file_atomic(
            self._snapshot_path(),
            json.dumps({"nodes": nodes, "ftoken": ftoken}).encode("utf-8"),
        )
        # The snapshot now covers everything in the WAL; truncate it —
        # unless a mutation landed meanwhile (_dirty set under the lock
        # with its WAL append), in which case the next cycle handles it.
        # (Crash between the two steps just replays idempotent records.)
        fut = None
        with self._lock:
            if self._wal is not None and not self._dirty:
                fut = self._wal.reset_async()
        if fut is not None:
            try:
                fut.result(timeout=10)
            except Exception:
                log.exception("coordinator WAL truncation failed")

    async def _snapshot_loop(self) -> None:
        while True:
            await asyncio.sleep(1.0)
            if self._wal is not None and self._wal.failed is not None:
                # fenced WAL: in-memory state may hold never-acked
                # mutations — do NOT persist it
                continue
            try:
                # off-loop: the snapshot's atomic write fsyncs (file +
                # dir) and its WAL-truncate future wait would otherwise
                # stall every session/heartbeat sharing this loop for
                # tens of ms per cycle (rstpu-check loop-blocking)
                await asyncio.get_running_loop().run_in_executor(
                    None, self._write_snapshot)
            except Exception:
                log.exception("coordinator snapshot failed")

    def _mark_dirty(self) -> None:
        if self._data_dir:
            self._dirty = True

    @property
    def port(self) -> int:
        return self._server.port

    def stop(self) -> None:
        self._reaper_task.cancel()
        if self._standby_task is not None:
            self._standby_task.cancel()
            self._standby_task = None
        if self._snapshot_task is not None:
            self._snapshot_task.cancel()
            try:
                self._write_snapshot()
            except Exception:
                pass
        if self._sync_pool is not None:
            pool, self._sync_pool = self._sync_pool, None
            try:
                self._ioloop.run_sync(pool.close(), timeout=5)
            except Exception:
                pass
        self._server.stop()
        if self._wal is not None:
            self._wal.close()

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------

    @staticmethod
    def _norm(path: str) -> str:
        if not path.startswith("/"):
            raise RpcApplicationError(NO_NODE, f"bad path {path!r}")
        return "/" + "/".join(p for p in path.split("/") if p)

    @staticmethod
    def _parent(path: str) -> str:
        return path.rsplit("/", 1)[0] or "/"

    def _signal_change(self, *paths: str) -> None:
        self._global_version += 1
        self._mark_dirty()
        self._signal_stream()
        for path in paths:
            ev = self._change_event.get(path)
            if ev is not None:
                ev.set()
                self._change_event.pop(path, None)

    async def _wait_change(self, path: str, timeout: float) -> None:
        ev = self._change_event.get(path)
        if ev is None:
            ev = asyncio.Event()
            self._change_event[path] = ev
        try:
            await asyncio.wait_for(ev.wait(), timeout)
        except asyncio.TimeoutError:
            pass

    def _check_session(self, sid: int) -> None:
        if sid and sid not in self._sessions:
            raise RpcApplicationError(NO_SESSION, str(sid))

    def _check_primary(self) -> None:
        if self._standby:
            up = f"{self._upstream[0]}:{self._upstream[1]}" \
                if self._upstream else ""
            raise RpcApplicationError(NOT_PRIMARY, up)

    async def _reap_sessions(self) -> None:
        while True:
            await asyncio.sleep(self._ttl / 3)
            if self._standby:
                continue  # replicated deadlines are inf until promote
            try:
                # delay = a stalled reaper (sessions outlive their TTL);
                # fail = a reap pass lost — both must only postpone
                # expiry, never wedge the loop
                await fp.async_hit("coordinator.reap")
            except OSError:
                continue
            now = time.monotonic()
            with self._lock:
                dead = [s for s, dl in self._sessions.items() if dl < now]
                for sid in dead:
                    del self._sessions[sid]
                touched: Set[str] = set()
                if dead:
                    dead_set = set(dead)
                    for path in [
                        p for p, n in self._nodes.items()
                        if n.ephemeral_owner in dead_set
                    ]:
                        del self._nodes[path]
                        touched.add(path)
                        touched.add(self._parent(path))
                    for sid in dead:
                        self._record({"op": "expire_session", "sid": sid},
                                     durable=False)
            for sid in dead:
                log.info("coordinator: session %d expired", sid)
            if dead:
                self._signal_change(*touched)

    # ------------------------------------------------------------------
    # session RPCs
    # ------------------------------------------------------------------

    async def handle_create_session(self, ttl: Optional[float] = None) -> dict:
        self._check_primary()
        self._check_quorum_lease()
        sid = next(self._session_ids)
        with self._lock:
            self._sessions[sid] = time.monotonic() + (ttl or self._ttl)
            self._max_sid_seen = max(self._max_sid_seen, sid)
            self._record({"op": "create_session", "sid": sid}, durable=False)
            sync_idx = self._mut_index
        self._signal_stream()
        await self._await_standby_ack(sync_idx)
        return {"session_id": sid, "ttl": ttl or self._ttl,
                "ftoken": self._fencing_token}

    async def handle_heartbeat(self, session_id: int = 0) -> dict:
        # dropped/stalled heartbeats are how chaos drives REAL session
        # expiry end to end (participant retry → TTL lapse → ephemeral
        # teardown → failover), not a simulated shortcut
        await fp.async_hit("coordinator.heartbeat")
        self._check_primary()
        # A minority-partitioned quorum primary must NOT keep sessions
        # (and their ephemeral lock nodes) alive: the majority side will
        # expire them and re-grant the locks — two holders otherwise.
        self._check_quorum_lease()
        with self._lock:
            if session_id not in self._sessions:
                raise RpcApplicationError(NO_SESSION, str(session_id))
            self._sessions[session_id] = time.monotonic() + self._ttl
        return {"ftoken": self._fencing_token}

    async def handle_close_session(self, session_id: int = 0) -> dict:
        self._check_primary()
        # mutates the tree (drops ephemerals): same lease gate as every
        # other mutation — a minority primary must not diverge its stream
        self._check_quorum_lease()
        with self._lock:
            self._sessions.pop(session_id, None)
            touched: Set[str] = set()
            for path in [
                p for p, n in self._nodes.items()
                if n.ephemeral_owner == session_id
            ]:
                del self._nodes[path]
                touched.add(path)
                touched.add(self._parent(path))
            self._record({"op": "close_session", "sid": session_id},
                         durable=False)
            sync_idx = self._mut_index
        self._signal_change(*touched)
        await self._await_standby_ack(sync_idx)
        return {"ftoken": self._fencing_token}

    # ------------------------------------------------------------------
    # node RPCs
    # ------------------------------------------------------------------

    async def handle_create(
        self, path: str = "", value: bytes = b"", ephemeral: bool = False,
        sequential: bool = False, session_id: int = 0,
        make_parents: bool = True,
    ) -> dict:
        self._check_primary()
        self._check_quorum_lease()
        path = self._norm(path)
        value = bytes(value)
        with self._lock:
            if ephemeral:
                self._check_session(session_id)
            parent = self._parent(path)
            created_parents: List[str] = []
            if parent not in self._nodes:
                if not make_parents:
                    raise RpcApplicationError(NO_NODE, parent)
                # materialize missing ancestors (persistent)
                parts = [p for p in parent.split("/") if p]
                cur = ""
                for p in parts:
                    cur += "/" + p
                    if cur not in self._nodes:
                        self._nodes[cur] = _Node(b"", None)
                        created_parents.append(cur)
            seq = None
            if sequential:
                pnode = self._nodes[parent]
                seq = pnode.seq_counter
                pnode.seq_counter += 1
                path = f"{path}{seq:010d}"
            if path in self._nodes:
                raise RpcApplicationError(NODE_EXISTS, path)
            self._nodes[path] = _Node(
                value, session_id if ephemeral else None
            )
            # WAL before ack. Ephemeral nodes die with the restart anyway,
            # but materialized persistent ancestors and sequential suffix
            # consumption ARE durable changes (lock ordering must never
            # regress across restarts). The stream gets every record —
            # standbys replicate ephemerals (incl. values) too.
            futs = [
                self._record({
                    "op": "create", "path": p, "value": "",
                    "ephemeral": False, "seq": None,
                })
                for p in created_parents
            ]
            futs.append(self._record(
                {
                    "op": "create", "path": path, "value": value.hex(),
                    "ephemeral": bool(ephemeral), "seq": seq,
                    "sid": session_id if ephemeral else None,
                },
                durable=not (ephemeral and seq is None),
            ))
            sync_idx = self._mut_index
        await self._await_durable(futs)
        # Wake parked standby long-polls BEFORE waiting for their ack —
        # otherwise a standby sitting in repl_updates cannot see the
        # mutation it must ack until its poll timeout, and every mutation
        # burns the full ack_timeout (matching handle_create_session).
        self._signal_stream()
        try:
            await self._await_standby_ack(sync_idx)
        finally:
            # even on QUORUM_LOST the node EXISTS locally (and may yet be
            # majority-replicated) — parked watchers must still fire
            self._signal_change(path, self._parent(path))
        return {"path": path, "ftoken": self._fencing_token}

    async def handle_get(self, path: str = "") -> dict:
        path = self._norm(path)
        with self._lock:
            node = self._nodes.get(path)
            if node is None:
                raise RpcApplicationError(NO_NODE, path)
            # ftoken on reads too: a client that has outgrown a deposed
            # primary rotates instead of consuming its stale tree
            return {"value": node.value, "version": node.version,
                    "ftoken": self._fencing_token}

    async def handle_exists(self, path: str = "") -> dict:
        path = self._norm(path)
        with self._lock:
            node = self._nodes.get(path)
            return {
                "exists": node is not None,
                "version": node.version if node else -1,
                "ftoken": self._fencing_token,
            }

    async def handle_set(
        self, path: str = "", value: bytes = b"", expected_version: int = -1
    ) -> dict:
        self._check_primary()
        self._check_quorum_lease()
        path = self._norm(path)
        value = bytes(value)
        with self._lock:
            node = self._nodes.get(path)
            if node is None:
                raise RpcApplicationError(NO_NODE, path)
            if expected_version >= 0 and node.version != expected_version:
                raise RpcApplicationError(
                    BAD_VERSION, f"{path}: {node.version} != {expected_version}"
                )
            node.value = value
            node.version += 1
            version = node.version
            futs = [self._record(
                {"op": "set", "path": path, "value": value.hex(),
                 "version": version},
                durable=node.ephemeral_owner is None,
            )]
            sync_idx = self._mut_index
        await self._await_durable(futs)
        self._signal_stream()  # wake standby long-polls before the ack wait
        try:
            await self._await_standby_ack(sync_idx)
        finally:
            self._signal_change(path)  # applied even if QUORUM_LOST
        return {"version": version, "ftoken": self._fencing_token}

    async def handle_delete(
        self, path: str = "", expected_version: int = -1,
        recursive: bool = False,
    ) -> dict:
        self._check_primary()
        self._check_quorum_lease()
        path = self._norm(path)
        with self._lock:
            node = self._nodes.get(path)
            if node is None:
                raise RpcApplicationError(NO_NODE, path)
            if expected_version >= 0 and node.version != expected_version:
                raise RpcApplicationError(BAD_VERSION, path)
            prefix = path + "/"
            children = [p for p in self._nodes if p.startswith(prefix)]
            if children and not recursive:
                raise RpcApplicationError(NOT_EMPTY, path)
            durable = node.ephemeral_owner is None or any(
                self._nodes[p].ephemeral_owner is None for p in children
            )
            for p in children:
                del self._nodes[p]
            del self._nodes[path]
            futs = [self._record({"op": "delete", "path": path},
                                 durable=durable)]
            sync_idx = self._mut_index
        await self._await_durable(futs)
        self._signal_stream()  # wake standby long-polls before the ack wait
        try:
            await self._await_standby_ack(sync_idx)
        finally:
            self._signal_change(path, self._parent(path))  # applied even
            # if QUORUM_LOST
        return {"ftoken": self._fencing_token}

    async def handle_multi(self, ops: Optional[list] = None) -> dict:
        """ZK multi() parity: an all-or-nothing batch of mutations.
        Each op is {"op": "create"|"set"|"delete"|"check", "path": ...}
        with the per-op fields of the single-op RPCs (create: value/
        ephemeral/sequential/session_id; set: value/expected_version;
        delete: expected_version/recursive; check: expected_version).
        EVERY op is validated under one lock hold before ANY is applied —
        failure returns the failing op's index and error with no state
        change. Election/lock recipes use this for check-and-act steps
        that single CAS ops cannot express atomically."""
        self._check_primary()
        self._check_quorum_lease()
        ops = ops or []
        results: List[dict] = []
        with self._lock:
            # Phase 1: simulate the WHOLE batch on a shadow view —
            # (version, exists) per path, seeded from the live tree — so
            # later ops observe earlier ops' effects exactly as the apply
            # phase will produce them (ZK multi semantics: ops apply in
            # order; version checks chain through intra-batch bumps,
            # deletes remove whole subtrees, creates materialize full
            # ancestor chains).
            view: Dict[str, int] = {
                p: n.version for p, n in self._nodes.items()
            }

            def ancestors(path):
                parts = [p for p in path.split("/") if p]
                cur_path = ""
                out = []
                for part in parts[:-1]:
                    cur_path += "/" + part
                    out.append(cur_path)
                return out

            for i, op in enumerate(ops):
                kind = op.get("op")
                path = self._norm(op.get("path", ""))
                try:
                    if kind == "check":
                        if path not in view:
                            raise RpcApplicationError(NO_NODE, path)
                        ev = int(op.get("expected_version", -1))
                        if ev >= 0 and view[path] != ev:
                            raise RpcApplicationError(BAD_VERSION, path)
                    elif kind == "create":
                        if op.get("ephemeral"):
                            self._check_session(
                                int(op.get("session_id", 0)))
                        if op.get("sequential"):
                            raise RpcApplicationError(
                                "BAD_OP",
                                "sequential not supported inside multi")
                        if path in view:
                            raise RpcApplicationError(NODE_EXISTS, path)
                        for anc in ancestors(path):
                            view.setdefault(anc, 0)
                        view[path] = 0
                    elif kind == "set":
                        if path not in view:
                            raise RpcApplicationError(NO_NODE, path)
                        ev = int(op.get("expected_version", -1))
                        if ev >= 0 and view[path] != ev:
                            raise RpcApplicationError(BAD_VERSION, path)
                        view[path] += 1
                    elif kind == "delete":
                        if path not in view:
                            raise RpcApplicationError(NO_NODE, path)
                        ev = int(op.get("expected_version", -1))
                        if ev >= 0 and view[path] != ev:
                            raise RpcApplicationError(BAD_VERSION, path)
                        prefix = path + "/"
                        kids = [p for p in view if p.startswith(prefix)]
                        if kids and not op.get("recursive"):
                            raise RpcApplicationError(NOT_EMPTY, path)
                        for p in kids:
                            del view[p]
                        del view[path]
                    else:
                        raise RpcApplicationError(
                            "BAD_OP", f"unknown multi op {kind!r}")
                except RpcApplicationError as e:
                    raise RpcApplicationError(
                        e.code,
                        f"multi op {i} ({kind} {path}): {e.message}")
            # phase 2: apply — cannot fail after validation (every apply
            # step below mirrors a validated view transition)
            futs = []
            touched: List[str] = []
            for op in ops:
                kind = op.get("op")
                path = self._norm(op.get("path", ""))
                if kind == "check":
                    results.append({"op": "check", "path": path})
                    continue
                if kind == "create":
                    # full ancestor chain, matching single-op create and
                    # the standby's replay (divergence otherwise)
                    for anc in ancestors(path):
                        if anc not in self._nodes:
                            self._nodes[anc] = _Node(b"", None)
                            futs.append(self._record({
                                "op": "create", "path": anc, "value": "",
                                "ephemeral": False, "seq": None}))
                            touched.append(anc)
                    value = bytes(op.get("value", b""))
                    eph = bool(op.get("ephemeral"))
                    sid = int(op.get("session_id", 0))
                    self._nodes[path] = _Node(value, sid if eph else None)
                    futs.append(self._record(
                        {"op": "create", "path": path,
                         "value": value.hex(), "ephemeral": eph,
                         "seq": None, "sid": sid if eph else None},
                        durable=not eph))
                    results.append({"op": "create", "path": path})
                elif kind == "set":
                    node = self._nodes[path]
                    node.value = bytes(op.get("value", b""))
                    node.version += 1
                    futs.append(self._record(
                        {"op": "set", "path": path,
                         "value": node.value.hex(),
                         "version": node.version},
                        durable=node.ephemeral_owner is None))
                    results.append({"op": "set", "path": path,
                                    "version": node.version})
                elif kind == "delete":
                    prefix = path + "/"
                    for p in [q for q in self._nodes
                              if q.startswith(prefix)]:
                        del self._nodes[p]
                        touched.append(p)
                    del self._nodes[path]
                    futs.append(self._record({"op": "delete",
                                              "path": path}))
                    results.append({"op": "delete", "path": path})
                touched.append(path)
                touched.append(self._parent(path))
            sync_idx = self._mut_index
        await self._await_durable(futs)
        self._signal_stream()
        try:
            await self._await_standby_ack(sync_idx)
        finally:
            if touched:
                self._signal_change(*touched)
        return {"results": results, "ftoken": self._fencing_token}

    async def handle_list(self, path: str = "") -> dict:
        path = self._norm(path)
        with self._lock:
            if path != "/" and path not in self._nodes:
                raise RpcApplicationError(NO_NODE, path)
            prefix = path if path.endswith("/") else path + "/"
            children = sorted({
                p[len(prefix):].split("/", 1)[0]
                for p in self._nodes
                if p.startswith(prefix)
            })
        return {"children": children, "ftoken": self._fencing_token}

    async def handle_watch(
        self, path: str = "", known_version: int = -2,
        max_wait_ms: int = 10_000,
    ) -> dict:
        """Long-poll: returns when the node (or its children) changed vs
        ``known_version`` (use the ``cversion`` from the previous call), or
        on timeout. version -1 = node absent."""
        path = self._norm(path)

        def snapshot():
            with self._lock:
                node = self._nodes.get(path)
                prefix = path if path.endswith("/") else path + "/"
                children = sorted({
                    p[len(prefix):].split("/", 1)[0]
                    for p in self._nodes if p.startswith(prefix)
                })
                version = node.version if node else -1
                cver = hash((version, tuple(children))) & 0x7FFFFFFF
                return {
                    "exists": node is not None,
                    "value": node.value if node else b"",
                    "version": version,
                    "children": children,
                    "cversion": cver,
                }

        snap = snapshot()
        if known_version != -2 and snap["cversion"] == known_version:
            # parked long-poll by design: the enclosing rpc.server root
            # must not be tail-kept as a slow outlier
            root = current_span()
            if root is not None:
                root.annotate(tail_exempt="watch_longpoll")
            await self._wait_change(path, max_wait_ms / 1000.0)
            snap = snapshot()
        return snap

    # ------------------------------------------------------------------
    # replication: primary-side RPCs
    # ------------------------------------------------------------------

    async def handle_sync(self, timeout_ms: int = 10_000) -> dict:
        """ZK sync() parity: on a STANDBY, block until this replica has
        applied everything the upstream primary had committed when the
        call arrived — a read issued after sync() therefore observes
        every write acked before it, even when the client's reads were
        rotated onto a standby. On the primary it is a no-op. (As with
        ZK, the guarantee is relative to the CURRENT upstream: across a
        primary restart the standby full-transfers and indices re-align
        before acks resume.)"""
        if not self._standby:
            return {"index": self._mut_index,
                    "ftoken": self._fencing_token}
        deadline = time.monotonic() + timeout_ms / 1000  # ONE budget for
        # the upstream probe AND the catch-up wait
        host, port = self._upstream
        if self._sync_pool is None:
            self._sync_pool = RpcClientPool()
        pos = await self._sync_pool.call(
            host, port, "repl_position", {},
            timeout=max(1.0, deadline - time.monotonic()))
        target = int(pos["mut_index"])
        while True:
            with self._lock:
                if self._mut_index >= target:
                    return {"index": self._mut_index,
                            "ftoken": self._fencing_token}
                ev = self._stream_event
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise RpcApplicationError(
                    "SYNC_TIMEOUT",
                    f"applied {self._mut_index} < upstream {target}")
            try:
                await asyncio.wait_for(ev.wait(), remaining)
            except asyncio.TimeoutError:
                pass

    async def handle_repl_state(self) -> dict:
        """Full state transfer for a (re)joining standby: every node
        (ephemerals included, with owners), live session ids, sequence
        counters, and the (epoch, index) to resume from."""
        with self._lock:
            # copy under the lock, serialize after releasing it — the
            # hex-encode of a large tree must not stall writes/heartbeats
            raw_nodes = [
                (p, n.value, n.version, n.seq_counter, n.ephemeral_owner)
                for p, n in self._nodes.items()
            ]
            sessions = sorted(self._sessions)
            max_sid = self._max_sid_seen
            next_index = self._mut_index + 1
        return {
            "nodes": [
                {"path": p, "value": v.hex(), "version": ver,
                 "seq": seq, "sid": sid}
                for p, v, ver, seq, sid in raw_nodes
            ],
            "sessions": sessions,
            "max_sid": max_sid,
            "next_index": next_index,
            "epoch": self._epoch,
            "ftoken": self._fencing_token,
        }

    async def handle_ensemble(self) -> dict:
        """Ensemble discovery (ZK dynamic-config analog): the serving
        addresses of every standby in recent lease contact. A client
        configured with ONE endpoint learns the rest and can fail over
        without static fallback lists."""
        now = time.monotonic()
        window = max(self._leader_lease_sec, 15.0)
        with self._lock:
            live = {
                sid: addr for sid, addr in self._standby_addrs.items()
                if now - self._standby_last_pull.get(sid, 0) <= window * 10
                or self._standby_parked.get(sid, 0) > 0
            }
            # prune long-dead ids (a crash-looping standby mints a fresh
            # id per restart; the dict must not grow unboundedly)
            self._standby_addrs = live
            standbys = sorted({
                addr for sid, addr in live.items()
                if now - self._standby_last_pull.get(sid, 0) <= window
                or self._standby_parked.get(sid, 0) > 0
            })
        # a STANDBY also advertises its upstream: a client that only
        # knows standbys can still find the primary
        upstream = ""
        if self._standby and self._upstream:
            upstream = f"{self._upstream[0]}:{self._upstream[1]}"
        return {"standbys": standbys, "is_standby": self._standby,
                "primary": upstream, "ftoken": self._fencing_token}

    async def handle_repl_position(self) -> dict:
        """Election probe: (fencing token, mutation index, role). The
        failover helper promotes the reachable standby with the highest
        (ftoken, mut_index) — the ZK highest-zxid-wins analog."""
        with self._lock:
            return {
                "ftoken": self._fencing_token,
                "mut_index": self._mut_index,
                "standby": self._standby,
            }

    async def handle_repl_updates(
        self, from_index: int = 1, max_wait_ms: int = 10_000,
        max_updates: int = 500, epoch: str = "", standby_id: str = "",
        standby_addr: str = "",
    ) -> dict:
        """Long-poll the mutation stream from ``from_index`` within
        ``epoch``. Returns ``reset=True`` when the epoch doesn't match
        this server instance or the ring no longer covers the index (the
        standby full-transfers and resumes). ``standby_id`` makes the
        pull an ACK: requesting from_index implies everything before it
        was received — the semi-sync wait watches these (the same
        implicit-ACK design as the replication plane's seq pulls)."""
        if max_wait_ms > 0:
            # long-poll serve by design — never tail-keep its root
            root = current_span()
            if root is not None:
                root.annotate(tail_exempt="repl_updates_longpoll")
        if standby_id:
            with self._lock:
                # lease contact counts even before the epoch handshake
                # completes (a full-transferring standby is in contact)
                self._standby_last_pull[standby_id] = time.monotonic()
                if standby_addr:
                    self._standby_addrs[standby_id] = standby_addr
                if epoch == self._epoch:
                    prev = self._standby_acked.get(standby_id, 0)
                    self._standby_acked[standby_id] = max(
                        prev, from_index - 1)
            if epoch == self._epoch:
                self._ack_event.set()
                self._ack_event = asyncio.Event()
        deadline = time.monotonic() + max_wait_ms / 1000.0
        # A standby PARKED in this long-poll is in contact by definition:
        # count it for the quorum lease for the whole poll (its
        # _standby_last_pull stamp otherwise ages up to max_wait_ms,
        # letting a healthy primary spuriously lose its lease), and
        # refresh the stamp on the way out.
        if standby_id:
            self._standby_parked[standby_id] = (
                self._standby_parked.get(standby_id, 0) + 1)
        try:
            while True:
                with self._lock:
                    ring_start = (
                        self._recent[0][0] if self._recent
                        else self._mut_index + 1
                    )
                    if (
                        epoch != self._epoch
                        or from_index < ring_start
                        or from_index > self._mut_index + 1
                    ):
                        return {"reset": True, "updates": [], "indices": [],
                                "ftoken": self._fencing_token}
                    updates = [
                        (i, r) for i, r in self._recent if i >= from_index
                    ][:max_updates]
                    if updates:
                        return {
                            "reset": False,
                            "updates": [r for _, r in updates],
                            "indices": [i for i, _ in updates],
                            "ftoken": self._fencing_token,
                        }
                    ev = self._stream_event
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {"reset": False, "updates": [], "indices": [],
                            "ftoken": self._fencing_token}
                try:
                    await asyncio.wait_for(ev.wait(), remaining)
                except asyncio.TimeoutError:
                    return {"reset": False, "updates": [], "indices": [],
                            "ftoken": self._fencing_token}
        finally:
            if standby_id:
                n = self._standby_parked.get(standby_id, 1) - 1
                if n <= 0:
                    self._standby_parked.pop(standby_id, None)
                else:
                    self._standby_parked[standby_id] = n
                self._standby_last_pull[standby_id] = time.monotonic()

    # ------------------------------------------------------------------
    # replication: standby side
    # ------------------------------------------------------------------

    def _apply_record_locked(self, rec: dict,
                             include_ephemeral: bool) -> Set[str]:
        """Apply one stream/WAL record; returns touched paths for watch
        signalling. WAL replay passes include_ephemeral=False (ephemerals
        die with the process that owned the sessions); standby apply
        passes True (it mirrors the primary's live state)."""
        op = rec.get("op")
        touched: Set[str] = set()
        if op == "create":
            parent = self._parent(rec["path"])
            parts = [p for p in parent.split("/") if p]
            cur = ""
            for p in parts:
                cur += "/" + p
                if cur not in self._nodes:
                    self._nodes[cur] = _Node(b"", None)
                    touched.add(cur)
            if rec.get("seq") is not None:
                pnode = self._nodes.get(parent)
                if pnode is not None:
                    pnode.seq_counter = max(
                        pnode.seq_counter, rec["seq"] + 1)
            if not rec.get("ephemeral"):
                node = self._nodes.setdefault(rec["path"], _Node(b"", None))
                node.value = bytes.fromhex(rec["value"])
                touched.add(rec["path"])
            elif include_ephemeral:
                self._nodes[rec["path"]] = _Node(
                    bytes.fromhex(rec["value"]), rec.get("sid"))
                touched.add(rec["path"])
            touched.add(parent)
        elif op == "set":
            node = self._nodes.get(rec["path"])
            if node is not None:
                node.value = bytes.fromhex(rec["value"])
                node.version = rec["version"]
                touched.add(rec["path"])
        elif op == "delete":
            prefix = rec["path"] + "/"
            for p in [q for q in self._nodes if q.startswith(prefix)]:
                del self._nodes[p]
                touched.add(p)
            if self._nodes.pop(rec["path"], None) is not None:
                touched.add(rec["path"])
            touched.add(self._parent(rec["path"]))
        elif op == "create_session":
            sid = rec["sid"]
            self._max_sid_seen = max(self._max_sid_seen, sid)
            # deadline inf until promote: a standby cannot observe the
            # owner's heartbeats, so it must not expire anything
            self._sessions[sid] = float("inf")
        elif op in ("close_session", "expire_session"):
            sid = rec["sid"]
            self._sessions.pop(sid, None)
            for p in [
                q for q, n in self._nodes.items()
                if n.ephemeral_owner == sid
            ]:
                del self._nodes[p]
                touched.add(p)
                touched.add(self._parent(p))
        return touched

    def _apply_stream_batch(self, updates: List[dict],
                            indices: List[int]) -> None:
        touched: Set[str] = set()
        with self._lock:
            for rec, idx in zip(updates, indices):
                touched |= self._apply_record_locked(
                    rec, include_ephemeral=True)
                self._mut_index = idx
                self._recent.append((idx, rec))
                # persist what the primary persists (same durability
                # filter) so a promoted standby restarts like a primary
                durable = (
                    rec.get("op") in ("set", "delete")
                    or (rec.get("op") == "create"
                        and not (rec.get("ephemeral")
                                 and rec.get("seq") is None))
                )
                if durable and self._wal is not None:
                    self._dirty = True
                    fut = self._wal.append_async(rec)
                    fut.add_done_callback(self._on_standby_wal_write)
        if touched:
            self._signal_change(*touched)
        else:
            self._signal_stream()

    def _on_standby_wal_write(self, fut) -> None:
        """A fenced WAL on a standby must be LOUD: persistence has
        stopped while replication looks healthy, and a later promote +
        restart would lose everything since the last snapshot. promote()
        refuses while the WAL is failed (force=True overrides)."""
        exc = fut.exception()
        if exc is not None and not getattr(self, "_wal_fail_logged", False):
            self._wal_fail_logged = True
            log.error(
                "coordinator standby: WAL append failed (%r) — durable "
                "persistence has STOPPED; promote() will refuse until "
                "the WAL is healthy", exc)

    def _apply_state_transfer(self, state: dict) -> None:
        with self._lock:
            self._nodes = {"/": _Node(b"", None)}
            for ent in state["nodes"]:
                node = _Node(bytes.fromhex(ent["value"]), ent.get("sid"))
                node.version = ent["version"]
                node.seq_counter = ent.get("seq", 0)
                self._nodes[ent["path"]] = node
            self._sessions = {sid: float("inf")
                              for sid in state.get("sessions", [])}
            self._max_sid_seen = state.get("max_sid", 0)
            self._mut_index = state["next_index"] - 1
            self._recent.clear()
            self._dirty = True
        self._signal_change(*[e["path"] for e in state["nodes"]])

    async def _standby_loop(self) -> None:
        """Tail the upstream primary: full transfer, then incremental
        long-poll catch-up; optional self-promotion after a sustained
        outage (see class docstring for the split-brain caveat)."""
        from ..rpc.errors import RpcConnectionError, RpcTimeout

        from ..utils.misc import local_ip

        pool = RpcClientPool()
        host, port = self._upstream
        next_index = None
        epoch = ""
        down_since: Optional[float] = None
        # advertised once: constant for the process lifetime. A loopback
        # answer is useless to REMOTE clients; advertise nothing rather
        # than teach every client a self-pointing fallback.
        my_ip = local_ip()
        my_addr = ("" if my_ip.startswith("127.")
                   else f"{my_ip}:{self.port}")
        try:
            while self._standby:
                try:
                    if self._upstream != (host, port):
                        host, port = self._upstream  # repointed mid-loop
                        next_index = None
                    if next_index is None:
                        state = await pool.call(
                            host, port, "repl_state", {}, timeout=30)
                        self._apply_state_transfer(state)
                        next_index = state["next_index"]
                        epoch = state.get("epoch", "")
                        self._adopt_ftoken(state.get("ftoken", 0))
                        log.info(
                            "coordinator standby: state transfer done "
                            "(%d nodes, resuming at %d epoch=%s)",
                            len(state["nodes"]), next_index, epoch[:8])
                    r = await pool.call(
                        host, port, "repl_updates",
                        {"from_index": next_index, "max_wait_ms": 5000,
                         "epoch": epoch, "standby_id": self._epoch,
                         # advertise our serving endpoint so clients can
                         # discover the ensemble from any one member
                         "standby_addr": my_addr},
                        timeout=35,
                        tail_exempt=True,  # 5s long-poll by design
                    )
                    down_since = None
                    self._adopt_ftoken(r.get("ftoken", 0))
                    if r.get("reset"):
                        next_index = None
                        continue
                    if r["updates"]:
                        self._apply_stream_batch(r["updates"], r["indices"])
                        next_index = r["indices"][-1] + 1
                except asyncio.CancelledError:
                    raise
                except (RpcConnectionError, RpcTimeout, ConnectionError,
                        OSError) as e:
                    # ONLY unreachability counts toward auto-promote: an
                    # application-level error with a LIVE primary must
                    # never trigger self-promotion (split-brain)
                    now = time.monotonic()
                    down_since = down_since or now
                    outage = now - down_since
                    if (
                        self._auto_promote_after is not None
                        and outage >= self._auto_promote_after
                    ):
                        log.warning(
                            "coordinator standby: upstream %s:%d "
                            "unreachable for %.1fs — self-promoting",
                            host, port, outage)
                        await self.promote_async()
                        return
                    log.debug("coordinator standby pull error: %r", e)
                    await asyncio.sleep(0.5)
                except Exception:
                    down_since = None
                    log.exception(
                        "coordinator standby: apply/protocol error — "
                        "retrying with full state transfer")
                    next_index = None
                    await asyncio.sleep(1.0)
        finally:
            await pool.close()

    def _adopt_ftoken(self, token: int) -> None:
        if token > self._fencing_token:
            self._fencing_token = token
            self._mark_dirty()

    def promote(self, force: bool = False) -> None:
        """Standby → primary. Replicated sessions get a fresh TTL grace
        window (owners re-establish by heartbeating, as with a ZK leader
        change); session ids continue above everything ever seen; the
        fencing token is bumped STRICTLY ABOVE the old primary's, so any
        client that has talked to this primary refuses acks from the
        deposed one. Refuses while the local WAL is fenced (state since
        the last snapshot would not be durable) unless ``force``.

        Loop-side callers (the standby loop's self-promotion, the
        promote RPC) use :meth:`promote_async`, which runs the durable
        snapshot in an executor — fsyncing on the loop at the promote
        moment is exactly when heartbeats/session grants must keep
        flowing (rstpu-check loop-blocking)."""
        if self._promote_state(force):
            self._post_promote_snapshot()

    async def promote_async(self, force: bool = False) -> None:
        if self._promote_state(force):
            # shield: once promotion flipped state, the durable snapshot
            # of the bumped fencing token must complete even if THIS
            # task is cancelled (the standby loop's self-promotion is
            # cancelled by _promote_state scheduling its own teardown)
            await asyncio.shield(asyncio.get_running_loop().run_in_executor(
                None, self._post_promote_snapshot))

    def _promote_state(self, force: bool) -> bool:
        """Flip standby→primary state; True iff a transition happened."""
        if (
            not force and self._wal is not None
            and self._wal.failed is not None
        ):
            raise RuntimeError(
                f"refusing to promote with a fenced WAL "
                f"({self._wal.failed!r}); pass force=True to override")
        with self._lock:
            if not self._standby:
                return False
            self._standby = False
            grace = time.monotonic() + self._ttl
            self._sessions = {sid: grace for sid in self._sessions}
            self._session_ids = itertools.count(self._max_sid_seen + 1)
            self._standby_acked.clear()  # acks restart under MY serving
            self._standby_last_pull.clear()  # lease restarts too
            self._standby_parked.clear()
            self._standby_addrs.clear()
            self._fencing_token += 1
            self._dirty = True
        task, self._standby_task = self._standby_task, None
        if task is not None:
            try:
                current = asyncio.current_task()
            except RuntimeError:  # sync promote() off the loop thread
                current = None
            if task is not current:
                # never cancel the task running THIS promotion (standby
                # self-promotion): the scheduled cancel would land on
                # promote_async's snapshot await; the loop returns right
                # after promoting anyway
                task.cancel()
        return True

    def _post_promote_snapshot(self) -> None:
        try:
            if self._data_dir:
                self._write_snapshot()  # make the token bump durable now
        except Exception:
            log.exception("coordinator: post-promote snapshot failed")
        log.info("coordinator: promoted to primary (%d sessions in grace, "
                 "fencing token %d)",
                 len(self._sessions), self._fencing_token)

    def repoint(self, host: str, port: int) -> None:
        """Re-target a standby at a NEW upstream (after a failover
        elsewhere in the ensemble). The standby loop notices and does a
        full state transfer from the new primary."""
        if not self._standby:
            raise RuntimeError("repoint: not a standby")
        self._upstream = (host, port)

    async def handle_repoint(self, host: str = "", port: int = 0) -> dict:
        try:
            self.repoint(host, int(port))
        except RuntimeError as e:
            raise RpcApplicationError(NOT_PRIMARY, str(e))
        return {}

    async def handle_promote(self, force: bool = False) -> dict:
        """Operator/controller-driven failover for standalone standby
        processes (the in-process path calls promote() directly)."""
        try:
            await self.promote_async(force=bool(force))
        except RuntimeError as e:
            raise RpcApplicationError("WAL_ERROR", str(e))
        return {"standby": self._standby}

    @property
    def is_standby(self) -> bool:
        return self._standby


def promote_best(endpoints: List[Tuple[str, int]],
                 ioloop: Optional[IoLoop] = None,
                 timeout: float = 10.0,
                 ensemble_size: Optional[int] = None) -> Tuple[str, int]:
    """Ensemble failover (controller/operator entry point): probe every
    reachable endpoint's (ftoken, mut_index), promote the most advanced
    STANDBY — the ZK highest-zxid-wins rule — then repoint the remaining
    standbys at the winner. Returns the new primary's endpoint.

    No-acked-write-lost guarantee: a quorum-acked mutation lives on
    >= floor(N/2) standbys, so the probe must reach enough standbys to
    intersect EVERY possible ack set — ceil(N/2) of the N-1 standbys
    (with the dead primary excluded). ``ensemble_size`` is N; defaults
    to len(endpoints) + 1 (caller lists the standbys, primary is dead).
    Fewer answers than that → RuntimeError instead of silently electing
    a lagging standby and discarding acked writes. Raises RuntimeError
    too when a live primary is still reachable."""
    loop = ioloop or IoLoop.default()
    pool = RpcClientPool()
    n = ensemble_size or (len(endpoints) + 1)

    async def probe(host, port):
        try:
            r = await pool.call(host, port, "repl_position", {},
                                timeout=timeout)
            return (host, port, r)
        except Exception:
            return (host, port, None)

    async def run():
        import asyncio as aio

        try:
            results = await aio.gather(
                *(probe(h, p) for h, p in endpoints))
            live = [(h, p, r) for h, p, r in results if r is not None]
            if any(not r["standby"] for _, _, r in live):
                alive = [(h, p) for h, p, r in live if not r["standby"]]
                raise RuntimeError(
                    f"live primary still reachable at {alive}; demote or "
                    f"partition it before promoting")
            standbys = [(h, p, r) for h, p, r in live if r["standby"]]
            need = n - n // 2  # ceil(N/2): intersects every ack majority
            if len(standbys) < need:
                raise RuntimeError(
                    f"only {len(standbys)}/{need} standbys answered "
                    f"(ensemble {n}): electing now could lose "
                    f"quorum-acked writes")
            standbys.sort(
                key=lambda t: (t[2]["ftoken"], t[2]["mut_index"]),
                reverse=True)
            win_h, win_p, _ = standbys[0]
            await pool.call(win_h, win_p, "promote", {}, timeout=timeout)
            for h, p, _ in standbys[1:]:
                try:
                    await pool.call(h, p, "repoint",
                                    {"host": win_h, "port": win_p},
                                    timeout=timeout)
                except Exception:
                    log.exception(
                        "promote_best: repoint %s:%d failed", h, p)
            return (win_h, win_p)
        finally:
            await pool.close()

    return loop.run_sync(run(), timeout=timeout * (len(endpoints) + 2))


class CoordinatorClient:
    """Sync client + session keepalive + watch loops + lock/election
    recipes (the Curator equivalent)."""

    def __init__(self, host: str, port: int, ioloop: Optional[IoLoop] = None,
                 session_ttl: Optional[float] = None,
                 fallbacks: Optional[List[Tuple[str, int]]] = None):
        self._host, self._port = host, port
        # failover rotation: primary first, then standbys. A NOT_PRIMARY
        # rejection or connection failure rotates to the next endpoint
        # (sessions are replicated, so the session survives the switch).
        self._endpoints: List[Tuple[str, int]] = [(host, port)]
        self._endpoints.extend(fallbacks or [])
        self._ioloop = ioloop or IoLoop.default()
        self._pool = RpcClientPool()
        self._stop = threading.Event()
        self._hb_suspended = threading.Event()
        self._requested_ttl = session_ttl
        # highest fencing token seen from any primary; acks carrying a
        # LOWER token come from a deposed primary and are rejected
        self._max_ftoken = 0
        # fired (from the heartbeat thread) after an expired session was
        # re-established: ephemerals owned by the old session are gone —
        # owners (participants) re-register here
        self.on_session_reestablished: Optional[Callable[[], None]] = None
        r = self._call("create_session", ttl=session_ttl)
        self.session_id = r["session_id"]
        self._ttl = r["ttl"]
        self._discover_endpoints()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, name="coord-heartbeat", daemon=True
        )
        self._hb_thread.start()
        self._watch_threads: List[threading.Thread] = []

    # -- plumbing ---------------------------------------------------------

    # mutations must NOT be silently re-sent after a connection error:
    # the primary may have executed them before the connection died, and
    # e.g. a duplicated ephemeral-sequential lock node deadlocks every
    # other contender. A NOT_PRIMARY rejection is always retry-safe (the
    # standby executed nothing). create_session is exempt: a duplicate
    # session just expires unused.
    _UNSAFE_RETRY = frozenset({"create", "set", "delete", "multi"})

    def _call(self, method: str, timeout: float = 30.0, **args):
        # any coordinator RPC that long-polls by protocol (watch, lock
        # recipes) has a BY-DESIGN slow RTT: never tail-keep it as an
        # outlier trace
        exempt = int(args.get("max_wait_ms") or 0) > 0

        async def go(host: str, port: int):
            return await self._pool.call(
                host, port, method, args, timeout=timeout,
                tail_exempt=exempt,
            )

        last: Optional[Exception] = None
        for attempt in range(max(2 * len(self._endpoints), 1)):
            host, port = self._host, self._port
            fenced = None
            try:
                r = self._ioloop.run_sync(
                    go(host, port), timeout=timeout + 5)
                ftoken = (r or {}).get("ftoken") \
                    if isinstance(r, dict) else None
                if ftoken is None or ftoken >= self._max_ftoken:
                    if ftoken is not None:
                        self._max_ftoken = ftoken
                    return r
                # fencing: this ack came from a DEPOSED primary (a newer
                # one has a higher token) — a mutation it applied may be
                # discarded by the failover, so never report it as
                # committed. Mutations must surface the failure (the
                # deposed primary DID apply them — a blind retry
                # double-applies); reads just rotate.
                fenced = RpcApplicationError(
                    NOT_PRIMARY,
                    f"fenced: ack token {ftoken} < {self._max_ftoken}")
            except RpcApplicationError as e:
                if e.code != NOT_PRIMARY or len(self._endpoints) == 1:
                    raise
                last = e
            except RpcError as e:
                if len(self._endpoints) == 1:
                    raise
                last = e
                if method in self._UNSAFE_RETRY:
                    # rotate so the NEXT call targets a live endpoint,
                    # but surface this failure — the caller must decide
                    # whether the mutation may have been applied
                    self._rotate(host, port)
                    raise
            if fenced is not None:
                self._rotate(host, port)
                if method in self._UNSAFE_RETRY:
                    raise fenced
                last = fenced
                continue
            # rotate to the next endpoint and retry
            self._rotate(host, port)
            if attempt >= len(self._endpoints):
                time.sleep(0.3)  # full rotation failed — brief backoff
        raise last  # type: ignore[misc]

    def _discover_endpoints(self) -> None:
        """Learn the rest of the ensemble from whichever endpoint is
        serving (ZK dynamic-config analog): standbys in lease contact
        become fallback endpoints, so a client configured with one
        address survives failovers. Best-effort; static fallbacks and
        already-known endpoints are kept."""
        try:
            r = self._call("ensemble", timeout=10.0)
        except Exception:
            return
        known = list(r.get("standbys") or [])
        if r.get("primary"):
            known.append(r["primary"])
        for addr in known:
            try:
                host, port_s = addr.rsplit(":", 1)
                ep = (host, int(port_s))
            except ValueError:
                continue
            if ep not in self._endpoints:
                self._endpoints.append(ep)

    def _rotate(self, host: str, port: int) -> None:
        idx = self._endpoints.index((host, port)) \
            if (host, port) in self._endpoints else 0
        self._host, self._port = self._endpoints[
            (idx + 1) % len(self._endpoints)]

    def suspend_heartbeats(self) -> None:
        """Stop heartbeating WITHOUT closing: the server expires the
        session after its TTL — the faithful 'process wedged / GC pause /
        partitioned' simulation (chaos harness + tests). resume() lets
        the next beat discover the expiry and re-establish."""
        self._hb_suspended.set()

    def resume_heartbeats(self) -> None:
        self._hb_suspended.clear()

    def _heartbeat_loop(self) -> None:
        interval = self._ttl / 3
        beats = 0
        while not self._stop.wait(interval):
            if self._hb_suspended.is_set():
                continue
            try:
                self._call("heartbeat", session_id=self.session_id)
            except RpcApplicationError as e:
                if e.code == NO_SESSION:
                    # the session expired server-side (TTL lapse while we
                    # were wedged/partitioned): its ephemerals are gone.
                    # Re-establish rather than beating a dead session
                    # forever — the ZK session-re-establishment analog.
                    self._reestablish_session()
            except RpcError:
                pass  # reconnects on next beat; session may expire meanwhile
            except Exception:
                log.exception("coordinator heartbeat failed")
            beats += 1
            if beats % 5 == 0 or len(self._endpoints) == 1:
                # keep the ensemble view fresh: a client created before
                # any standby registered would otherwise never learn
                # its failover endpoints
                self._discover_endpoints()

    def _reestablish_session(self) -> None:
        try:
            r = self._call("create_session", ttl=self._requested_ttl)
        except Exception:
            log.exception("coordinator session re-establishment failed "
                          "(retrying on the next beat)")
            return
        old = self.session_id
        self.session_id = r["session_id"]
        self._ttl = r["ttl"]
        log.warning("coordinator session %d expired — re-established as %d",
                    old, self.session_id)
        cb = self.on_session_reestablished
        if cb is not None:
            try:
                cb()
            except Exception:
                log.exception("on_session_reestablished callback failed")

    def close(self) -> None:
        self._stop.set()
        try:
            self._call("close_session", session_id=self.session_id)
        except Exception:
            pass
        self._hb_thread.join(timeout=2.0)
        for t in self._watch_threads:
            t.join(timeout=2.0)
        self._ioloop.run_sync(self._pool.close())

    # -- node ops ---------------------------------------------------------

    def create(self, path: str, value: bytes = b"", ephemeral: bool = False,
               sequential: bool = False) -> str:
        return self._call(
            "create", path=path, value=value, ephemeral=ephemeral,
            sequential=sequential, session_id=self.session_id,
        )["path"]

    def ensure(self, path: str, value: bytes = b"") -> None:
        try:
            self.create(path, value)
        except RpcApplicationError as e:
            if e.code != NODE_EXISTS:
                raise

    def get(self, path: str) -> Tuple[bytes, int]:
        r = self._call("get", path=path)
        return bytes(r["value"]), r["version"]

    def get_or_none(self, path: str) -> Optional[bytes]:
        try:
            return self.get(path)[0]
        except RpcApplicationError as e:
            if e.code == NO_NODE:
                return None
            raise

    def set(self, path: str, value: bytes, expected_version: int = -1) -> int:
        return self._call(
            "set", path=path, value=value, expected_version=expected_version
        )["version"]

    def put(self, path: str, value: bytes) -> None:
        """create-or-set."""
        try:
            self.create(path, value)
        except RpcApplicationError as e:
            if e.code != NODE_EXISTS:
                raise
            self.set(path, value)

    def delete(self, path: str, recursive: bool = False) -> None:
        self._call("delete", path=path, recursive=recursive)

    def delete_if_exists(self, path: str, recursive: bool = False) -> None:
        try:
            self.delete(path, recursive=recursive)
        except RpcApplicationError as e:
            if e.code != NO_NODE:
                raise

    def list(self, path: str) -> List[str]:
        try:
            return self._call("list", path=path)["children"]
        except RpcApplicationError as e:
            if e.code == NO_NODE:
                return []
            raise

    def exists(self, path: str) -> bool:
        return self._call("exists", path=path)["exists"]

    def multi(self, ops: List[dict]) -> List[dict]:
        """Atomic all-or-nothing batch (ZK multi). Each op dict mirrors
        the single-op RPC fields, e.g.
        {"op": "check", "path": p, "expected_version": v},
        {"op": "create", "path": p, "value": b"..."},
        {"op": "set", "path": p, "value": b"...", "expected_version": v},
        {"op": "delete", "path": p, "recursive": True}."""
        return self._call("multi", ops=ops)["results"]

    def sync(self, timeout_ms: int = 10_000) -> int:
        """ZK sync() parity: make the endpoint this client currently
        reads from catch up with its primary before the next read —
        read-your-writes even when reads rotated onto a standby.
        Returns the endpoint's applied index."""
        # RPC timeout must cover the server-side wait budget
        return self._call("sync", timeout=timeout_ms / 1000 + 5.0,
                          timeout_ms=timeout_ms)["index"]

    # -- watches ----------------------------------------------------------

    def watch(self, path: str, callback, poll_ms: int = 5_000) -> threading.Event:
        """Fire ``callback(snapshot_dict)`` on every observed change (and
        once initially). Returns an Event; set it to stop the watch.

        Error backoff goes through the unified RetryPolicy (growing,
        jittered, deterministic under RSTPU_RETRY_SEED like the follower
        pull loop; ``retry.attempts op=coord.watch`` on /stats) instead
        of the old flat 0.5 s sleep — a control-plane outage must not be
        hammered at a fixed cadence by every watcher at once."""
        from ..utils.retry_policy import (RetryPolicy, backoff_step,
                                          seeded_rng)

        stop = threading.Event()
        policy = RetryPolicy(max_attempts=1 << 30, base_delay=0.2,
                             max_delay=2.0, floor=0.1)
        rng = seeded_rng()

        def loop():
            known = -2
            attempt = 0
            while not stop.is_set() and not self._stop.is_set():
                try:
                    snap = self._call(
                        "watch", path=path, known_version=known,
                        max_wait_ms=poll_ms, timeout=poll_ms / 1000 + 10,
                    )
                except (RpcError, RpcApplicationError):
                    backoff_step(policy, attempt, op="coord.watch", rng=rng)
                    attempt += 1
                    continue
                except Exception:
                    log.exception("watch loop error for %s", path)
                    backoff_step(policy, attempt, op="coord.watch", rng=rng)
                    attempt += 1
                    continue
                attempt = 0
                if snap["cversion"] != known:
                    known = snap["cversion"]
                    try:
                        callback(snap)
                    except Exception:
                        log.exception("watch callback failed for %s", path)

        t = threading.Thread(target=loop, name=f"watch:{path}", daemon=True)
        t.start()
        self._watch_threads.append(t)
        return stop

    # -- recipes -----------------------------------------------------------

    def acquire_lock(self, lock_path: str, timeout: float = 30.0) -> Optional[str]:
        """InterProcessMutex recipe: ephemeral sequential node; lowest wins.
        Returns my node path (pass to release_lock), or None on timeout."""
        self.ensure(lock_path)
        me = self.create(f"{lock_path}/lock-", ephemeral=True, sequential=True)
        my_name = me.rsplit("/", 1)[1]
        deadline = time.monotonic() + timeout
        known = -2  # first watch returns immediately with the snapshot
        while time.monotonic() < deadline:
            remaining = max(0.05, deadline - time.monotonic())
            wait_ms = int(min(remaining, 2.0) * 1000)
            snap = self._call(
                "watch", path=lock_path, known_version=known,
                max_wait_ms=wait_ms, timeout=wait_ms / 1000 + 10,
            )
            known = snap["cversion"]
            siblings = sorted(snap["children"])
            if siblings and siblings[0] == my_name:
                return me
        self.delete_if_exists(me)
        return None

    def release_lock(self, my_node: str) -> None:
        self.delete_if_exists(my_node)

    def elect_leader(self, election_path: str, my_id: str) -> bool:
        """Simple leader election: ephemeral node claim. True if leader."""
        self.ensure(election_path)
        try:
            self.create(f"{election_path}/leader", my_id.encode(),
                        ephemeral=True)
            return True
        except RpcApplicationError as e:
            if e.code == NODE_EXISTS:
                return False
            raise

    def current_leader(self, election_path: str) -> Optional[str]:
        raw = self.get_or_none(f"{election_path}/leader")
        return raw.decode() if raw is not None else None


def main(argv=None) -> int:
    """Standalone coordinator process (the zkServer analog)."""
    import argparse

    p = argparse.ArgumentParser(description="coordination server")
    p.add_argument("--port", type=int, default=2181)
    p.add_argument("--data_dir", default=None,
                   help="durable WAL+snapshot dir (omit for in-memory)")
    p.add_argument("--session_ttl", type=float, default=DEFAULT_SESSION_TTL)
    p.add_argument("--replica_of", default=None, metavar="HOST:PORT",
                   help="run as a standby tailing this primary")
    p.add_argument("--auto_promote_after", type=float, default=None,
                   help="standby self-promotes after the primary is "
                        "unreachable this many seconds (deploy at most "
                        "one such standby)")
    p.add_argument("--min_sync_standbys", type=int, default=0,
                   help="semi-sync: mutations ack only after this many "
                        "standbys received them (0 = async shipping)")
    args = p.parse_args(argv)
    upstream = None
    if args.replica_of:
        h, _, pt = args.replica_of.rpartition(":")
        upstream = (h, int(pt))
    srv = CoordinatorServer(port=args.port, session_ttl=args.session_ttl,
                            data_dir=args.data_dir, replica_of=upstream,
                            auto_promote_after=args.auto_promote_after,
                            min_sync_standbys=args.min_sync_standbys)
    print(f"coordinator up: port={srv.port} data_dir={args.data_dir} "
          f"standby={srv.is_standby}", flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
