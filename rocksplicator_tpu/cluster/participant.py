"""Participant: joins the cluster and drives state transitions.

Reference: Participant.java:67-512 — started in the embedded JVM by
``common::JoinCluster`` (helix_client.cpp:216-227); registers the state
-model factory by type, executes controller-issued transitions against the
local Admin service, reports current states. Rebuilt natively: the
participant is a plain object the service process constructs — no JVM.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

from ..testing import failpoints as fp
from ..utils.stats import Stats
from .coordinator import CoordinatorClient
from .helix_utils import AdminClient
from .model import (
    DROPPED,
    ERROR,
    InstanceInfo,
    OFFLINE,
    cluster_path,
    decode_assignments,
    encode_states,
)
from .state_models import FACTORIES
from .state_models.base import ClusterContext, TransitionError

log = logging.getLogger(__name__)


class Participant:
    def __init__(
        self,
        coord_host: str,
        coord_port: int,
        cluster: str,
        instance: InstanceInfo,
        state_model: str = "LeaderFollower",
        backup_store_uri: Optional[str] = None,
        transition_workers: int = 4,
        catch_up_timeout: float = 30.0,
        error_retry_backoff: float = 1.0,
        view_cluster: Optional[str] = None,
        coord_fallbacks: Optional[List[Tuple[str, int]]] = None,
        promotion_seq_slack: Optional[int] = None,
    ):
        self.error_retry_backoff = error_retry_backoff
        self.cluster = cluster
        self.instance = instance
        self.coord = CoordinatorClient(coord_host, coord_port,
                                       fallbacks=coord_fallbacks)
        self.admin = AdminClient()
        self.ctx = ClusterContext(
            self.coord, self.admin, cluster, instance,
            backup_store_uri=backup_store_uri,
            catch_up_timeout=catch_up_timeout,
            view_cluster=view_cluster,
            promotion_seq_slack=promotion_seq_slack,
        )
        factory_cls = FACTORIES[state_model]
        self.factory = factory_cls(self.ctx)
        self._current: Dict[str, str] = {}
        self._applied_upstream: Dict[str, str] = {}
        self._applied_epoch: Dict[str, int] = {}
        # set when a rejoin attempt failed mid-way: the periodic seq
        # loop retries it (heartbeats succeed on the fresh session, so
        # NO_SESSION never fires again to re-trigger the callback)
        self._rejoin_pending = False
        self._state_lock = threading.Lock()
        self._publish_lock = threading.Lock()
        self._executor = ThreadPoolExecutor(
            max_workers=transition_workers, thread_name_prefix="transition"
        )
        self._inflight: Dict[str, bool] = {}
        self._path = lambda *p: cluster_path(cluster, *p)
        self._stopped = False
        # register (ephemeral) + publish empty current state + watch
        self.coord.ensure(self._path("instances"))
        self.coord.create(
            self._path("instances", instance.instance_id),
            instance.encode(), ephemeral=True,
        )
        self.coord.put(
            self._path("currentstates", instance.instance_id),
            encode_states({}),
        )
        # session-expiry recovery (the ZK session-re-establishment
        # analog): a reaped participant re-registers its ephemeral
        # instance node, republishes current state, and re-evaluates
        # assignments — serving resumes WITHOUT a process restart
        self.coord.on_session_reestablished = self._rejoin
        self._watch_stop = self.coord.watch(
            self._path("assignments", instance.instance_id),
            self._on_assignments,
        )
        # PartitionStateUpdater (reference utils/PartitionStateUpdater.java):
        # periodically checkpoint led partitions' seqs so the 3-node-failure
        # guard compares against fresh numbers, not just promotion-time ones.
        self._seq_updater = threading.Thread(
            target=self._partition_seq_loop, name="partition-seq-updater",
            daemon=True,
        )
        self._seq_updater.start()

    def _partition_seq_loop(self, interval: float = 5.0) -> None:
        from ..utils.segment_utils import partition_name_to_db_name

        while not self._stopped:
            time.sleep(interval)
            if self._rejoin_pending and not self._stopped:
                self._rejoin()
            try:
                for partition, state in self.current_states.items():
                    db_name = partition_name_to_db_name(partition)
                    if state in ("LEADER", "MASTER"):
                        seq = self.admin.get_sequence_number(
                            self.ctx.local_admin_addr, db_name)
                        if seq is not None:
                            self.ctx.set_partition_seq(partition, seq)
                    elif state in ("FOLLOWER", "SLAVE"):
                        self._heal_pull_stall(partition, db_name)
            except Exception:
                if not self._stopped:
                    log.exception("partition seq updater failed")

    def _heal_pull_stall(self, partition: str, db_name: str) -> None:
        """Self-heal a steady follower whose pull loop can NEVER
        converge (it gets no state transition on its own — both states
        were found wedged by the reshard chaos harness):

        - ``pull_stalled_wal_gap``: the upstream purged its WAL past
          our position. Force the ERROR→replan path: the
          Offline→Follower transition re-runs with the needRebuildDB
          WAL-availability check and rebuilds from a peer snapshot
          (local data kept until the rebuild lands).
        - ``pull_diverged``: we are persistently AHEAD of the leader's
          commit point — a deposed-leader window write poisoned our
          suffix. Clear + rejoin through OFFLINE (the follower analog
          of the r11 deposed-leader resync; the lineage's copies live
          on the leader and its other followers).

        Discipline: the COMMON path (no stall) probes WITHOUT touching
        the partition's inflight slot — claiming it even briefly races
        assignment delivery (an update arriving while claimed is
        skipped by _on_assignments and, since the controller never
        rewrites identical assignments, would be lost for good — the
        exact lost-update class _run_transition's finally re-evaluation
        exists for). Only a CONFIRMED stall claims the slot, re-probes
        under it (the destructive clear must not race a transition that
        just promoted this node), acts, releases, and then ALWAYS
        re-evaluates assignments to recover any update that arrived
        while claimed. The probe is the flags-only check_pull_stall
        RPC (no disk I/O), cheap enough per follower shard per tick."""
        info = self.admin.check_pull_stall(
            self.ctx.local_admin_addr, db_name)
        if not info or not (info.get("pull_diverged")
                            or info.get("pull_stalled_wal_gap")):
            return
        with self._state_lock:
            if self._inflight.get(partition):
                return
            if self._current.get(partition) not in ("FOLLOWER", "SLAVE"):
                return
            self._inflight[partition] = True
        try:
            # re-probe under the claim: the stall (and this node's
            # follower role) must still hold with transitions excluded
            info = self.admin.check_pull_stall(
                self.ctx.local_admin_addr, db_name)
            if not info or info.get("role") not in ("FOLLOWER",
                                                    "OBSERVER"):
                return
            if info.get("pull_diverged"):
                log.warning(
                    "%s: follower DIVERGED from the lineage (applied "
                    "ahead of the leader's commit point) — clearing "
                    "and rejoining", partition)
                Stats.get().incr("participant.diverged_resyncs")
                try:
                    self.admin.clear_db(self.ctx.local_admin_addr,
                                        db_name, reopen=False)
                except Exception:
                    log.exception("%s: diverged-resync clear failed "
                                  "(will retry)", partition)
                    return
                self._set_current(partition, OFFLINE)
            elif info.get("pull_stalled_wal_gap"):
                log.warning(
                    "%s: follower stalled on a WAL gap (upstream "
                    "purged past our position) — forcing snapshot "
                    "rebuild via ERROR replan", partition)
                Stats.get().incr("participant.wal_gap_rebuilds")
                self._set_current(partition, ERROR)
        finally:
            with self._state_lock:
                self._inflight.pop(partition, None)
            # recover any assignment update delivered while claimed
            try:
                raw = self.coord.get_or_none(
                    self._path("assignments",
                               self.instance.instance_id))
                if raw is not None:
                    self._on_assignments({"value": raw})
            except Exception:
                log.exception("%s: post-heal re-evaluation failed",
                              partition)

    # ------------------------------------------------------------------

    def _on_assignments(self, snap: dict) -> None:
        if self._stopped:
            return
        targets = decode_assignments(bytes(snap.get("value") or b""))
        for partition, target in targets.items():
            # epochs flow to the state models through the context; noted
            # BEFORE any transition below reads them
            self.ctx.note_partition_epoch(partition, target.epoch)
        with self._state_lock:
            partitions = set(targets) | set(self._current)
            for partition in partitions:
                target = targets.get(partition)
                target_state = target.state if target else DROPPED
                cur = self._current.get(partition, OFFLINE)
                if self._inflight.get(partition):
                    continue
                if cur == ERROR and target is None:
                    continue  # nothing to recover toward
                if cur == target_state:
                    # State already right — but the upstream may have moved
                    # (leader handoff): repoint without a state transition
                    # (reference "repoint all others",
                    # LeaderFollowerStateModelFactory.java promote step).
                    if (
                        target is not None
                        and target.upstream
                        and self._applied_upstream.get(partition)
                        != target.upstream
                        and target_state in ("FOLLOWER", "SLAVE")
                    ):
                        self._inflight[partition] = True
                        self._executor.submit(
                            self._run_repoint, partition, target_state,
                            target.upstream, target.epoch,
                        )
                    elif (
                        target is not None
                        and target_state in ("LEADER", "MASTER",
                                             "FOLLOWER", "SLAVE")
                        and target.epoch
                        > self._applied_epoch.get(partition, 0)
                    ):
                        # state AND upstream already right but the epoch
                        # moved (sticky leader across a ledger re-mint, or
                        # a follower whose upstream survived a chained
                        # handoff): adopt in place — followers carrying
                        # the new epoch would otherwise fence this node
                        self._inflight[partition] = True
                        self._executor.submit(
                            self._run_adopt_epoch, partition, target.epoch
                        )
                    continue
                self._inflight[partition] = True
                self._executor.submit(
                    self._run_transition, partition, cur, target_state
                )

    def _run_transition(self, partition: str, from_state: str,
                        to_state: str) -> None:
        epoch = self.ctx.partition_epoch(partition)
        try:
            # the control-plane seam where a transition touches durable
            # state: a trip lands in the ERROR + paced-retry path below,
            # exactly like a real failed transition
            fp.hit("participant.transition")
            model = self.factory.get(partition)
            # ERROR recovers via OFFLINE (Helix resets ERROR->OFFLINE)
            plan_from = OFFLINE if from_state == ERROR else from_state
            try:
                steps = model.plan(plan_from, to_state)
            except TransitionError:
                steps = None
            if steps is None:
                log.error("%s: no path %s->%s", partition, from_state, to_state)
                self._set_current(partition, ERROR)
                time.sleep(self.error_retry_backoff)
                return
            state = plan_from
            for a, b in steps:
                log.info("%s: %s -> %s", partition, a, b)
                model.transition(a, b)
                state = b
                self._set_current(partition, state)
            with self._state_lock:
                # the epoch captured BEFORE the transition ran: a bump
                # landing mid-flight stays > applied, so the re-evaluation
                # below schedules the adoption
                if epoch > self._applied_epoch.get(partition, 0):
                    self._applied_epoch[partition] = epoch
        except Exception:
            log.exception("%s: transition %s->%s failed", partition,
                          from_state, to_state)
            self._set_current(partition, ERROR)
            # paced retry, not a hot loop: the finally-block re-evaluation
            # will plan again from OFFLINE after the backoff
            time.sleep(self.error_retry_backoff)
        finally:
            with self._state_lock:
                self._inflight.pop(partition, None)
            # re-evaluate: the target may have moved meanwhile. Guarded:
            # an exception escaping here dies silently in the executor
            # future and the missed update would never be re-applied.
            if not self._stopped:
                try:
                    raw = self.coord.get_or_none(
                        self._path("assignments", self.instance.instance_id)
                    )
                    if raw is not None:
                        self._on_assignments({"value": raw})
                except Exception:
                    log.exception(
                        "%s: post-transition re-evaluation failed", partition)

    def _run_repoint(self, partition: str, state: str, upstream: str,
                     epoch: int = 0) -> None:
        from ..utils.segment_utils import partition_name_to_db_name

        try:
            host, _, port = upstream.partition(":")
            db_name = partition_name_to_db_name(partition)
            log.info("%s: repointing upstream -> %s (epoch %d)",
                     partition, upstream, epoch)
            self.ctx.admin.change_db_role_and_upstream(
                self.ctx.local_admin_addr, db_name, state, (host, int(port)),
                epoch=epoch,
            )
            with self._state_lock:
                self._applied_upstream[partition] = upstream
                if epoch > self._applied_epoch.get(partition, 0):
                    self._applied_epoch[partition] = epoch
        except Exception:
            log.exception("%s: repoint failed", partition)
            # paced like _run_transition: the finally-block re-evaluation
            # below would otherwise resubmit a fast-failing repoint in a
            # tight submit/fail loop
            time.sleep(self.error_retry_backoff)
        finally:
            with self._state_lock:
                self._inflight.pop(partition, None)
            # Re-evaluate like _run_transition does: an assignment update
            # that arrived while this repoint was in flight was skipped by
            # _on_assignments (inflight guard) — without this re-check a
            # final controller write landing in that window would never be
            # applied (observed: soak failover followers stuck on a stale
            # upstream, replicas_converged=false). Guarded: an exception
            # escaping here dies silently in the executor future.
            if not self._stopped:
                try:
                    raw = self.coord.get_or_none(
                        self._path("assignments", self.instance.instance_id)
                    )
                    if raw is not None:
                        self._on_assignments({"value": raw})
                except Exception:
                    log.exception(
                        "%s: post-repoint re-evaluation failed", partition)

    def _run_adopt_epoch(self, partition: str, epoch: int) -> None:
        """In-place fencing-epoch adoption: state and upstream already
        match the assignment, only the epoch moved. No reopen — the
        ReplicatedDB just raises its epoch (monotonic)."""
        from ..utils.segment_utils import partition_name_to_db_name

        try:
            self.ctx.admin.set_db_epoch(
                self.ctx.local_admin_addr,
                partition_name_to_db_name(partition), epoch,
            )
            with self._state_lock:
                if epoch > self._applied_epoch.get(partition, 0):
                    self._applied_epoch[partition] = epoch
        except Exception:
            log.exception("%s: epoch adoption failed", partition)
            time.sleep(self.error_retry_backoff)
        finally:
            with self._state_lock:
                self._inflight.pop(partition, None)
            if not self._stopped:
                try:
                    raw = self.coord.get_or_none(
                        self._path("assignments", self.instance.instance_id)
                    )
                    if raw is not None:
                        self._on_assignments({"value": raw})
                except Exception:
                    log.exception(
                        "%s: post-adopt re-evaluation failed", partition)

    def _rejoin(self) -> None:
        """Called by the coordinator client after it re-established an
        expired session: the old session's ephemerals (our instance
        registration) were reaped — re-register, republish current
        state, and re-evaluate assignments so serving resumes without a
        restart (reference: ZK session re-establishment → Helix
        re-registers the live-instance znode)."""
        if self._stopped:
            return
        self._rejoin_pending = False
        try:
            self.coord.ensure(self._path("instances"))
            path = self._path("instances", self.instance.instance_id)
            try:
                self.coord.create(path, self.instance.encode(),
                                  ephemeral=True)
            except Exception:
                # a stale node from the dead session the reaper hasn't
                # collected yet — replace it under OUR session
                self.coord.delete_if_exists(path)
                self.coord.create(path, self.instance.encode(),
                                  ephemeral=True)
            with self._publish_lock:
                with self._state_lock:
                    snapshot = dict(self._current)
                self.coord.put(
                    self._path("currentstates", self.instance.instance_id),
                    encode_states(snapshot),
                )
            Stats.get().incr("participant.rejoins")
            log.warning(
                "%s: session expired — re-registered and resumed",
                self.instance.instance_id)
            raw = self.coord.get_or_none(
                self._path("assignments", self.instance.instance_id))
            if raw is not None:
                self._on_assignments({"value": raw})
        except Exception:
            # a transient failure here (e.g. the coordinator itself
            # failing over) must not strand the node unregistered
            # forever: the periodic seq loop retries
            self._rejoin_pending = True
            log.exception("%s: rejoin after session expiry failed "
                          "(will retry)", self.instance.instance_id)

    def _set_current(self, partition: str, state: str) -> None:
        # _publish_lock serializes snapshot+put as one unit so concurrent
        # transition threads cannot publish snapshots out of order (an older
        # snapshot overwriting a newer one would hide partitions from the
        # spectator until the next unrelated update).
        with self._publish_lock:
            with self._state_lock:
                if state == DROPPED:
                    self._current.pop(partition, None)
                else:
                    self._current[partition] = state
                snapshot = dict(self._current)
            self.coord.put(
                self._path("currentstates", self.instance.instance_id),
                encode_states(snapshot),
            )

    @property
    def current_states(self) -> Dict[str, str]:
        with self._state_lock:
            return dict(self._current)

    def make_leader_resolver(self):
        """db_name -> (host, repl_port) of the partition's current leader,
        from the coordinator's external view. Wire into the AdminHandler
        (set_leader_resolver) so a steady follower whose leader died can
        repoint itself from the pull loop's forced-reset path even if the
        controller's assignment write raced its inflight repoint —
        the data-plane half of the reference's GetLeaderInstanceId
        (replicated_db.cpp:278-312)."""
        from ..utils.segment_utils import db_name_to_partition_name

        def resolve(db_name: str) -> Optional[Tuple[str, int]]:
            try:
                partition = db_name_to_partition_name(db_name)
                view = self.ctx.external_view(partition)
                instances = self.ctx.live_instances()
                for iid, state in view.items():
                    if state not in ("LEADER", "MASTER"):
                        continue
                    if iid == self.instance.instance_id:
                        continue
                    info = instances.get(iid)
                    if info is not None:
                        return (info.host, info.repl_port)
            except Exception:
                log.exception("leader resolver failed for %s", db_name)
            return None

        return resolve

    def stop(self) -> None:
        """shutDownParticipant (Participant.java) — drop membership."""
        self._stopped = True
        self._watch_stop.set()
        self._executor.shutdown(wait=True)
        try:
            self.coord.delete_if_exists(
                self._path("instances", self.instance.instance_id)
            )
        except Exception:
            pass
        self.coord.close()
        self.admin.close()
