"""ConfigGenerator: external view → shard-map JSON.

Reference: ConfigGenerator.java:167-474 — on ExternalView/config change,
regenerate the shard map ``{resource: {num_shards, "ip:port:az": ["00001:M",
...]}}`` and hand it to a pluggable ShardMapPublisher. The map format is
exactly what the data-plane router parses (rpc/router.py), with the
replication port carried as the 4th host-key field.
"""

from __future__ import annotations

import json
import logging
from typing import Dict, List

from ..utils.segment_utils import partition_name_to_db_name, extract_shard_id, db_name_to_segment
from .model import (InstanceInfo, ResourceDef, SplitRecord, cluster_path,
                    decode_states)

log = logging.getLogger(__name__)

_LEADERLIKE = {"LEADER", "MASTER"}
_SERVING = _LEADERLIKE | {"FOLLOWER", "SLAVE", "ONLINE"}


def generate_shard_map(coord, cluster: str) -> Dict:
    """Build the shard map from the coordinator's current states."""
    path = lambda *p: cluster_path(cluster, *p)
    instances: Dict[str, InstanceInfo] = {}
    for iid in coord.list(path("instances")):
        raw = coord.get_or_none(path("instances", iid))
        if raw:
            instances[iid] = InstanceInfo.decode(raw)
    resources: Dict[str, ResourceDef] = {}
    for seg in coord.list(path("resources")):
        raw = coord.get_or_none(path("resources", seg))
        if raw:
            resources[seg] = ResourceDef.decode(raw)

    shard_map: Dict[str, Dict] = {
        seg: {"num_shards": r.num_shards} for seg, r in resources.items()
    }
    # ACTIVE range splits ride inside the segment body under the
    # reserved "__splits__" key: {parent_shard: {split_key, low, high}}.
    # num_shards stays the HASH width (clients keep hashing to the
    # parent slot); the router resolves slot → serving child by range.
    for p in coord.list(path("splits")):
        rec = SplitRecord.decode(coord.get_or_none(path("splits", p)))
        if rec is None or rec.phase != "active" or rec.segment not in shard_map:
            continue
        shard_map[rec.segment].setdefault("__splits__", {})[
            str(rec.parent_shard)] = {
                "split_key": rec.split_key,
                "low": rec.low_shard,
                "high": rec.high_shard,
        }
    for iid, info in instances.items():
        states = decode_states(coord.get_or_none(path("currentstates", iid)))
        host_key = f"{info.host}:{info.admin_port}:{info.az}:{info.repl_port}"
        for partition, state in sorted(states.items()):
            if state not in _SERVING:
                continue
            db_name = partition_name_to_db_name(partition)
            seg = db_name_to_segment(db_name)
            if seg not in shard_map:
                continue
            shard = extract_shard_id(db_name)
            marker = "M" if state in _LEADERLIKE else "S"
            shard_map[seg].setdefault(host_key, []).append(
                f"{shard:05d}:{marker}"
            )
    return shard_map
