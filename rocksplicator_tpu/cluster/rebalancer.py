"""Autonomous load-driven rebalancer: the round-14 hot-spot sensor
closed into an actuator loop.

Reference: the Helix rebalancer recomputes placement whenever the
cluster changes shape; Pinterest's fleet leans on it plus operator
runbooks for HOT shards — a human watches the dashboards, picks a
donor/target, runs the move tool. This module automates exactly that
runbook, with the same conservatism a careful operator applies:

- **sense** — scrape the published shard map's replicas for per-shard
  stat records and fold them into ONE hot-spot score per shard
  (:func:`composite_loads`): 1-minute read+write rate by default — the
  identical signal ``drain_node`` ranks targets by — optionally blended
  with ``replicator.applied_seq_lag`` and worst-replica compaction debt
  via ``RSTPU_REBALANCE_WEIGHTS="rate=1,lag=0.5,debt=0.2"`` (a shard
  whose followers can't keep up, or that is drowning in uncompacted
  levels, is hot even at peer-equal serving rates). Each scrape folds
  into a per-shard EWMA. One scrape is an anecdote; the EWMA plus a
  consecutive-scrapes requirement (``sustain``) is evidence.
- **decide** (failpoint ``rebalance.decide``) — a shard is HOT when its
  EWMA exceeds ``hot_factor`` x the fleet mean for ``sustain``
  consecutive scrapes, and stays hot until it drops below
  ``cool_factor`` x mean (hysteresis: the entry and exit thresholds
  differ, so a shard oscillating at the boundary never flaps). When one
  shard's own EWMA exceeds ``split_factor`` x mean, no placement can
  absorb it — moving it just moves the fire — so the decision is SPLIT
  (range-partitioned virtual children, cluster/shard_split.py).
- **plan** (``rebalance.plan``) — move the hot shard's LEADER replica to
  the least-loaded live instance not already hosting it, ranked exactly
  like ``drain_node`` (scraped served-load, shard-count tie-break).
  Moving the leader replica is deliberate: the ShardMove pin's
  ``preferred_leader`` routes the flip through the controller's own
  two-phase demote → epoch-mint → promote path, so the hot leader is
  gracefully PRE-DEMOTED rather than killed.
- **dispatch** (``rebalance.dispatch``) — at most ``max_concurrent``
  moves+splits in flight fleet-wide (in-flight ledger records count
  against the budget, so a second rebalancer — or a crashed one's
  leftovers — cannot stampede the cluster).

The loop is PAUSABLE and inspectable: a durable flag + status document
at ``/clusters/<cluster>/rebalancer`` (``admin_cli rebalance
status|pause|resume|once``). Every knob reads
``RSTPU_REBALANCE_*`` env first so chaos/bench harnesses shrink the
cadence without code changes.

:class:`RebalancerPolicy` is pure (scrape in, decisions out, no I/O) —
the macro-bench's ``--hot_shift`` arm drives the same policy against a
static cluster with :class:`~.shard_move.DirectShardMove` as the
actuator, so the A/B artifact exercises the decision logic the
production loop runs.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..testing import failpoints as fp
from ..utils.segment_utils import (
    db_name_to_partition_name,
    db_name_to_segment,
    extract_shard_id,
    partition_name_to_db_name,
)
from ..utils.stats import Stats
from .coordinator import CoordinatorClient
from .helix_utils import AdminClient
from .model import InstanceInfo, cluster_path, decode_states
from .shard_move import (MoveError, MoveFlags, ShardMove,
                         _scraped_shard_load, _scraped_shard_stats,
                         list_active_moves)
from .shard_split import (ShardSplit, SplitError, choose_split_key,
                          list_splits)

log = logging.getLogger(__name__)

_LEADERLIKE = {"LEADER", "MASTER"}
_SERVING = _LEADERLIKE | {"FOLLOWER", "SLAVE"}


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _parse_weights(raw: str) -> Dict[str, float]:
    """``RSTPU_REBALANCE_WEIGHTS="rate=1,lag=0.5,debt=0.2"`` → the
    composite-score weights. Unknown keys and garbage values are
    ignored; the default is rate-only (the pre-weights behavior)."""
    out = {"rate": 1.0, "lag": 0.0, "debt": 0.0}
    for part in (raw or "").split(","):
        key, sep, val = part.partition("=")
        key = key.strip()
        if sep and key in out:
            try:
                out[key] = float(val)
            except ValueError:
                pass
    return out


def composite_loads(per_shard: Dict[str, dict],
                    weights: Dict[str, float]) -> Dict[str, float]:
    """Fold the aggregated per-shard stats records into ONE hot-spot
    score per shard: ``rate`` weights the 1-minute read+write ops/s,
    ``lag`` weights ``max_applied_seq_lag`` (one lagging seq ≈ one
    pending op, so the units line up naturally — a shard whose
    followers can't keep up is hot even when its serving rate matches
    its peers), ``debt`` weights worst-replica compaction debt per MiB
    (a shard drowning in uncompacted levels amplifies every read).
    With the default weights the score IS the rate — bit-identical to
    the pre-weights sensor."""
    w_rate = weights.get("rate", 1.0)
    w_lag = weights.get("lag", 0.0)
    w_debt = weights.get("debt", 0.0)
    out: Dict[str, float] = {}
    for db, rec in per_shard.items():
        score = w_rate * (float(rec.get("read_rate_1m", 0.0))
                          + float(rec.get("write_rate_1m", 0.0)))
        score += w_lag * float(rec.get("max_applied_seq_lag", 0.0))
        score += w_debt * (
            float(rec.get("compaction_debt_bytes", 0.0)) / (1 << 20))
        out[db] = score
    return out


@dataclass
class RebalancerFlags:
    """Policy + loop knobs (env-overridable, RSTPU_REBALANCE_*)."""

    interval: float = 15.0        # seconds between scrapes
    ewma_alpha: float = 0.3       # EWMA weight of the newest scrape
    hot_factor: float = 2.0       # enter-hot threshold, x fleet mean
    cool_factor: float = 1.3      # exit-hot threshold (hysteresis band)
    sustain: int = 3              # consecutive hot scrapes before acting
    max_concurrent: int = 1       # moves+splits in flight, fleet-wide
    split_factor: float = 4.0     # split instead of move above this
    min_rate: float = 1.0         # ops/s floor below which nothing is hot
    # composite-score weights (RSTPU_REBALANCE_WEIGHTS): rate-only by
    # default; lag/debt fold replication and compaction health into the
    # same hot-spot ranking
    weights: Dict[str, float] = field(
        default_factory=lambda: {"rate": 1.0, "lag": 0.0, "debt": 0.0})

    @classmethod
    def from_env(cls) -> "RebalancerFlags":
        return cls(
            interval=_env_float("RSTPU_REBALANCE_INTERVAL", 15.0),
            ewma_alpha=_env_float("RSTPU_REBALANCE_EWMA_ALPHA", 0.3),
            hot_factor=_env_float("RSTPU_REBALANCE_HOT_FACTOR", 2.0),
            cool_factor=_env_float("RSTPU_REBALANCE_COOL_FACTOR", 1.3),
            sustain=int(_env_float("RSTPU_REBALANCE_SUSTAIN", 3)),
            max_concurrent=int(
                _env_float("RSTPU_REBALANCE_MAX_CONCURRENT", 1)),
            split_factor=_env_float("RSTPU_REBALANCE_SPLIT_FACTOR", 4.0),
            min_rate=_env_float("RSTPU_REBALANCE_MIN_RATE", 1.0),
            weights=_parse_weights(
                os.environ.get("RSTPU_REBALANCE_WEIGHTS", "")),
        )


@dataclass
class Decision:
    """One shard the policy wants acted on this tick."""

    kind: str       # "move" | "split"
    db_name: str
    ewma: float
    fleet_mean: float


@dataclass
class _ShardState:
    ewma: float = 0.0
    hot_streak: int = 0
    latched_hot: bool = False


class RebalancerPolicy:
    """Pure hot-spot detector: feed it one scrape per tick
    (``observe``), it returns the shards that have EARNED action.

    Sustained-ness is the whole point: a one-scrape blip (a retry
    storm, a scan burst, a scrape racing a compaction) bumps the EWMA
    but cannot clear ``sustain`` consecutive above-threshold ticks; and
    once latched hot, a shard stays actionable until it cools below the
    LOWER band, so the policy never oscillates plan/cancel across the
    boundary."""

    def __init__(self, flags: Optional[RebalancerFlags] = None):
        self.flags = flags or RebalancerFlags()
        self._shards: Dict[str, _ShardState] = {}

    def observe(self, loads: Dict[str, float]) -> List[Decision]:
        fp.hit("rebalance.decide")
        f = self.flags
        if not loads:
            return []
        # fold the scrape into per-shard EWMAs (new shards seed at the
        # observed rate — a freshly split child starts from truth, not
        # from zero)
        for db, rate in loads.items():
            st = self._shards.get(db)
            if st is None:
                self._shards[db] = _ShardState(ewma=float(rate))
            else:
                st.ewma += f.ewma_alpha * (float(rate) - st.ewma)
        for db in list(self._shards):
            if db not in loads:
                # no longer in the map (moved away mid-split, retired):
                # forget it rather than letting a stale EWMA decide
                del self._shards[db]
        mean = sum(s.ewma for s in self._shards.values()) / len(self._shards)
        out: List[Decision] = []
        for db, st in sorted(self._shards.items()):
            enter = max(f.min_rate, f.hot_factor * mean)
            exit_ = max(f.min_rate, f.cool_factor * mean)
            if st.latched_hot:
                if st.ewma < exit_:
                    st.latched_hot = False
                    st.hot_streak = 0
                    continue
            elif st.ewma > enter:
                st.hot_streak += 1
                if st.hot_streak < f.sustain:
                    continue
                st.latched_hot = True
            else:
                st.hot_streak = 0
                continue
            kind = "split" if st.ewma > max(
                f.min_rate, f.split_factor * mean) else "move"
            out.append(Decision(kind=kind, db_name=db, ewma=st.ewma,
                                fleet_mean=mean))
        return out

    def forget(self, db_name: str) -> None:
        """Drop a shard's latch after acting on it — the action changed
        the world; let the next scrapes re-earn any further action."""
        self._shards.pop(db_name, None)

    def snapshot(self) -> Dict[str, dict]:
        return {db: {"ewma": round(st.ewma, 2),
                     "hot_streak": st.hot_streak,
                     "hot": st.latched_hot}
                for db, st in sorted(self._shards.items())}


class Rebalancer:
    """The coordinator-mode driver: sense → decide → plan → dispatch,
    every ``interval`` seconds, under the durable pause flag."""

    def __init__(self, coord: CoordinatorClient, cluster: str,
                 store_uri: str,
                 flags: Optional[RebalancerFlags] = None,
                 move_flags: Optional[MoveFlags] = None,
                 admin: Optional[AdminClient] = None,
                 load_fn: Optional[Callable[[], Optional[Dict[str, float]]]]
                 = None):
        self.coord = coord
        self.cluster = cluster
        self.store_uri = store_uri
        self.flags = flags or RebalancerFlags.from_env()
        self.move_flags = move_flags or MoveFlags()
        self.admin = admin or AdminClient()
        self._owns_admin = admin is None
        self._load_fn = load_fn or self._composite_scrape
        self.policy = RebalancerPolicy(self.flags)
        self._path = lambda *p: cluster_path(cluster, *p)
        self._stats = Stats.get()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._workers: List[threading.Thread] = []
        self._dispatched = {"moves": 0, "splits": 0, "failed": 0}
        self._last_decisions: List[dict] = []

    def _composite_scrape(self) -> Optional[Dict[str, float]]:
        """Default sensor: the aggregated per-shard stat records folded
        through the ``RSTPU_REBALANCE_WEIGHTS`` composite score. With
        default weights this is exactly ``_scraped_shard_load`` (serving
        rate only); lag/debt weights let a replication-lagging or
        compaction-indebted shard outrank a rate-equal peer."""
        per = _scraped_shard_stats(self.coord, self.cluster)
        if per is None:
            return None
        return composite_loads(per, self.flags.weights)

    # -- pause flag + status ---------------------------------------------

    def _status_doc(self) -> dict:
        raw = self.coord.get_or_none(self._path("rebalancer"))
        if raw:
            try:
                return json.loads(bytes(raw).decode())
            except (ValueError, UnicodeDecodeError):
                pass
        return {}

    @property
    def paused(self) -> bool:
        return bool(self._status_doc().get("paused"))

    def publish_status(self) -> None:
        doc = self._status_doc()
        doc.update({
            "paused": bool(doc.get("paused")),
            "updated_ms": int(time.time() * 1000),
            "dispatched": dict(self._dispatched),
            "last_decisions": self._last_decisions[-8:],
            "shards": self.policy.snapshot(),
        })
        self.coord.put(self._path("rebalancer"),
                       json.dumps(doc).encode())

    @staticmethod
    def set_paused(coord: CoordinatorClient, cluster: str,
                   paused: bool) -> None:
        """Durable operator pause/resume (CLI); merges into the status
        doc so pausing never erases the loop's last published state."""
        path = cluster_path(cluster, "rebalancer")
        raw = coord.get_or_none(path)
        doc = {}
        if raw:
            try:
                doc = json.loads(bytes(raw).decode())
            except (ValueError, UnicodeDecodeError):
                doc = {}
        doc["paused"] = bool(paused)
        doc["updated_ms"] = int(time.time() * 1000)
        coord.put(path, json.dumps(doc).encode())

    # -- one tick ---------------------------------------------------------

    def _in_flight(self) -> int:
        live = len(list_active_moves(self.coord, self.cluster))
        live += sum(1 for r in list_splits(self.coord, self.cluster)
                    if r.phase != "active")
        return live

    def _cluster_view(self):
        states_of: Dict[str, Dict[str, str]] = {}
        for iid in self.coord.list(self._path("currentstates")):
            states_of[iid] = decode_states(
                self.coord.get_or_none(self._path("currentstates", iid)))
        instances: Dict[str, InstanceInfo] = {}
        for iid in self.coord.list(self._path("instances")):
            raw = self.coord.get_or_none(self._path("instances", iid))
            if raw:
                instances[iid] = InstanceInfo.decode(raw)
        return states_of, instances

    def _plan_move(self, d: Decision, states_of, instances,
                   db_load: Dict[str, float]) -> Optional[dict]:
        partition = db_name_to_partition_name(d.db_name)
        hosting = {iid for iid, st in states_of.items()
                   if st.get(partition) in _SERVING}
        leader = next((iid for iid, st in states_of.items()
                       if st.get(partition) in _LEADERLIKE), None)
        if leader is None:
            return None
        candidates = [iid for iid in instances
                      if iid not in hosting]
        if not candidates:
            return None
        counts = {iid: sum(1 for st in states_of.get(iid, {}).values()
                           if st in _SERVING) for iid in candidates}
        # drain_node's least-loaded ranking, verbatim semantics: scraped
        # served-rate first, shard count as the noise-absorbing tie-break
        served = {iid: round(sum(
            db_load.get(partition_name_to_db_name(p), 0.0)
            for p, st in states_of.get(iid, {}).items()
            if st in _SERVING), 1) for iid in candidates}
        target = min(candidates,
                     key=lambda iid: (served[iid], counts[iid], iid))
        return {"kind": "move", "partition": partition,
                "source": leader, "target": target}

    def _plan_split(self, d: Decision, states_of, instances
                    ) -> Optional[dict]:
        partition = db_name_to_partition_name(d.db_name)
        hosting = {iid for iid, st in states_of.items()
                   if st.get(partition) in _SERVING}
        leader = next((iid for iid, st in states_of.items()
                       if st.get(partition) in _LEADERLIKE), None)
        if leader is None or leader not in instances:
            return None
        candidates = [iid for iid in instances if iid not in hosting]
        if not candidates:
            return None
        counts = {iid: sum(1 for st in states_of.get(iid, {}).values()
                           if st in _SERVING) for iid in candidates}
        target = min(candidates, key=lambda iid: (counts[iid], iid))
        info = instances[leader]
        key = choose_split_key(self.admin, (info.host, info.repl_port),
                               d.db_name)
        if key is None:
            log.warning("%s: split wanted but no usable split key "
                        "(shard too small?) — falling back to a move",
                        d.db_name)
            return None
        return {"kind": "split", "partition": partition,
                "segment": db_name_to_segment(d.db_name),
                "parent_shard": extract_shard_id(d.db_name),
                "split_key": key, "target": target}

    def _dispatch(self, plan: dict) -> None:
        fp.hit("rebalance.dispatch")
        kind = plan["kind"]

        def work():
            try:
                if kind == "move":
                    mv = ShardMove.start(
                        self.coord, self.cluster, plan["partition"],
                        plan["source"], plan["target"], self.store_uri,
                        flags=self.move_flags)
                    mv.run()
                else:
                    sp = ShardSplit.start(
                        self.coord, self.cluster, plan["segment"],
                        plan["parent_shard"], plan["split_key"],
                        plan["target"], self.store_uri,
                        flags=self.move_flags)
                    sp.run()
                self._stats.incr(f"rebalancer.{kind}s_completed")
            except (MoveError, SplitError, Exception):
                self._dispatched["failed"] += 1
                self._stats.incr(f"rebalancer.{kind}s_failed")
                log.warning("rebalancer: %s of %s failed", kind,
                            plan["partition"], exc_info=True)

        t = threading.Thread(target=work, daemon=True,
                             name=f"rebalance-{kind}-{plan['partition']}")
        t.start()
        self._workers.append(t)
        self._dispatched[f"{kind}s"] += 1
        self._stats.incr(f"rebalancer.{kind}s_dispatched")

    def once(self) -> List[dict]:
        """One full sense→decide→plan→dispatch tick; returns the plans
        dispatched (CLI ``rebalance once`` and the loop body)."""
        self._workers = [t for t in self._workers if t.is_alive()]
        loads = self._load_fn()
        if loads is None:
            log.info("rebalancer: no scrape this tick (no published "
                     "map or no replica answered)")
            self.publish_status()
            return []
        decisions = self.policy.observe(loads)
        self._last_decisions = [
            {"kind": d.kind, "db": d.db_name, "ewma": round(d.ewma, 2),
             "mean": round(d.fleet_mean, 2),
             "at_ms": int(time.time() * 1000)}
            for d in decisions] or self._last_decisions
        dispatched: List[dict] = []
        if decisions:
            fp.hit("rebalance.plan")
            states_of, instances = self._cluster_view()
            budget = max(0, self.flags.max_concurrent
                         - self._in_flight()
                         - len([t for t in self._workers
                                if t.is_alive()]))
            for d in decisions:
                if budget <= 0:
                    break
                if d.kind == "split":
                    plan = self._plan_split(d, states_of, instances) \
                        or self._plan_move(d, states_of, instances,
                                           loads)
                else:
                    plan = self._plan_move(d, states_of, instances,
                                           loads)
                if plan is None:
                    continue
                try:
                    self._dispatch(plan)
                except Exception:
                    log.warning("rebalancer: dispatch failed",
                                exc_info=True)
                    continue
                self.policy.forget(d.db_name)
                dispatched.append(plan)
                budget -= 1
        self.publish_status()
        return dispatched

    # -- the loop ---------------------------------------------------------

    def run_forever(self) -> None:
        while not self._stop.is_set():
            try:
                if self.paused:
                    self._stats.incr("rebalancer.ticks_paused")
                else:
                    self.once()
                    self._stats.incr("rebalancer.ticks")
            except Exception:
                log.warning("rebalancer tick failed", exc_info=True)
            self._stop.wait(self.flags.interval)

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run_forever,
                                        daemon=True, name="rebalancer")
        self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
        for t in self._workers:
            t.join(timeout)
        if self._owns_admin:
            self.admin.close()
            self._owns_admin = False
