"""Leader-handoff event history.

Reference: cluster_management eventstore/ — typed events (init/success/
failure of each transition phase) merged into ZK nodes
(ZkMergeableEventStore) and analyzed by EventHistoryAnalysisTool. Here:
per-partition JSON event lists in the coordinator with CAS-merge appends
and a capped length.
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional

from ..rpc.errors import RpcApplicationError
from .model import cluster_path

MAX_EVENTS = 64


def _events_path(cluster: str, partition: str) -> str:
    return cluster_path(cluster, "events", partition)


def append_event(
    coord,
    cluster: str,
    partition: str,
    event_type: str,
    originator: str,
    detail: str = "",
    max_retries: int = 5,
) -> None:
    """CAS-merge append (ZkMergeableEventStore semantics)."""
    path = _events_path(cluster, partition)
    event = {
        "ts_ms": int(time.time() * 1000),
        "type": event_type,
        "originator": originator,
        "detail": detail,
    }
    for _ in range(max_retries):
        try:
            raw, version = coord.get(path)
            events = json.loads(bytes(raw).decode()) if raw else []
        except RpcApplicationError as e:
            if e.code != "NO_NODE":
                raise
            try:
                coord.create(path, json.dumps([event]).encode())
                return
            except RpcApplicationError as e2:
                if e2.code != "NODE_EXISTS":
                    raise
                continue  # lost the create race; retry the merge path
        events.append(event)
        events = events[-MAX_EVENTS:]
        try:
            coord.set(path, json.dumps(events).encode(), expected_version=version)
            return
        except RpcApplicationError as e:
            if e.code != "BAD_VERSION":
                raise
            # merged by someone else concurrently; retry


def read_events(coord, cluster: str, partition: str) -> List[Dict]:
    raw = coord.get_or_none(_events_path(cluster, partition))
    return json.loads(bytes(raw).decode()) if raw else []


def analyze_leader_history(coord, cluster: str, partition: str) -> Dict:
    """EventHistoryAnalysisTool essentials: handoff counts + last leader."""
    events = read_events(coord, cluster, partition)
    promotions = [e for e in events if e["type"] == "follower_to_leader_success"]
    failures = [e for e in events if e["type"].endswith("_failure")]
    return {
        "num_events": len(events),
        "num_promotions": len(promotions),
        "num_failures": len(failures),
        "last_leader": promotions[-1]["originator"] if promotions else None,
        "events": events,
    }
