"""ApplicationDBManager: the name → ApplicationDB registry.

Reference: rocksdb_admin/application_db_manager.{h,cpp} — shared_mutex map;
removal spin-waits use_count()==1 (here: explicit close after removal from
the map — new lookups can't find it, in-flight ops finish on their
reference); DB-size stats text dump (application_db_manager.cpp:140-150).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..utils.stats import Stats
from .application_db import ApplicationDB


class ApplicationDBManager:
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._dbs: Dict[str, ApplicationDB] = {}

    def add_db(self, name: str, app_db: ApplicationDB) -> bool:
        with self._lock:
            if name in self._dbs:
                return False
            self._dbs[name] = app_db
            return True

    def get_db(self, name: str) -> Optional[ApplicationDB]:
        with self._lock:
            return self._dbs.get(name)

    def remove_db(self, name: str, close: bool = True) -> Optional[ApplicationDB]:
        with self._lock:
            app_db = self._dbs.pop(name, None)
        if app_db is not None and close:
            app_db.close()
        return app_db

    def get_all_db_names(self) -> List[str]:
        with self._lock:
            return sorted(self._dbs.keys())

    def __len__(self) -> int:
        with self._lock:
            return len(self._dbs)

    def dump_db_stats_as_text(self) -> str:
        """reference DumpDBStatsAsText + per-db size gauges
        (application_db_manager.cpp:120-150)."""
        lines = []
        with self._lock:
            dbs = list(self._dbs.items())
        for name, app_db in sorted(dbs):
            try:
                size = app_db.db.approximate_disk_size()
                seq = app_db.latest_sequence_number()
                lines.append(
                    f"db={name} role={app_db.role.value} seq={seq} "
                    f"sst_bytes={size}"
                )
            except Exception as e:  # closed mid-dump
                lines.append(f"db={name} error={e!r}")
        return "\n".join(lines) + "\n"
