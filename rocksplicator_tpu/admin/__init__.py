"""Admin / data plane (reference: rocksdb_admin/, cdc_admin/ — SURVEY §2.2)."""

from .application_db import ApplicationDB
from .db_manager import ApplicationDBManager
from .handler import AdminHandler, DBMetaData
from .cdc import CdcAdminHandler

__all__ = [
    "ApplicationDB", "ApplicationDBManager", "AdminHandler", "DBMetaData",
    "CdcAdminHandler",
]
