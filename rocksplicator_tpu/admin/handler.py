"""AdminHandler: the admin data-plane service.

Reference: rocksdb_admin/rocksdb_admin.thrift:259-363 (15 RPCs) +
rocksdb_admin/admin_handler.{h,cpp} (2.2k LoC). Implements:

ping, addDB, backupDB, restoreDB, backupDBToS3, restoreDBFromS3, checkDB,
closeDB, changeDBRoleAndUpStream, getSequenceNumber, clearDB,
addS3SstFilesToDB, startMessageIngestion, stopMessageIngestion,
setDBOptions, compactDB.

Structure parity: a private meta_db at ``<rocksdb_dir>/meta_db`` storing
per-db DBMetaData (admin_handler.cpp:204-212, 556-595); per-db ObjectLock
serializing admin ops; an object-store cache; an ingest concurrency gate
(``num_current_s3_sst_downloadings_``); message-ingestion watcher map.
"S3" RPC names are kept for wire parity — the bucket argument is any
object-store URI (local dir or s3://).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import shutil
import tempfile
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import asdict, dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..observability.context import wire_context
from ..observability.span import start_span
from ..replication.replicated_db import LeaderResolver
from ..replication.replicator import Replicator
from ..replication.wire import ReplicaRole
from ..rpc.errors import RpcApplicationError
from ..storage import backup as backup_mod
from ..storage.engine import DB, DBOptions, destroy_db
from ..storage.errors import StorageError
from ..testing import failpoints as fp
from ..utils.flags import FLAGS, define_flag
from ..utils.object_lock import ObjectLock
from ..utils.objectstore import build_object_store
from ..utils.segment_utils import db_name_to_segment
from ..utils.stats import Stats
from ..utils.timer import Timer
from .application_db import ApplicationDB
from .db_manager import ApplicationDBManager
from .ingest_pipeline import (BatchCompactor, IngestGate,
                              default_sst_loading_concurrency)

log = logging.getLogger(__name__)

# Reference gflag parity: direct-IO SST downloads keep a restore/ingest
# storm from evicting the serving working set (s3util.h:82-103)
define_flag("s3_direct_io", False,
            "download ingest SSTs through O_DIRECT sinks (page-cache "
            "bypass)")

# AdminErrorCode parity (rocksdb_admin.thrift)
DB_NOT_FOUND = "DB_NOT_FOUND"
DB_ALREADY_EXISTS = "DB_ALREADY_EXISTS"
INVALID_DB_ROLE = "INVALID_DB_ROLE"
INVALID_UPSTREAM = "INVALID_UPSTREAM"
DB_ADMIN_ERROR = "DB_ADMIN_ERROR"
DB_ERROR = "DB_ERROR"
TOO_MANY_REQUESTS = "TOO_MANY_REQUESTS"
NOT_IMPLEMENTED = "NOT_IMPLEMENTED"

_ROLE_ALIASES = {
    "LEADER": ReplicaRole.LEADER, "MASTER": ReplicaRole.LEADER,
    "FOLLOWER": ReplicaRole.FOLLOWER, "SLAVE": ReplicaRole.FOLLOWER,
    "NOOP": ReplicaRole.NOOP, "OBSERVER": ReplicaRole.OBSERVER,
}

OptionsGenerator = Callable[[str], DBOptions]

# sentinel marking an in-flight startMessageIngestion reservation
_RESERVED = object()


@dataclass
class DBMetaData:
    """rocksdb_admin.thrift DBMetaData (+ the split-trim retain range:
    hex key bounds a range-split child keeps across reopens so its
    compactions keep dropping the other half's keys)."""

    db_name: str
    s3_bucket: str = ""
    s3_path: str = ""
    last_kafka_msg_timestamp_ms: int = 0
    retain_lo: str = ""
    retain_hi: str = ""

    def encode(self) -> bytes:
        return json.dumps(asdict(self)).encode("utf-8")

    @classmethod
    def decode(cls, db_name: str, raw: Optional[bytes]) -> "DBMetaData":
        if not raw:
            return cls(db_name=db_name)
        d = json.loads(bytes(raw).decode("utf-8"))
        d.setdefault("db_name", db_name)
        return cls(**d)


def _current_mode(app_db: ApplicationDB) -> Optional[int]:
    """The db's live ack mode, for preserving across reopen/role change."""
    if app_db.replicated_db is not None:
        return app_db.replicated_db.replication_mode
    return None


def _current_epoch(app_db: ApplicationDB) -> int:
    """The db's live fencing epoch, preserved (max-merged) across
    reopen/role change so a legacy caller passing no epoch can never
    regress a shard below an epoch it already served under."""
    if app_db.replicated_db is not None:
        return app_db.replicated_db.epoch
    return 0


def _parse_role(role: str) -> ReplicaRole:
    r = _ROLE_ALIASES.get(role.upper())
    if r is None:
        raise RpcApplicationError(INVALID_DB_ROLE, role)
    return r


class AdminHandler:
    def __init__(
        self,
        rocksdb_dir: str,
        replicator: Replicator,
        db_manager: Optional[ApplicationDBManager] = None,
        options_generator: Optional[OptionsGenerator] = None,
        leader_resolver: Optional[LeaderResolver] = None,
        executor_threads: int = 8,
        max_sst_loading_concurrency: Optional[int] = None,
        object_store_rate_limit_bytes: Optional[float] = None,
        tpu_compaction: bool = False,
        compact_parallelism: Optional[int] = None,
    ):
        self.rocksdb_dir = os.path.abspath(rocksdb_dir)
        os.makedirs(self.rocksdb_dir, exist_ok=True)
        # sweep staging dirs orphaned by a crash mid-backup/restore:
        # they live on the data volume (same-fs for hardlinks/rename)
        # and are only meaningful to the in-flight op that created them
        for entry in os.listdir(self.rocksdb_dir):
            if entry.startswith((".restore-", ".backup-")):
                shutil.rmtree(os.path.join(self.rocksdb_dir, entry),
                              ignore_errors=True)
        self.replicator = replicator
        self.db_manager = db_manager or ApplicationDBManager()
        self._options_gen = options_generator or (lambda segment: DBOptions())
        self._leader_resolver = leader_resolver
        self._executor = ThreadPoolExecutor(
            max_workers=executor_threads, thread_name_prefix="admin"
        )
        self._db_admin_lock = ObjectLock()
        self._store_rate_limit = object_store_rate_limit_bytes
        # ingest admission gate: the 999 default made TOO_MANY_REQUESTS
        # dead code — None now derives a sane bound from the host
        self._ingest_gate = IngestGate(
            max_sst_loading_concurrency
            if max_sst_loading_concurrency is not None
            else default_sst_loading_concurrency()
        )
        self._tpu_compaction = tpu_compaction
        self._batch_compactor = BatchCompactor(
            use_tpu=tpu_compaction, compact_parallelism=compact_parallelism)
        self._meta_db = DB(os.path.join(self.rocksdb_dir, "meta_db"))
        # db_name -> message-ingestion watcher (kafka-equivalent stack)
        self._ingestion: Dict[str, object] = {}
        self._stats = Stats.get()

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------

    async def _run(self, fn: Callable, *args):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(self._executor, fn, *args)

    def _db_path(self, db_name: str) -> str:
        return os.path.join(self.rocksdb_dir, db_name)

    def _options_for(self, db_name: str) -> DBOptions:
        try:
            segment = db_name_to_segment(db_name)
        except ValueError:
            segment = db_name
        options = self._options_gen(segment)
        if self._tpu_compaction:
            # North star: the TPU compaction service registers behind the
            # engine's CompactionBackend seam for every db this admin hosts.
            from ..tpu.compaction_service import TpuCompactionService

            TpuCompactionService.install_on_options(options)
        return options

    def _get_app_db(self, db_name: str) -> ApplicationDB:
        app_db = self.db_manager.get_db(db_name)
        if app_db is None:
            raise RpcApplicationError(DB_NOT_FOUND, db_name)
        return app_db

    def set_leader_resolver(self, resolver: Optional[LeaderResolver]) -> None:
        """Install (or replace) the data-plane leader resolver. Takes
        effect for every hosted DB, including those already open — the
        per-DB resolver closure reads this attribute at resolve time."""
        self._leader_resolver = resolver

    def get_meta_data(self, db_name: str) -> DBMetaData:
        """admin_handler.cpp:556-576."""
        raw = self._meta_db.get(db_name.encode("utf-8"))
        return DBMetaData.decode(db_name, raw)

    def write_meta_data(
        self, db_name: str, s3_bucket: str = "", s3_path: str = "",
        last_kafka_msg_timestamp_ms: Optional[int] = None,
        retain_lo: Optional[str] = None, retain_hi: Optional[str] = None,
    ) -> None:
        """admin_handler.cpp:578-595. ``retain_lo``/``retain_hi``: None
        keeps the stored bounds (the common metadata update must never
        erase a split child's trim range)."""
        meta = self.get_meta_data(db_name)
        meta.s3_bucket = s3_bucket
        meta.s3_path = s3_path
        if last_kafka_msg_timestamp_ms is not None:
            meta.last_kafka_msg_timestamp_ms = last_kafka_msg_timestamp_ms
        if retain_lo is not None:
            meta.retain_lo = retain_lo
        if retain_hi is not None:
            meta.retain_hi = retain_hi
        self._meta_db.put(db_name.encode("utf-8"), meta.encode())

    def clear_meta_data(self, db_name: str) -> None:
        self._meta_db.delete(db_name.encode("utf-8"))

    def _store(self, uri: str):
        return build_object_store(uri, self._store_rate_limit)

    def _open_app_db(
        self,
        db_name: str,
        role: ReplicaRole,
        upstream: Optional[Tuple[str, int]],
        overwrite: bool = False,
        replication_mode: Optional[int] = None,
        epoch: int = 0,
    ) -> ApplicationDB:
        path = self._db_path(db_name)
        if overwrite:
            destroy_db(path)
        options = self._options_for(db_name)
        # a split child's retain range is durable identity (DBMetaData),
        # not dbconfig: reapply it on every reopen so scheduled
        # compactions keep trimming the inherited other-half keys
        meta = self.get_meta_data(db_name)
        if meta.retain_lo or meta.retain_hi:
            options.retain_lo = meta.retain_lo or None
            options.retain_hi = meta.retain_hi or None
        db = DB(path, options)
        app_db = ApplicationDB(
            db_name, db, role,
            replicator=self.replicator,
            upstream_addr=upstream,
            replication_mode=replication_mode,
            epoch=epoch,
            # late-bound: set_leader_resolver (called once the participant
            # exists — it is constructed after the handler) must reach DBs
            # that are already open, so the wrapper defers the lookup
            leader_resolver=lambda name: (
                self._leader_resolver(name) if self._leader_resolver
                else None
            ),
        )
        if not self.db_manager.add_db(db_name, app_db):
            app_db.close()
            raise RpcApplicationError(DB_ALREADY_EXISTS, db_name)
        return app_db

    # ------------------------------------------------------------------
    # RPC: liveness / introspection
    # ------------------------------------------------------------------

    async def handle_ping(self) -> dict:
        return {"ok": True, "timestamp_ms": int(time.time() * 1000)}

    async def handle_get_sequence_number(self, db_name: str = "") -> dict:
        app_db = self._get_app_db(db_name)
        return {"seq_num": app_db.latest_sequence_number()}

    async def handle_check_db(self, db_name: str = "") -> dict:
        """checkDB: seq + WAL/update recency info for rebuild decisions
        (needRebuildDB, LeaderFollowerStateModelFactory.java:469-479)."""
        app_db = self._get_app_db(db_name)

        def collect():
            seq = app_db.latest_sequence_number()
            last_ts = None
            # newest update timestamp from the WAL tail
            for _seq, raw in app_db.db.get_updates_since(max(1, seq)):
                from ..storage.records import decode_batch

                last_ts = decode_batch(raw).extract_timestamp_ms()
            wal_dir = os.path.join(app_db.db.path, "wal")
            oldest_wal_ts = None
            try:
                segs = sorted(os.listdir(wal_dir))
                if segs:
                    oldest_wal_ts = int(
                        os.path.getmtime(os.path.join(wal_dir, segs[0])) * 1000
                    )
            except OSError:
                pass
            rdb = app_db.replicated_db
            return {
                "seq_num": seq,
                "last_update_timestamp_ms": last_ts,
                "oldest_wal_timestamp_ms": oldest_wal_ts,
                # needRebuildDB's WAL-availability input: a rebuilding
                # peer below this seq cannot WAL-catch-up from us
                "oldest_wal_seq": app_db.db.oldest_wal_seq(),
                "db_size_bytes": app_db.db.approximate_disk_size(),
                "role": app_db.role.value,
                # live shard moves read these: the direct (coordinator-
                # less) mover mints its cutover epoch from the shard's
                # live one, and verifies the pause it armed
                "epoch": rdb.epoch if rdb is not None else 0,
                "write_paused": (rdb.write_paused
                                 if rdb is not None else False),
                # a puller whose position predates its upstream's WAL:
                # the participant loop converts this into a snapshot
                # rebuild (pulling can never catch it up)
                "pull_stalled_wal_gap": bool(
                    rdb is not None
                    and getattr(rdb, "pull_stalled_wal_gap", False)),
                # a follower persistently AHEAD of its leader's commit
                # point: divergent suffix — the participant loop clears
                # + rejoins it (the follower analog of deposed resync)
                "pull_diverged": bool(
                    rdb is not None
                    and getattr(rdb, "pull_diverged", False)),
            }

        return await self._run(collect)

    # ------------------------------------------------------------------
    # RPC: lifecycle
    # ------------------------------------------------------------------

    async def handle_add_db(
        self,
        db_name: str = "",
        upstream_ip: str = "",
        upstream_port: int = 0,
        role: str = "FOLLOWER",
        overwrite: bool = False,
        replication_mode: Optional[int] = None,
        epoch: int = 0,
    ) -> dict:
        """addDB (admin_handler.cpp:597-694): open the db and register it
        with the replicator in the given role."""
        parsed = _parse_role(role)
        upstream = (upstream_ip, upstream_port) if upstream_ip else None
        if parsed in (ReplicaRole.FOLLOWER, ReplicaRole.OBSERVER) and not upstream:
            raise RpcApplicationError(INVALID_UPSTREAM, "follower requires upstream")

        def do():
            with self._db_admin_lock.locked(db_name):
                if self.db_manager.get_db(db_name) is not None:
                    raise RpcApplicationError(DB_ALREADY_EXISTS, db_name)
                self._open_app_db(db_name, parsed, upstream, overwrite,
                                  replication_mode=replication_mode,
                                  epoch=int(epoch))

        await self._run(do)
        return {}

    async def handle_close_db(self, db_name: str = "") -> dict:
        def do():
            with self._db_admin_lock.locked(db_name):
                if self.db_manager.remove_db(db_name) is None:
                    raise RpcApplicationError(DB_NOT_FOUND, db_name)

        await self._run(do)
        return {}

    async def handle_clear_db(
        self, db_name: str = "", reopen_db: bool = True
    ) -> dict:
        """clearDB: destroy data; optionally reopen fresh with the same
        role/upstream (admin_handler.cpp clearDB + reopen pattern)."""

        def do():
            with self._db_admin_lock.locked(db_name):
                app_db = self.db_manager.get_db(db_name)
                role, upstream, mode, epoch = ReplicaRole.NOOP, None, None, 0
                if app_db is not None:
                    role = app_db.role
                    mode = _current_mode(app_db)
                    epoch = _current_epoch(app_db)
                    if app_db.replicated_db is not None:
                        upstream = app_db.replicated_db.upstream_addr
                    self.db_manager.remove_db(db_name)
                destroy_db(self._db_path(db_name))
                self.clear_meta_data(db_name)
                if reopen_db:
                    self._open_app_db(db_name, role, upstream,
                                      replication_mode=mode, epoch=epoch)

        await self._run(do)
        return {}

    async def handle_change_db_role_and_upstream(
        self,
        db_name: str = "",
        new_role: str = "FOLLOWER",
        upstream_ip: str = "",
        upstream_port: int = 0,
        epoch: int = 0,
    ) -> dict:
        """changeDBRoleAndUpStream (admin_handler.cpp:1438): implemented as
        removeDB + addDB with the new role, keeping the storage.
        ``epoch`` is the controller's assignment epoch for the shard;
        max-merged with the live epoch so legacy callers (epoch 0) can
        never regress the fencing token."""
        parsed = _parse_role(new_role)
        upstream = (upstream_ip, upstream_port) if upstream_ip else None
        if parsed in (ReplicaRole.FOLLOWER, ReplicaRole.OBSERVER) and not upstream:
            raise RpcApplicationError(INVALID_UPSTREAM, "follower requires upstream")

        def do():
            with self._db_admin_lock.locked(db_name):
                app_db = self.db_manager.get_db(db_name)
                if app_db is None:
                    raise RpcApplicationError(DB_NOT_FOUND, db_name)
                # the ack mode survives role changes (an explicit addDB mode
                # must not silently revert to the dbconfig default)
                mode = _current_mode(app_db)
                new_epoch = max(int(epoch), _current_epoch(app_db))
                self.db_manager.remove_db(db_name)  # closes storage + repl
                self._open_app_db(db_name, parsed, upstream,
                                  replication_mode=mode, epoch=new_epoch)

        await self._run(do)
        return {}

    async def handle_rename_db(
        self,
        db_name: str = "",
        new_db_name: str = "",
        new_role: str = "",
        upstream_ip: str = "",
        upstream_port: int = 0,
        epoch: int = 0,
        retain_lo: str = "",
        retain_hi: str = "",
    ) -> dict:
        """renameDB — the shard-split cutover primitive: close the db,
        rename its storage directory, reopen under the new name with the
        given role/upstream/epoch (role empty = keep the current one).
        A range-split child starts life as a full copy of its parent
        under the PARENT's name (so the WAL-tail pull addresses match);
        at cutover this flips the copy to its child identity in one
        local, idempotent step.

        ``retain_lo``/``retain_hi`` (hex, [lo, hi)) record the child's
        key range in its durable metadata: every reopen folds the bounds
        into the engine options, and scheduled compactions then DROP the
        inherited other-half keys (DBOptions.retain_lo — the split-trim
        path) instead of carrying dead bytes forever.

        Idempotent for a resumed driver: if the new name is already
        registered and the old is gone, the rename already happened —
        succeed. If the process crashed between the directory rename and
        the reopen, the orphaned directory is adopted under the new
        name. Both per-db admin locks are taken in sorted-name order (a
        concurrent opposite-direction rename must not deadlock)."""
        if not new_db_name or new_db_name == db_name:
            raise RpcApplicationError(DB_ADMIN_ERROR,
                                      f"bad rename target {new_db_name!r}")
        parsed = _parse_role(new_role) if new_role else None
        upstream = (upstream_ip, upstream_port) if upstream_ip else None

        def do():
            first, second = sorted((db_name, new_db_name))
            with self._db_admin_lock.locked(first), \
                    self._db_admin_lock.locked(second):
                if self.db_manager.get_db(new_db_name) is not None:
                    if self.db_manager.get_db(db_name) is None:
                        return  # resumed after a completed rename
                    raise RpcApplicationError(DB_ALREADY_EXISTS, new_db_name)
                old_path = self._db_path(db_name)
                new_path = self._db_path(new_db_name)
                app_db = self.db_manager.get_db(db_name)
                role = parsed
                mode: Optional[int] = None
                live_epoch = 0
                up = upstream
                if app_db is not None:
                    if role is None:
                        role = app_db.role
                    mode = _current_mode(app_db)
                    live_epoch = _current_epoch(app_db)
                    if (up is None and app_db.replicated_db is not None
                            and role in (ReplicaRole.FOLLOWER,
                                         ReplicaRole.OBSERVER)):
                        up = app_db.replicated_db.upstream_addr
                    self.db_manager.remove_db(db_name)  # closes storage
                elif not os.path.exists(old_path):
                    # crashed between rename and reopen: adopt the dir
                    if not os.path.exists(new_path):
                        raise RpcApplicationError(DB_NOT_FOUND, db_name)
                if os.path.exists(old_path):
                    if os.path.exists(new_path):
                        # leftover from a crashed earlier attempt — the
                        # live data is still under the OLD name
                        destroy_db(new_path)
                    os.rename(old_path, new_path)
                if role is None:
                    raise RpcApplicationError(
                        INVALID_DB_ROLE, "rename of unregistered db "
                        "requires an explicit new_role")
                if role in (ReplicaRole.FOLLOWER, ReplicaRole.OBSERVER) \
                        and up is None:
                    raise RpcApplicationError(
                        INVALID_UPSTREAM, "follower requires upstream")
                # metadata BEFORE reopen: _open_app_db reads the retain
                # range out of the new name's metadata record
                meta = self.get_meta_data(db_name)
                self.write_meta_data(new_db_name, meta.s3_bucket,
                                     meta.s3_path,
                                     meta.last_kafka_msg_timestamp_ms,
                                     retain_lo=retain_lo or None,
                                     retain_hi=retain_hi or None)
                self.clear_meta_data(db_name)
                self._open_app_db(new_db_name, role, up,
                                  replication_mode=mode,
                                  epoch=max(int(epoch), live_epoch))

        await self._run(do)
        return {}

    async def handle_set_tenant_quota(
        self, tenant: str = "", ops_per_sec: float = 0.0,
        bytes_per_sec: float = 0.0,
    ) -> dict:
        """Runtime-mutable per-tenant admission quotas: override THIS
        node's token-bucket rates for one tenant without a restart
        (round-19 residual: quotas were static per-node env). Zero/zero
        clears the override back to the env defaults."""
        from ..rpc.admission import TenantAdmission, sanitize_tenant

        name = sanitize_tenant(tenant)
        TenantAdmission.get().set_quota(
            name, float(ops_per_sec), float(bytes_per_sec))
        return {"tenant": name, "ops_per_sec": float(ops_per_sec),
                "bytes_per_sec": float(bytes_per_sec)}

    async def handle_check_pull_stall(self, db_name: str = "") -> dict:
        """Flags-only sibling of check_db for the participant's 5s
        stall-heal probe: two booleans read straight off the
        ReplicatedDB, no disk I/O (check_db walks the WAL dir and the
        db directory — too heavy to run per follower shard per tick)."""
        app_db = self._get_app_db(db_name)
        rdb = app_db.replicated_db
        return {
            "role": app_db.role.value,
            "pull_stalled_wal_gap": bool(
                rdb is not None
                and getattr(rdb, "pull_stalled_wal_gap", False)),
            "pull_diverged": bool(
                rdb is not None
                and getattr(rdb, "pull_diverged", False)),
        }

    async def handle_pause_db_writes(
        self, db_name: str = "", duration_ms: float = 0.0
    ) -> dict:
        """Arm (or clear, duration_ms<=0) the shard's cutover write
        pause: NEW leader writes raise WRITE_PAUSED until the window
        expires, bounding the WAL tail a live shard move must drain.
        Auto-expiring by construction — a mover that dies after arming
        this leaves the shard serving again within the window."""

        def do():
            rdb = self._get_app_db(db_name).replicated_db
            if rdb is None:
                raise RpcApplicationError(
                    DB_ADMIN_ERROR, f"{db_name} is not replicated")
            rdb.pause_writes(float(duration_ms))
            return rdb.write_paused

        return {"paused": await self._run(do)}

    async def handle_set_db_epoch(
        self, db_name: str = "", epoch: int = 0
    ) -> dict:
        """Raise a hosted db's fencing epoch WITHOUT a role transition —
        the sticky-leader path: the controller re-stamped the assignment
        epoch (e.g. after a ledger rebuild) while the leader stays put,
        and the leader must adopt it before its followers (which learned
        the new epoch from their repoints) fence it as deposed. Epochs
        only move forward; a lower value is a no-op."""

        def do():
            # under the per-db admin lock like every other db mutation:
            # an adopt racing a concurrent reopen must not land on a
            # discarded ReplicatedDB and silently vanish
            with self._db_admin_lock.locked(db_name):
                rdb = self._get_app_db(db_name).replicated_db
                if rdb is not None:
                    rdb.adopt_epoch(int(epoch))
                return rdb.epoch if rdb is not None else 0

        return {"epoch": await self._run(do)}

    # ------------------------------------------------------------------
    # RPC: backup / restore
    # ------------------------------------------------------------------

    async def handle_backup_db(self, db_name: str = "", hdfs_backup_dir: str = "") -> dict:
        """backupDB — the reference's HDFS path; here any store URI
        (admin_handler.cpp:696-766)."""
        return await self._backup(db_name, hdfs_backup_dir, "")

    async def handle_restore_db(
        self, db_name: str = "", hdfs_backup_dir: str = "",
        upstream_ip: str = "", upstream_port: int = 0, to_seq: int = 0,
    ) -> dict:
        return await self._restore(db_name, hdfs_backup_dir, "",
                                   upstream_ip, upstream_port, to_seq)

    async def handle_backup_db_to_s3(
        self, db_name: str = "", s3_bucket: str = "", s3_backup_dir: str = "",
        limit_mbs: int = 0,
    ) -> dict:
        """backupDBToS3 (admin_handler.cpp:996-1129 checkpoint path)."""
        return await self._backup(db_name, s3_bucket, s3_backup_dir)

    async def handle_restore_db_from_s3(
        self, db_name: str = "", s3_bucket: str = "", s3_backup_dir: str = "",
        upstream_ip: str = "", upstream_port: int = 0, limit_mbs: int = 0,
        to_seq: int = 0, role: str = "",
    ) -> dict:
        """restoreDBFromS3 + PITR extension: ``to_seq > 0`` replays the
        backup's WAL archive (<prefix>/wal, written by the backup
        manager's archive_wal rider) over the checkpoint up to that
        sequence point. ``role`` overrides the post-restore registration
        role — a live shard move restores its target as an OBSERVER
        (WAL-tail catch-up without joining the semi-sync ack set: a
        write must never be acked solely by a half-built replica that an
        aborted move will sweep)."""
        return await self._restore(db_name, s3_bucket, s3_backup_dir,
                                   upstream_ip, upstream_port, to_seq,
                                   role=role)

    async def _backup(self, db_name: str, store_uri: str, sub_path: str) -> dict:
        app_db = self._get_app_db(db_name)
        store = self._store(store_uri)
        prefix = sub_path or db_name
        # run_in_executor drops contextvars: carry the rpc.server span's
        # context across the hop so the backup phases join the RPC trace.
        # always=True: control-plane ops are rare enough to trace
        # unconditionally — the 45 s backup round trip gets a per-phase
        # breakdown (checkpoint → upload batches → dbmeta) every time.
        tctx = wire_context()

        def do():
            # The per-db admin lock covers ONLY the checkpoint (fast,
            # hardlink-based): the upload — the 45 s part — runs outside
            # it, off the checkpoint's immutable hardlinked file set, so
            # a backup no longer blocks addDB/closeDB/ingest on the same
            # db for its whole duration (rstpu-check blocking-under-lock;
            # same narrowing as the round-7 ingest pipeline).
            with Timer("admin.backup_ms"), \
                    start_span("admin.backup_db", always=True, remote=tctx,
                               db=db_name):
                meta = self.get_meta_data(db_name)
                # stage INSIDE rocksdb_dir: same filesystem as the db,
                # so the checkpoint's os.link fast path works — on /tmp
                # an EXDEV fallback would copy every SST under the DB
                # lock, inverting the narrowing this path exists for
                tmp = tempfile.mkdtemp(
                    dir=self.rocksdb_dir, prefix=f".backup-{db_name}-")
                ckpt_dir = os.path.join(tmp, "ckpt")
                try:
                    with self._db_admin_lock.locked(db_name), \
                            start_span("admin.backup.checkpoint"):
                        # re-fetch under the lock: a closeDB+addDB that
                        # raced the pre-lock resolution must checkpoint
                        # the LIVE instance, not a closed stale handle
                        live = self.db_manager.get_db(db_name)
                        if live is None:
                            raise RpcApplicationError(DB_NOT_FOUND, db_name)
                        ckpt_seq = live.db.checkpoint(ckpt_dir)
                    return backup_mod.upload_checkpoint(
                        live.db.path, store, prefix, ckpt_dir, ckpt_seq,
                        meta={"last_kafka_msg_timestamp_ms":
                              meta.last_kafka_msg_timestamp_ms},
                    )
                finally:
                    shutil.rmtree(tmp, ignore_errors=True)

        dbmeta = await self._run(do)
        return {"seq": dbmeta["seq"], "timestamp_ms": dbmeta["timestamp_ms"]}

    async def _restore(
        self, db_name: str, store_uri: str, sub_path: str,
        upstream_ip: str, upstream_port: int, to_seq: int = 0,
        role: str = "",
    ) -> dict:
        store = self._store(store_uri)
        prefix = sub_path or db_name
        upstream = (upstream_ip, upstream_port) if upstream_ip else None
        if role:
            role = _parse_role(role)
            if role in (ReplicaRole.FOLLOWER, ReplicaRole.OBSERVER) \
                    and not upstream:
                raise RpcApplicationError(
                    INVALID_UPSTREAM, f"{role.value} requires upstream")
        else:
            role = ReplicaRole.FOLLOWER if upstream else ReplicaRole.NOOP
        tctx = wire_context()

        def do():
            with Timer("admin.restore_ms"), \
                    start_span("admin.restore_db", always=True, remote=tctx,
                               db=db_name, to_seq=to_seq):
                if to_seq > 0:
                    # PITR: checkpoint download + WAL-archive replay must
                    # materialize into the final path in one step; rare
                    # enough to stay fully serialized
                    from ..storage.archive import restore_db_to_seq

                    with self._db_admin_lock.locked(db_name):
                        if self.db_manager.get_db(db_name) is not None:
                            self.db_manager.remove_db(db_name)
                        destroy_db(self._db_path(db_name))
                        dbmeta = restore_db_to_seq(
                            store, prefix, f"{prefix}/wal",
                            self._db_path(db_name), to_seq=to_seq)
                        self._finish_restore(db_name, role, upstream, dbmeta)
                    return dbmeta
                # Plain restore: the download — the long part — runs into
                # a staging dir OUTSIDE the per-db admin lock, so a
                # restore no longer blocks same-db admin ops for its
                # whole transfer (rstpu-check blocking-under-lock); the
                # lock is taken only for the destroy→rename→reopen flip.
                # staging parent is unique per attempt (concurrent
                # restores of one db each download privately; last one
                # to take the lock wins the flip, as before) and lives
                # in rocksdb_dir so the rename is same-filesystem
                tmp_parent = tempfile.mkdtemp(
                    dir=self.rocksdb_dir, prefix=f".restore-{db_name}-")
                staging = os.path.join(tmp_parent, "db")
                try:
                    # the bulk transfer rides the SAME admission gate as
                    # SST loads (IngestGate): a drain-node restoring N
                    # moved shards onto this host pipelines its
                    # downloads boundedly instead of running N-wide.
                    # Restores QUEUE (enter_wait) rather than bounce —
                    # but the wait budget stays WELL below the caller's
                    # 600s RPC deadline: a slot that frees at t=550s
                    # would start a download with no client budget
                    # left, orphaning a server-side restore the mover
                    # already gave up on (and later re-registering a
                    # replica no move record points at)
                    if not self._ingest_gate.enter_wait(timeout=120.0):
                        raise RpcApplicationError(
                            TOO_MANY_REQUESTS,
                            f"{self._ingest_gate.in_flight} bulk loads in "
                            f"flight (max {self._ingest_gate.capacity})")
                    try:
                        dbmeta = backup_mod.restore_db(store, prefix,
                                                       staging)
                    finally:
                        self._ingest_gate.exit()
                    with self._db_admin_lock.locked(db_name):
                        if self.db_manager.get_db(db_name) is not None:
                            self.db_manager.remove_db(db_name)
                        destroy_db(self._db_path(db_name))
                        os.rename(staging, self._db_path(db_name))
                        self._finish_restore(db_name, role, upstream, dbmeta)
                finally:
                    shutil.rmtree(tmp_parent, ignore_errors=True)
                return dbmeta

        dbmeta = await self._run(do)
        # PITR restores report the seq actually reached after WAL replay,
        # not the checkpoint's
        return {"seq": dbmeta.get("restored_seq", dbmeta["seq"])}

    def _finish_restore(self, db_name, role, upstream, dbmeta) -> None:
        """Post-materialization half of a restore, under the per-db
        admin lock: register the reopened db + persist its kafka meta."""
        self._open_app_db(db_name, role, upstream)
        ts = dbmeta.get("last_kafka_msg_timestamp_ms")
        if ts:
            self.write_meta_data(db_name, last_kafka_msg_timestamp_ms=ts)

    # ------------------------------------------------------------------
    # RPC: SST bulk ingest — the north-star workload (§3.3)
    # ------------------------------------------------------------------

    async def handle_add_s3_sst_files_to_db(
        self,
        db_name: str = "",
        s3_bucket: str = "",
        s3_path: str = "",
        ingest_behind: bool = False,
        allow_overlapping_keys: bool = True,
        s3_download_limit_mb: int = 64,
        compact_db_after_load: bool = False,
    ) -> dict:
        """addS3SstFilesToDB (admin_handler.cpp:1635-1850), pipelined.

        Call-stack parity per SURVEY §3.3, with the per-db admin lock
        NARROWED (ISSUE 3): admission (idempotency + ingest-behind
        validation) takes the lock briefly, the download + SST validation
        run OUTSIDE it under the global ingest gate, then the lock is
        re-taken — with a close/idempotency staleness re-check — for the
        engine ingest + meta write only. N shards therefore download
        while others ingest; the post-load compaction coalesces across
        shards in the BatchCompactor."""
        store = self._store(s3_bucket)
        tctx = wire_context()

        def do():
            with start_span("admin.add_s3_sst", always=True, remote=tctx,
                            db=db_name, path=s3_path) as sp:
                return self._add_s3_sst(
                    sp, db_name, store, s3_bucket, s3_path, ingest_behind,
                    allow_overlapping_keys, compact_db_after_load,
                )

        return await self._run(do)

    def _add_s3_sst(
        self, sp, db_name, store, s3_bucket, s3_path,
        ingest_behind, allow_overlapping_keys, compact_after,
    ) -> dict:
        # -- admission: cheap checks only under the per-db lock ------------
        with self._db_admin_lock.locked(db_name):
            app_db = self._get_app_db(db_name)
            # idempotency via meta_db (:1655-1667)
            meta = self.get_meta_data(db_name)
            if meta.s3_bucket == s3_bucket and meta.s3_path == s3_path:
                return {"skipped": True}
            self._check_ingest_behind(app_db, ingest_behind)
        # concurrency gate (:1692-1706) — bounds the download/validate
        # stage globally, NOT under any db lock
        if not self._ingest_gate.try_enter():
            raise RpcApplicationError(
                TOO_MANY_REQUESTS,
                f"{self._ingest_gate.in_flight} ingests in flight "
                f"(max {self._ingest_gate.capacity})",
            )
        try:
            return self._do_ingest(
                sp, db_name, store, s3_bucket, s3_path,
                ingest_behind, allow_overlapping_keys, compact_after,
            )
        finally:
            self._ingest_gate.exit()

    @staticmethod
    def _check_ingest_behind(app_db: ApplicationDB, ingest_behind: bool):
        if not ingest_behind:
            return
        if not app_db.db.options.allow_ingest_behind:
            raise RpcApplicationError(
                DB_ADMIN_ERROR, "db not opened with allow_ingest_behind"
            )
        if not app_db.db_lmax_empty():
            raise RpcApplicationError(
                DB_ADMIN_ERROR, "bottom level not empty"
            )

    def _do_ingest(
        self, sp, db_name, store, s3_bucket, s3_path,
        ingest_behind, allow_overlapping_keys, compact_after,
    ) -> dict:
        tmp = tempfile.mkdtemp(prefix=f"rstpu-ingest-{db_name}-")
        try:
            # -- download + validate: OUTSIDE the per-db admin lock --------
            with Timer("admin.sst_download_ms"), \
                    start_span("admin.ingest.download"):
                local_files = store.get_objects(  # :1724-1726
                    s3_path, tmp,
                    direct_io=bool(FLAGS.get("s3_direct_io")))
            sst_files = [p for p in local_files if p.endswith(".tsst")]
            if not sst_files:
                raise RpcApplicationError(DB_ADMIN_ERROR, f"no .tsst under {s3_path}")
            with start_span("admin.ingest.validate", files=len(sst_files)):
                from ..storage.sst import SSTReader

                for path in sst_files:
                    try:
                        SSTReader(path).close()  # format/checksum probe
                    except Exception as e:
                        raise RpcApplicationError(
                            DB_ADMIN_ERROR, f"bad SST {os.path.basename(path)}: {e}"
                        ) from e
                    # Break object-store download hardlinks HERE, outside
                    # every lock: the engine's global-seqno footer rewrite
                    # must own the inode, and its own nlink guard would
                    # otherwise pay this copy under the DB lock.
                    if os.stat(path).st_nlink > 1:
                        tmp_copy = path + ".unlink"
                        shutil.copyfile(path, tmp_copy)
                        os.replace(tmp_copy, path)
            # -- ingest + meta: re-take the per-db lock, with staleness
            #    re-checks (the db and its meta may have changed while we
            #    were downloading without the lock) ------------------------
            with self._db_admin_lock.locked(db_name):
                app_db = self.db_manager.get_db(db_name)
                if app_db is None:
                    # closeDB won the race: surface DB_NOT_FOUND, never
                    # ingest into a closed/stale handle
                    raise RpcApplicationError(DB_NOT_FOUND, db_name)
                meta = self.get_meta_data(db_name)
                if meta.s3_bucket == s3_bucket and meta.s3_path == s3_path:
                    # a concurrent ingest of the same set won: idempotent
                    return {"skipped": True}
                self._check_ingest_behind(app_db, ingest_behind)
                target_db = app_db
                if not allow_overlapping_keys and not ingest_behind:
                    # full replace: close → destroy → reopen → re-add
                    # (:1774-1817)
                    role = app_db.role
                    mode = _current_mode(app_db)
                    epoch = _current_epoch(app_db)
                    upstream = (
                        app_db.replicated_db.upstream_addr
                        if app_db.replicated_db else None
                    )
                    self.db_manager.remove_db(db_name)
                    destroy_db(self._db_path(db_name))
                    target_db = self._open_app_db(db_name, role, upstream,
                                                  replication_mode=mode,
                                                  epoch=epoch)
                fp.hit("admin.ingest.engine")
                with Timer("admin.sst_ingest_ms"), \
                        start_span("admin.ingest.ingest", files=len(sst_files)):
                    target_db.db.ingest_external_file(
                        sst_files,
                        move_files=True,
                        allow_global_seqno=True,
                        ingest_behind=ingest_behind,
                        validated=True,  # probed in the pre-lock stage
                    )  # :1819-1827
                # the crash-consistency seam the chaos harness leans on:
                # a fault HERE must leave the DB fully post-ingest with
                # meta still pre-ingest (retryable), never meta-without-
                # data (tests/test_failpoints.py ingest invariants)
                fp.hit("admin.ingest.meta")
                with start_span("admin.ingest.meta"):
                    self.write_meta_data(db_name, s3_bucket, s3_path)  # :1836
            # -- post-load compaction: outside the admin lock, batched
            #    across concurrently-loading shards ------------------------
            if compact_after:
                with Timer("admin.post_ingest_compact_ms"), \
                        start_span("admin.ingest.compact") as csp:
                    try:
                        batched_with = self._batch_compactor.compact(
                            db_name, target_db.db)  # :1845-1850
                        csp.annotate(batch=batched_with)
                    except StorageError:
                        # compaction is advisory: a closeDB/clearDB that
                        # raced in after our ingest+meta committed tears
                        # the db down mid-compact — the load itself
                        # succeeded and a closed db needs no compaction,
                        # so don't fail the RPC for it
                        if self.db_manager.get_db(db_name) is not None:
                            raise
                        csp.annotate(skipped="db closed during compact")
                        log.info("%s closed during post-load compact; "
                                 "ingest already committed", db_name)
            sp.annotate(files=len(sst_files))
            self._stats.incr("admin.sst_files_ingested", len(sst_files))
            return {"ingested_files": len(sst_files)}
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    # ------------------------------------------------------------------
    # RPC: options / compaction
    # ------------------------------------------------------------------

    async def handle_set_db_options(
        self, db_name: str = "", options: Optional[Dict[str, Any]] = None
    ) -> dict:
        """setDBOptions (admin_handler.cpp:2134-2158)."""
        def do():
            with self._db_admin_lock.locked(db_name):
                app_db = self._get_app_db(db_name)
                try:
                    app_db.db.set_options(options or {})
                except StorageError as e:
                    raise RpcApplicationError(DB_ADMIN_ERROR, str(e)) from e

        await self._run(do)
        return {}

    async def handle_compact_db(self, db_name: str = "") -> dict:
        tctx = wire_context()

        def do():
            # per-db lock: a concurrent clearDB/closeDB must not destroy the
            # directory under a running compaction
            with self._db_admin_lock.locked(db_name):
                app_db = self._get_app_db(db_name)
                with Timer("admin.compact_ms"), \
                        start_span("admin.compact_db", always=True,
                                   remote=tctx, db=db_name):
                    app_db.compact_range()

        await self._run(do)
        return {}

    # ------------------------------------------------------------------
    # RPC: message ingestion (kafka-equivalent; wired by the queue stack)
    # ------------------------------------------------------------------

    async def handle_start_message_ingestion(
        self, db_name: str = "", topic_name: str = "",
        kafka_broker_serverset_path: str = "", replay_timestamp_ms: int = 0,
    ) -> dict:
        from ..kafka.ingestion import start_ingestion  # lazy: optional stack

        app_db = self._get_app_db(db_name)
        # Reserve the slot before any await (atomic on the event loop): two
        # concurrent starts must not both pass the check and leak a watcher.
        if db_name in self._ingestion:
            raise RpcApplicationError(DB_ADMIN_ERROR, f"{db_name} already ingesting")
        self._ingestion[db_name] = _RESERVED
        try:
            meta = self.get_meta_data(db_name)
            start_ts = max(replay_timestamp_ms, meta.last_kafka_msg_timestamp_ms)
            watcher = await self._run(
                start_ingestion, self, db_name, app_db, topic_name,
                kafka_broker_serverset_path, start_ts,
            )
        except BaseException:
            if self._ingestion.get(db_name) is _RESERVED:
                del self._ingestion[db_name]
            raise
        self._ingestion[db_name] = watcher
        return {}

    async def handle_stop_message_ingestion(self, db_name: str = "") -> dict:
        watcher = self._ingestion.get(db_name)
        if watcher is None:
            raise RpcApplicationError(DB_NOT_FOUND, f"{db_name} not ingesting")
        if watcher is _RESERVED:
            raise RpcApplicationError(DB_ADMIN_ERROR, f"{db_name} still starting")
        del self._ingestion[db_name]
        await self._run(watcher.stop)
        return {}

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def storage_info_text(self) -> str:
        """/storage_info.txt endpoint body (reference /rocksdb_info.txt)."""
        return self.db_manager.dump_db_stats_as_text()

    def close(self) -> None:
        for name in self.db_manager.get_all_db_names():
            self.db_manager.remove_db(name)
        for watcher in self._ingestion.values():
            try:
                watcher.stop()
            except Exception:
                pass
        self._ingestion.clear()
        self._meta_db.close()
        self._batch_compactor.close()
        self._executor.shutdown(wait=False)
