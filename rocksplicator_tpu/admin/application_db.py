"""ApplicationDB: storage DB + replication registration.

Reference: rocksdb_admin/application_db.{h,cpp} — wraps rocksdb::DB, routes
writes through ``ReplicatedDB::Write`` when the db is replicated
(application_db.cpp:122-136), delegates reads with stats, exposes
``CompactRange``/``GetProperty`` including the custom
``applicationdb.num-levels`` / ``applicationdb.highest-empty-level`` props
backing the ``DBLmaxEmpty()`` ingest-behind safety check
(application_db.cpp:183-225). The constructor registers with the
replicator (application_db.cpp:52-70); ``close`` unregisters.
"""

from __future__ import annotations

import itertools
import logging
from typing import Iterator, List, Optional, Tuple

from ..replication.db_wrapper import (DbWrapper, StorageDbWrapper,
                                      execute_read_op)
from ..replication.replicated_db import LeaderResolver, ReplicatedDB
from ..replication.replicator import Replicator
from ..replication.wire import ReplicaRole
from ..storage.engine import DB
from ..storage.records import WriteBatch
from ..utils.stats import Stats, tagged

log = logging.getLogger(__name__)

# process-unique suffixes for the fallback gauge registrations below
_APPDB_GAUGE_REFS = itertools.count(1)


class ApplicationDB:
    def __init__(
        self,
        name: str,
        db: DB,
        role: ReplicaRole,
        replicator: Optional[Replicator] = None,
        upstream_addr: Optional[Tuple[str, int]] = None,
        replication_mode: Optional[int] = None,
        leader_resolver: Optional[LeaderResolver] = None,
        wrapper: Optional[DbWrapper] = None,
        enable_read_stats: bool = True,  # optional: ~10M Get/s design point
        epoch: int = 0,
    ):
        self.name = name
        self.db = db
        self.role = role
        self._replicator = replicator
        self._stats = Stats.get()
        self._enable_read_stats = enable_read_stats
        # local engine reader for the bounded-staleness read path: always
        # reads THIS replica's engine, independent of whatever wrapper
        # (possibly a non-persisting proxy) is registered for replication
        self._reader = StorageDbWrapper(db)
        self.replicated_db: Optional[ReplicatedDB] = None
        repl_wrapper = wrapper or StorageDbWrapper(db)
        if replicator is not None and role is not ReplicaRole.NOOP:
            self.replicated_db = replicator.add_db(
                name,
                repl_wrapper,
                role,
                upstream_addr=upstream_addr,
                replication_mode=replication_mode,
                leader_resolver=leader_resolver,
                epoch=epoch,
            )
        # engine introspection gauges (round 14): the replicator's
        # add_db registers them when the replication wrapper exposes the
        # engine; otherwise (unreplicated/NOOP dbs, CDC observers whose
        # wrapper has no local engine) this ApplicationDB owns them. The
        # ref tag disambiguates colocated same-name shards (in-process
        # test topologies) the way the replicator path's port tag does —
        # without it, two registrations would silently overwrite each
        # other and either close() would strip the survivor's gauges.
        from ..storage.engine import register_db_gauges

        self._gauge_names: list = []
        if self.replicated_db is None or repl_wrapper.gauge_target() is None:
            self._gauge_names = register_db_gauges(
                name, db, ref=f"a{next(_APPDB_GAUGE_REFS)}")

    # -- writes ------------------------------------------------------------

    def write(self, batch: WriteBatch) -> int:
        if self.replicated_db is not None:
            seq = self.replicated_db.write(batch)
        else:
            seq = self.db.write(batch)
        self._stats.incr(tagged("applicationdb.writes", db=self.name))
        return seq

    def write_async(self, batch: WriteBatch):
        """Pipelined write: WAL-commit now, return an AckWaiter whose
        ``future`` (a concurrent.futures.Future) resolves when the
        replication ack condition is met — async handlers await it via
        asyncio.wrap_future instead of parking an executor thread per
        in-flight write. Unreplicated DBs return an already-resolved
        waiter."""
        from ..replication.ack_window import resolved_waiter

        if self.replicated_db is not None:
            waiter = self.replicated_db.write_async(batch)
        else:
            waiter = resolved_waiter(self.db.write(batch))
        self._stats.incr(tagged("applicationdb.writes", db=self.name))
        return waiter

    def write_many(self, batches: List[WriteBatch]) -> int:
        """Grouped-commit apply (round 6 ``write_many``): every batch
        commits with ONE storage lock pass and one WAL flush. The CDC
        batched apply path rides this; blocking semantics mirror
        ``write`` (replicated dbs wait each batch's ack future — ack or
        timeout — so callers see the same degradation accounting as N
        blocking writes). Returns the first batch's start seq."""
        if not batches:
            return 0
        if self.replicated_db is not None:
            import time as _time

            waiters = self.replicated_db.write_async_many(batches)
            for w in waiters:
                try:
                    w.result(max(0.0, w.deadline - _time.monotonic()) + 2.0)
                except Exception:
                    pass  # timeout accounting lives in the ack window
            seq = waiters[0].seq
        else:
            seq = self.db.write_many([(b, None) for b in batches])
        self._stats.incr(
            tagged("applicationdb.writes", db=self.name), len(batches))
        return seq

    # -- reads -------------------------------------------------------------

    def get(self, key: bytes) -> Optional[bytes]:
        if self._enable_read_stats:
            self._stats.incr(tagged("applicationdb.gets", db=self.name))
        return self.db.get(key)

    def multi_get(self, keys: List[bytes]) -> List[Optional[bytes]]:
        if self._enable_read_stats:
            self._stats.incr(
                tagged("applicationdb.multigets", db=self.name), len(keys)
            )
        return self.db.multi_get(keys)

    def read(
        self,
        op: str = "get",
        keys=None,
        start: Optional[bytes] = None,
        count: Optional[int] = None,
        max_lag: Optional[int] = None,
        epoch: Optional[int] = None,
    ) -> dict:
        """Bounded-staleness local read (round 13): the in-process analog
        of the replication plane's ``read`` RPC, for embedding services
        (reference: ApplicationDB delegating reads to rocksdb,
        application_db.cpp:138-181) that want the same guarantees a
        routed client gets. Replicated dbs gate through
        ``ReplicatedDB.read_gate`` — a FOLLOWER serves only within
        ``max_lag`` of the leader's committed sequence and rejects a
        newer-epoch (deposed-lineage) read exactly as it rejects
        stale-epoch pulls; the sync gate never probes, so a follower
        whose commit-point estimate aged out bounces rather than
        blocking. Unreplicated/NOOP dbs serve directly."""
        gate: dict = {"applied_seq": None, "leader_seq": None, "lag": None}
        if self.replicated_db is not None:
            gate = self.replicated_db.read_gate(max_lag=max_lag, epoch=epoch)
        if self._enable_read_stats:
            self._stats.incr(tagged("applicationdb.reads", db=self.name))
        # one shared dispatch with the RPC path (execute_read_op) over a
        # local engine reader, so the two surfaces cannot diverge
        values = execute_read_op(self._reader, op, keys=keys, start=start,
                                 count=count)
        return {**gate, "values": values, "source_role": self.role.value}

    def new_iterator(self, start=None, end=None) -> Iterator[Tuple[bytes, bytes]]:
        return self.db.new_iterator(start, end)

    # -- admin surface -----------------------------------------------------

    def compact_range(self, start=None, end=None) -> None:
        self.db.compact_range(start, end)

    def get_property(self, name: str) -> Optional[str]:
        # applicationdb.* prefix parity (application_db.cpp:183-199)
        if name.startswith("applicationdb."):
            name = name[len("applicationdb."):]
        return self.db.get_property(name)

    def db_lmax_empty(self) -> bool:
        """True iff the bottom level is empty ⇒ ingest_behind is safe
        (application_db.cpp:200-225). highest-empty-level is -1 exactly
        when the bottom level holds files."""
        return int(self.get_property("highest-empty-level") or -1) != -1

    def latest_sequence_number(self) -> int:
        return self.db.latest_sequence_number()

    def close(self) -> None:
        from ..storage.engine import unregister_db_gauges

        unregister_db_gauges(self._gauge_names)
        self._gauge_names = []
        if self.replicated_db is not None and self._replicator is not None:
            try:
                self._replicator.remove_db(self.name)
            except KeyError:
                pass
            self.replicated_db = None
        self.db.close()
