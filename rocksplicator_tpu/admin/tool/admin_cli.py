"""Admin CLI — the script-driven cluster management tool.

Reference: rocksdb_admin/tool/rocksdb_admin.py (731 LoC) — config
generation from a host file, ping, failover (promote/demote via
changeDBRoleAndUpStream), remove_host, load_sst orchestration across the
cluster. Commands here speak the Admin RPC directly or read a shard-map
file for cluster-wide operations.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

from ...cluster.helix_utils import AdminClient
from ...rpc.router import ClusterLayout, Role
from ...utils.segment_utils import segment_to_db_name


def _load_layout(path: str) -> ClusterLayout:
    with open(path, "rb") as f:
        return ClusterLayout.parse(f.read())


def cmd_ping(admin: AdminClient, args) -> int:
    ok = admin.ping((args.host, args.port))
    print(f"{args.host}:{args.port} {'OK' if ok else 'UNREACHABLE'}")
    return 0 if ok else 1


def cmd_status(admin: AdminClient, args) -> int:
    layout = _load_layout(args.shard_map)
    rc = 0
    for segment, seg in sorted(layout.segments.items()):
        print(f"segment {segment}: {seg.num_shards} shards")
        for shard in sorted(seg.shard_to_hosts):
            db_name = segment_to_db_name(segment, shard)
            for host, role in seg.shard_to_hosts[shard]:
                seq = admin.get_sequence_number((host.ip, host.port), db_name)
                mark = "M" if role is Role.LEADER else "S"
                status = f"seq={seq}" if seq is not None else "DOWN"
                if seq is None:
                    rc = 1
                print(f"  {db_name} {mark} {host.ip}:{host.port} {status}")
    return rc


def cmd_config_gen(admin: AdminClient, args) -> int:
    """Static shard map from a host file (one ip:port:az per line):
    round-robin leaders, next-host followers (reference config gen)."""
    hosts: List[str] = []
    with open(args.host_file) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                hosts.append(line)
    if not hosts:
        print("no hosts", file=sys.stderr)
        return 1
    seg: Dict[str, object] = {"num_shards": args.shard_num}
    per_host: Dict[str, List[str]] = {h: [] for h in hosts}
    for shard in range(args.shard_num):
        for r in range(min(args.replicas, len(hosts))):
            host = hosts[(shard + r) % len(hosts)]
            marker = "M" if r == 0 else "S"
            per_host[host].append(f"{shard:05d}:{marker}")
    for host, entries in per_host.items():
        if entries:
            seg[host] = entries
    print(json.dumps({args.segment: seg}, indent=2, sort_keys=True))
    return 0


def cmd_failover(admin: AdminClient, args) -> int:
    """Promote --new_leader; demote the old leader to its follower
    (reference promote/demote via changeDBRoleAndUpStream)."""
    layout = _load_layout(args.shard_map)
    seg = layout.segments[args.segment]
    db_name = segment_to_db_name(args.segment, args.shard)
    new_ip, new_port = args.new_leader.split(":")
    new_port = int(new_port)
    old_leader = None
    new_host = None
    for host, role in seg.shard_to_hosts[args.shard]:
        if role is Role.LEADER:
            old_leader = host
        if (host.ip, host.port) == (new_ip, new_port):
            new_host = host
    if new_host is None:
        print(f"{args.new_leader} does not host shard {args.shard}",
              file=sys.stderr)
        return 1
    if old_leader and (old_leader.ip, old_leader.port) != (new_ip, new_port):
        admin.change_db_role_and_upstream(
            (old_leader.ip, old_leader.port), db_name, "FOLLOWER",
            new_host.repl_addr,
        )
        print(f"demoted {old_leader.ip}:{old_leader.port}")
    admin.change_db_role_and_upstream(
        (new_ip, new_port), db_name, "LEADER"
    )
    print(f"promoted {args.new_leader} for {db_name}")
    # repoint remaining followers
    for host, role in seg.shard_to_hosts[args.shard]:
        if (host.ip, host.port) in ((new_ip, new_port),
                                    (old_leader.ip, old_leader.port)
                                    if old_leader else ()):
            continue
        admin.change_db_role_and_upstream(
            (host.ip, host.port), db_name, "FOLLOWER", new_host.repl_addr
        )
        print(f"repointed {host.ip}:{host.port}")
    return 0


def cmd_remove_host(admin: AdminClient, args) -> int:
    layout = _load_layout(args.shard_map)
    ip, port = args.target.split(":")
    port = int(port)
    removed = 0
    for segment, seg in layout.segments.items():
        for shard, hosts in seg.shard_to_hosts.items():
            for host, _role in hosts:
                if (host.ip, host.port) == (ip, port):
                    db_name = segment_to_db_name(segment, shard)
                    try:
                        admin.close_db((ip, port), db_name)
                        removed += 1
                    except Exception as e:
                        print(f"  {db_name}: {e}", file=sys.stderr)
    print(f"closed {removed} dbs on {args.target}")
    return 0


def cmd_load_sst(admin: AdminClient, args) -> int:
    """Cluster-wide SST load: ingest each shard's files on its leader
    (reference load_sst orchestration)."""
    layout = _load_layout(args.shard_map)
    seg = layout.segments[args.segment]
    failures = 0
    for shard in sorted(seg.shard_to_hosts):
        db_name = segment_to_db_name(args.segment, shard)
        leader = next(
            (h for h, r in seg.shard_to_hosts[shard] if r is Role.LEADER),
            None,
        )
        if leader is None:
            print(f"{db_name}: no leader", file=sys.stderr)
            failures += 1
            continue
        try:
            r = admin.ingest_from_store(
                (leader.ip, leader.port), db_name, args.store_uri,
                f"{args.sst_path}/{shard:05d}",
                ingest_behind=args.ingest_behind,
                compact_db_after_load=args.compact,
            )
            print(f"{db_name}: {r}")
        except Exception as e:
            print(f"{db_name}: FAILED {e}", file=sys.stderr)
            failures += 1
    return 1 if failures else 0


def _coord_client(spec: str):
    from ...cluster.coordinator import CoordinatorClient

    host, _, port = spec.partition(":")
    return CoordinatorClient(host, int(port))


def cmd_move_shard(admin: AdminClient, args) -> int:
    """Live elastic shard move (snapshot → bulk-ingest → WAL-tail
    catch-up → epoch-bumped flip) driven by the resumable step machine;
    --resume continues a recorded in-flight move, --abort unwinds a
    pre-cutover one."""
    from ...cluster.shard_move import MoveError, ShardMove

    partition = f"{args.segment}_{args.shard}"
    if not (args.resume or args.abort) and not (
            args.source and args.target and args.store_uri):
        print("move-shard: --source, --target and --store_uri are "
              "required for a new move", file=sys.stderr)
        return 2
    coord = _coord_client(args.coord)
    try:
        if args.abort:
            ShardMove.resume(coord, args.cluster, partition,
                             admin=admin).abort()
            print(f"{partition}: move aborted")
            return 0
        if args.resume:
            mv = ShardMove.resume(coord, args.cluster, partition,
                                  admin=admin)
        else:
            mv = ShardMove.start(
                coord, args.cluster, partition, args.source, args.target,
                args.store_uri, admin=admin)
        rec = mv.run()
        print(json.dumps({
            "move_id": rec.move_id, "partition": rec.partition,
            "source": rec.source, "target": rec.target,
            "bytes_ingested": rec.bytes_ingested,
        }))
        return 0
    except MoveError as e:
        print(f"move failed: {e}", file=sys.stderr)
        return 1
    finally:
        coord.close()


def cmd_drain_node(admin: AdminClient, args) -> int:
    """Move every replica off --node (sequential moves) — the minimal
    whole-node evacuation. Targets rank least-loaded-first by the
    scraped /cluster_stats per-shard rates when the coordinator has a
    published shard map, falling back to least shard count."""
    from ...cluster.shard_move import MoveError, drain_node

    coord = _coord_client(args.coord)
    try:
        moved = drain_node(coord, args.cluster, args.node,
                           args.store_uri, admin=admin, log_fn=print)
        print(f"drained {args.node}: {len(moved)} partition(s)")
        return 0
    except MoveError as e:
        print(f"drain failed: {e}", file=sys.stderr)
        return 1
    finally:
        coord.close()


def cmd_split_shard(admin: AdminClient, args) -> int:
    """Live hot-shard range split: the parent hash slot becomes two
    range-partitioned virtual children (low = the parent's replicas
    renamed in place; high = snapshot → observer catch-up → rename on
    --target). --split_key is the hex boundary; omit it to sample the
    leader's keyspace median. --resume continues a recorded split,
    --abort unwinds a strictly pre-cutover one."""
    from ...cluster.shard_split import (ShardSplit, SplitError,
                                        choose_split_key)

    partition = f"{args.segment}_{args.shard}"
    coord = _coord_client(args.coord)
    try:
        if args.abort:
            ShardSplit.resume(coord, args.cluster, partition,
                              admin=admin).abort()
            print(f"{partition}: split aborted")
            return 0
        if args.resume:
            sp = ShardSplit.resume(coord, args.cluster, partition,
                                   admin=admin)
        else:
            if not (args.target and args.store_uri):
                print("split-shard: --target and --store_uri are "
                      "required for a new split", file=sys.stderr)
                return 2
            split_key = bytes.fromhex(args.split_key) \
                if args.split_key else None
            if split_key is None:
                # sample the leader's keyspace for the median boundary
                from ...cluster.model import (InstanceInfo, cluster_path,
                                              decode_states as _ds)
                from ...utils.segment_utils import (
                    db_name_to_partition_name, segment_to_db_name)
                db_name = segment_to_db_name(args.segment, args.shard)
                leader_addr = None
                for iid in coord.list(
                        cluster_path(args.cluster, "currentstates")):
                    st = _ds(coord.get_or_none(cluster_path(
                        args.cluster, "currentstates", iid))).get(
                            db_name_to_partition_name(db_name))
                    if st in ("LEADER", "MASTER"):
                        raw = coord.get_or_none(cluster_path(
                            args.cluster, "instances", iid))
                        if raw:
                            info = InstanceInfo.decode(raw)
                            leader_addr = (info.host, info.repl_port)
                        break
                if leader_addr is not None:
                    split_key = choose_split_key(admin, leader_addr,
                                                 db_name)
            if not split_key:
                print("split-shard: no --split_key given and the "
                      "keyspace sample found no usable boundary",
                      file=sys.stderr)
                return 1
            sp = ShardSplit.start(
                coord, args.cluster, args.segment, args.shard,
                split_key, args.target, args.store_uri, admin=admin)
        rec = sp.run()
        print(json.dumps({
            "split_id": rec.split_id, "segment": rec.segment,
            "parent_shard": rec.parent_shard,
            "split_key": rec.split_key, "low_shard": rec.low_shard,
            "high_shard": rec.high_shard, "epoch": rec.epoch,
        }))
        return 0
    except SplitError as e:
        print(f"split failed: {e}", file=sys.stderr)
        return 1
    finally:
        coord.close()


def cmd_rebalance(admin: AdminClient, args) -> int:
    """Rebalancer control surface: ``status`` prints the durable status
    document, ``pause``/``resume`` flip the durable pause flag every
    rebalancer honors, ``once`` runs a single sense→decide→plan→
    dispatch tick inline (policy-initiated moves/splits, no loop)."""
    from ...cluster.rebalancer import Rebalancer
    from ...cluster.model import cluster_path

    coord = _coord_client(args.coord)
    try:
        if args.action == "status":
            raw = coord.get_or_none(cluster_path(args.cluster,
                                                 "rebalancer"))
            doc = {}
            if raw:
                try:
                    doc = json.loads(bytes(raw).decode())
                except (ValueError, UnicodeDecodeError):
                    doc = {}
            print(json.dumps(doc, indent=1, sort_keys=True))
            return 0
        if args.action in ("pause", "resume"):
            Rebalancer.set_paused(coord, args.cluster,
                                  args.action == "pause")
            print(f"rebalancer {args.action}d")
            return 0
        # once
        if not args.store_uri:
            print("rebalance once: --store_uri is required (move/split "
                  "snapshots land there)", file=sys.stderr)
            return 2
        rb = Rebalancer(coord, args.cluster, args.store_uri, admin=admin)
        plans = rb.once()
        for t in rb._workers:
            t.join()
        print(json.dumps({"dispatched": plans,
                          "counters": rb._dispatched}))
        return 0
    finally:
        coord.close()


def cmd_set_tenant_quota(admin: AdminClient, args) -> int:
    """Push a live per-tenant admission quota override to each node
    (host:admin_port list) — takes effect on the tenant's next request,
    no restart."""
    rc = 0
    for spec in args.nodes:
        ip, _, port = spec.partition(":")
        try:
            r = admin.set_tenant_quota((ip, int(port)), args.tenant,
                                       args.ops_per_sec,
                                       args.bytes_per_sec)
            print(f"{spec}: {json.dumps(r)}")
        except Exception as e:
            print(f"{spec}: FAILED {e}", file=sys.stderr)
            rc = 1
    return rc


def cmd_backup(admin: AdminClient, args) -> int:
    r = admin.backup_db_to_store(
        (args.host, args.port), args.db, args.store_uri, args.backup_path
    )
    print(json.dumps(r))
    return 0


def cmd_restore(admin: AdminClient, args) -> int:
    upstream = None
    if args.upstream:
        ip, port = args.upstream.split(":")
        upstream = (ip, int(port))
    r = admin.restore_db_from_store(
        (args.host, args.port), args.db, args.store_uri, args.backup_path,
        upstream, to_seq=args.to_seq,
    )
    print(json.dumps(r))
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="admin_cli")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("ping")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, required=True)
    sp.set_defaults(fn=cmd_ping)

    sp = sub.add_parser("status")
    sp.add_argument("--shard_map", required=True)
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser("config_gen")
    sp.add_argument("--host_file", required=True)
    sp.add_argument("--segment", required=True)
    sp.add_argument("--shard_num", type=int, default=1000)
    sp.add_argument("--replicas", type=int, default=3)
    sp.set_defaults(fn=cmd_config_gen)

    sp = sub.add_parser("failover")
    sp.add_argument("--shard_map", required=True)
    sp.add_argument("--segment", required=True)
    sp.add_argument("--shard", type=int, required=True)
    sp.add_argument("--new_leader", required=True, help="ip:service_port")
    sp.set_defaults(fn=cmd_failover)

    sp = sub.add_parser("remove_host")
    sp.add_argument("--shard_map", required=True)
    sp.add_argument("--target", required=True, help="ip:service_port")
    sp.set_defaults(fn=cmd_remove_host)

    sp = sub.add_parser("load_sst")
    sp.add_argument("--shard_map", required=True)
    sp.add_argument("--segment", required=True)
    sp.add_argument("--store_uri", required=True)
    sp.add_argument("--sst_path", required=True)
    sp.add_argument("--ingest_behind", action="store_true")
    sp.add_argument("--compact", action="store_true")
    sp.set_defaults(fn=cmd_load_sst)

    sp = sub.add_parser("move-shard")
    sp.add_argument("--coord", required=True, help="host:port")
    sp.add_argument("--cluster", required=True)
    sp.add_argument("--segment", required=True)
    sp.add_argument("--shard", type=int, required=True)
    sp.add_argument("--source", default="",
                    help="instance_id donating the replica")
    sp.add_argument("--target", default="",
                    help="instance_id receiving it")
    sp.add_argument("--store_uri", default="",
                    help="object store for the move snapshot")
    sp.add_argument("--resume", action="store_true",
                    help="continue the recorded in-flight move")
    sp.add_argument("--abort", action="store_true",
                    help="unwind a pre-cutover move (sweeps the "
                         "target's half-built replica)")
    sp.set_defaults(fn=cmd_move_shard)

    sp = sub.add_parser("split-shard")
    sp.add_argument("--coord", required=True, help="host:port")
    sp.add_argument("--cluster", required=True)
    sp.add_argument("--segment", required=True)
    sp.add_argument("--shard", type=int, required=True,
                    help="parent shard (hash slot or live child)")
    sp.add_argument("--split_key", default="",
                    help="hex boundary key; omitted = sample the "
                         "leader's keyspace median")
    sp.add_argument("--target", default="",
                    help="instance_id receiving the high child")
    sp.add_argument("--store_uri", default="",
                    help="object store for the split snapshot")
    sp.add_argument("--resume", action="store_true",
                    help="continue the recorded in-flight split")
    sp.add_argument("--abort", action="store_true",
                    help="unwind a strictly pre-cutover split")
    sp.set_defaults(fn=cmd_split_shard)

    sp = sub.add_parser("rebalance")
    sp.add_argument("action",
                    choices=("status", "pause", "resume", "once"))
    sp.add_argument("--coord", required=True, help="host:port")
    sp.add_argument("--cluster", required=True)
    sp.add_argument("--store_uri", default="",
                    help="object store for policy-initiated move/split "
                         "snapshots (required for `once`)")
    sp.set_defaults(fn=cmd_rebalance)

    sp = sub.add_parser("set-tenant-quota")
    sp.add_argument("--tenant", required=True)
    sp.add_argument("--ops_per_sec", type=float, default=0.0)
    sp.add_argument("--bytes_per_sec", type=float, default=0.0)
    sp.add_argument("nodes", nargs="+",
                    help="host:admin_port of each node to push to")
    sp.set_defaults(fn=cmd_set_tenant_quota)

    sp = sub.add_parser("drain-node")
    sp.add_argument("--coord", required=True, help="host:port")
    sp.add_argument("--cluster", required=True)
    sp.add_argument("--node", required=True, help="instance_id to drain")
    sp.add_argument("--store_uri", required=True)
    sp.set_defaults(fn=cmd_drain_node)

    sp = sub.add_parser("backup")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, required=True)
    sp.add_argument("--db", required=True)
    sp.add_argument("--store_uri", required=True)
    sp.add_argument("--backup_path", required=True)
    sp.set_defaults(fn=cmd_backup)

    sp = sub.add_parser("restore")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, required=True)
    sp.add_argument("--db", required=True)
    sp.add_argument("--store_uri", required=True)
    sp.add_argument("--backup_path", required=True)
    sp.add_argument("--upstream", default=None, help="ip:repl_port")
    sp.add_argument("--to_seq", type=int, default=0,
                    help="point-in-time restore: replay the WAL archive "
                         "up to this sequence number (0 = plain restore)")
    sp.set_defaults(fn=cmd_restore)

    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    admin = AdminClient()
    try:
        return args.fn(admin, args)
    finally:
        admin.close()


if __name__ == "__main__":
    sys.exit(main())
