"""Bulk-ingest pipeline plumbing: admission gate + cross-shard batched
post-load compaction.

The pipelined load_sst path (ISSUE 3) is three bounded stages:

- **download/validate** — outside the per-db admin lock, globally bounded
  by :class:`IngestGate` (the reference's
  ``num_current_s3_sst_downloadings_`` TOO_MANY_REQUESTS gate,
  admin_handler.cpp:1692-1706) so shard k+1's object-store fetch overlaps
  shard k's engine ingest;
- **ingest + meta** — back under the per-db admin lock with a staleness
  re-check (the lock-narrowing half; see admin/handler.py);
- **post-load compact** — :class:`BatchCompactor`: concurrent shards'
  compactions coalesce AckWindow/group-commit style; one submitter
  becomes the dispatch leader and drains the whole queue as a batch (one
  padded device launch on the TPU backend via
  tpu.compaction_service.compact_dbs_batched; thread-pool fan-out on
  CPU), every submitter just waits on its shard's future.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

from ..testing import failpoints as fp

log = logging.getLogger(__name__)


def default_sst_loading_concurrency() -> int:
    """CPU-derived default for the ingest admission gate. The reference
    gflag defaulted to 999 — dead code as a gate; download+validate is
    IO-plus-checksum work, so ~2 slots per core keeps the pipeline full
    without letting an ingest storm starve serving threads."""
    return max(4, 2 * (os.cpu_count() or 2))


class IngestGate:
    """Counting admission gate for in-flight SST loads. ``try_enter``
    never blocks — over-capacity callers are REJECTED (the handler maps
    that to TOO_MANY_REQUESTS, matching the reference's behavior of
    telling the orchestrator to back off rather than queueing)."""

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._free = threading.Condition(self._lock)
        self._in_flight = 0
        self._waiting = 0

    @property
    def in_flight(self) -> int:
        with self._lock:
            return self._in_flight

    def try_enter(self) -> bool:
        with self._lock:
            if self._in_flight >= self.capacity:
                return False
            self._in_flight += 1
            return True

    def enter_wait(self, timeout: float, max_waiting: int = 2) -> bool:
        """Blocking admission for callers that should QUEUE rather than
        bounce: snapshot-restore downloads in a live shard move (a
        drain-node moving N shards pipelines its bulk transfers through
        this gate, exactly like the SST-load path, instead of saturating
        the NIC/disk N-wide). Returns False when no slot freed within
        ``timeout`` — or IMMEDIATELY when ``max_waiting`` callers are
        already parked: each waiter occupies a shared admin-executor
        thread, and an unbounded queue of 10-minute waits would starve
        every other admin RPC on the host (the PR-9 WRITE_WINDOW_FULL
        fail-fast lesson). The SST-load RPC keeps try_enter's
        reject-don't-queue contract."""
        deadline = time.monotonic() + timeout
        with self._free:
            if self._in_flight >= self.capacity \
                    and self._waiting >= max_waiting:
                return False
            self._waiting += 1
            try:
                while self._in_flight >= self.capacity:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._free.wait(remaining):
                        if self._in_flight < self.capacity:
                            break
                        return False
                self._in_flight += 1
                return True
            finally:
                self._waiting -= 1

    def exit(self) -> None:
        with self._lock:
            self._in_flight -= 1
            self._free.notify()


class BatchCompactor:
    """Group-commit for post-load compactions.

    ``compact(db_name, db)`` blocks until the shard's full compaction is
    done, but concurrent callers are BATCHED: the first submitter into an
    idle compactor becomes the leader and repeatedly drains everything
    queued (shards that arrive while a batch runs form the next batch —
    the same natural coalescing as WAL group commit). Dispatch goes
    through the configured backend: one padded device launch per batch
    when ``use_tpu`` (compact_dbs_batched), thread-pool fan-out of
    per-db ``compact_range`` otherwise (and for shards the lane
    representation declines).
    """

    def __init__(self, use_tpu: bool = False,
                 compact_parallelism: Optional[int] = None,
                 max_batch: int = 64):
        self._use_tpu = use_tpu
        self._max_batch = max_batch
        self._lock = threading.Lock()
        self._queue: List[Tuple[str, object, Future]] = []
        self._dispatching = False
        # compaction releases the GIL in its numpy/zlib/fsync phases, so
        # more workers than cores still overlaps usefully
        self._pool = ThreadPoolExecutor(
            max_workers=compact_parallelism or max(4, os.cpu_count() or 2),
            thread_name_prefix="post-load-compact",
        )
        # observability: batches dispatched and their sizes (tests + the
        # bench's "did the batching actually batch" assertion)
        self.dispatch_count = 0
        self.batch_sizes: List[int] = []

    def compact(self, db_name: str, db) -> int:
        """Compact ``db`` (a storage.engine.DB), batched with concurrent
        callers. Returns the size of the batch this shard rode in."""
        fut: Future = Future()
        with self._lock:
            self._queue.append((db_name, db, fut))
            leader = not self._dispatching
            if leader:
                self._dispatching = True
        if leader:
            try:
                while True:
                    with self._lock:
                        batch = self._queue[: self._max_batch]
                        del self._queue[: self._max_batch]
                        if not batch:
                            self._dispatching = False
                            break
                    try:
                        self._dispatch(batch)
                    except BaseException as e:
                        # a dispatch blow-up (e.g. pool shutdown mid-close)
                        # must fail ITS batch loudly and keep draining —
                        # never strand waiters or the leadership flag
                        log.exception("compact dispatch failed")
                        for _n, _d, f in batch:
                            if not f.done():
                                f.set_exception(e)
            except BaseException:
                # pathological (queue handling itself raised): hand
                # leadership back so the compactor is not wedged forever
                with self._lock:
                    self._dispatching = False
                raise
        return fut.result()

    # -- dispatch ---------------------------------------------------------

    def _dispatch(self, batch: List[Tuple[str, object, Future]]) -> None:
        from ..observability.span import start_span

        with start_span("admin.compact_dispatch", always=True,
                        shards=len(batch), tpu=self._use_tpu):
            self._dispatch_spanned(batch)

    def _dispatch_spanned(self, batch: List[Tuple[str, object, Future]]) -> None:
        fp.hit("compact.dispatch")  # a raise must fail the batch loudly,
        # release every waiter, and keep the leader loop draining
        self.dispatch_count += 1
        self.batch_sizes.append(len(batch))
        # Deduplicate by DB identity: the same db can legally ride one
        # batch twice (back-to-back ingests), one full compaction
        # satisfies every waiter — and a duplicate would deadlock the
        # batched plan stage on the db's compaction mutex.
        futures: Dict[int, List[Future]] = {}
        by_db: Dict[int, Tuple[str, object]] = {}
        for name, db, fut in batch:
            futures.setdefault(id(db), []).append(fut)
            by_db.setdefault(id(db), (name, db))
        remaining = list(by_db.values())

        def resolve(db, result=None, exc=None) -> None:
            for fut in futures[id(db)]:
                if fut.done():
                    continue
                if exc is not None:
                    fut.set_exception(exc)
                else:
                    fut.set_result(result)

        if self._use_tpu:
            from ..tpu.compaction_service import compact_dbs_batched

            try:
                # host stages (plan/lane-read, SST write/install) fan out
                # over this pool; only the device launch is centralized
                handled, remaining = compact_dbs_batched(
                    remaining, pool=self._pool)
            except BaseException:  # launch machinery itself blew up
                log.exception("compact_dbs_batched failed; per-db fallback")
                remaining = list(by_db.values())
            # everything not handed back for per-db fallback was compacted
            rem_ids = {id(db) for _n, db in remaining}
            for _name, db in by_db.values():
                if id(db) not in rem_ids:
                    resolve(db, result=len(batch))
        # per-db fan-out: CPU backends, declined shards, single shards.
        # DBs running the adaptive compaction scheduler take its manual
        # queue (DB.schedule_compaction) so the post-ingest compaction
        # obeys the same PRIORITY order as background picks — an
        # L0-storm drain outranks it; schedule_compaction returns None
        # for engines without an adaptive compaction thread (inline
        # mode, scheduler off), which keep the direct compact_range.
        def one(name: str, db) -> None:
            try:
                fut = None
                submit = getattr(db, "schedule_compaction", None)
                if submit is not None:
                    fut = submit()
                if fut is not None:
                    fut.result()
                else:
                    db.compact_range()
                resolve(db, result=len(batch))
            except BaseException as e:
                resolve(db, exc=e)

        waits = [self._pool.submit(one, name, db) for name, db in remaining]
        for w in waits:
            w.result()

    def close(self) -> None:
        self._pool.shutdown(wait=False)
