"""ApplicationDBBackupManager: continuous incremental backups.

Reference: rocksdb_admin/application_db_backup_manager.{h,cpp} — optional
background thread periodically checkpoint-backing-up every hosted DB to the
object store (flag ``enable_async_incremental_backup_dbs``,
admin_handler.cpp:467-470).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..storage import backup as backup_mod
from ..utils.objectstore import ObjectStore
from ..utils.stats import Stats
from .db_manager import ApplicationDBManager

log = logging.getLogger(__name__)


class ApplicationDBBackupManager:
    def __init__(
        self,
        db_manager: ApplicationDBManager,
        store: ObjectStore,
        prefix: str = "incremental_backups",
        interval_sec: float = 300.0,
        parallelism: int = 8,
    ):
        self._db_manager = db_manager
        self._store = store
        self._prefix = prefix.rstrip("/")
        self._interval = interval_sec
        self._parallelism = parallelism
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="backup-manager", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def backup_all_dbs(self) -> int:
        """One pass over every hosted DB (backupAllDBsToS3). Returns the
        number successfully backed up."""
        ok = 0
        for name in self._db_manager.get_all_db_names():
            app_db = self._db_manager.get_db(name)
            if app_db is None:
                continue
            try:
                backup_mod.backup_db(
                    app_db.db, self._store, f"{self._prefix}/{name}",
                    parallelism=self._parallelism, incremental=True,
                )
                ok += 1
                Stats.get().incr("backup_manager.backups_ok")
            except Exception:
                Stats.get().incr("backup_manager.backups_failed")
                log.exception("incremental backup failed for %s", name)
        return ok

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self.backup_all_dbs()
