"""ApplicationDBBackupManager: continuous incremental backups.

Reference: rocksdb_admin/application_db_backup_manager.{h,cpp} — optional
background thread periodically checkpoint-backing-up every hosted DB to the
object store (flag ``enable_async_incremental_backup_dbs``,
admin_handler.cpp:467-470).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..observability.span import start_span
from ..storage import backup as backup_mod
from ..utils.objectstore import ObjectStore
from ..utils.stats import Stats
from .db_manager import ApplicationDBManager

log = logging.getLogger(__name__)


class ApplicationDBBackupManager:
    def __init__(
        self,
        db_manager: ApplicationDBManager,
        store: ObjectStore,
        prefix: str = "incremental_backups",
        interval_sec: float = 300.0,
        parallelism: int = 8,
        archive_wal: bool = False,
    ):
        self._db_manager = db_manager
        self._store = store
        self._prefix = prefix.rstrip("/")
        self._interval = interval_sec
        self._parallelism = parallelism
        # WAL archival rider (storage/archive.py): each backup pass also
        # ships every live WAL segment under <prefix>/<db>/wal and
        # installs the archiver as the DB's TTL-purge sink, so restores
        # can replay to ANY point since the oldest checkpoint
        # (restore_db_to_seq) — the BackupEngine-chain parity.
        self._archive_wal = archive_wal
        self._archivers: dict = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _archiver(self, db_name: str, db):
        """One archiver per (db, incarnation). A destroyed+recreated DB
        reuses WAL segment names with NEW content — a fresh incarnation
        gets a fresh archive prefix (recorded in each backup's dbmeta as
        ``wal_prefix``), so stale same-named segments can neither be
        skipped as already-shipped nor mixed into a later replay."""
        from ..storage.archive import WalArchiver

        incarnation = getattr(db, "_incarnation", "0")
        key = (db_name, incarnation)
        arch = self._archivers.get(key)
        if arch is None:
            # drop prior-incarnation entries for this db (a clear/restore
            # cycle would otherwise leak one archiver per recreate)
            for stale in [k for k in self._archivers if k[0] == db_name]:
                del self._archivers[stale]
            arch = WalArchiver(
                self._store,
                f"{self._prefix}/{db_name}/wal-{incarnation}")
            self._archivers[key] = arch
        return arch

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="backup-manager", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def backup_all_dbs(self) -> int:
        """One pass over every hosted DB (backupAllDBsToS3). Returns the
        number successfully backed up."""
        ok = 0
        for name in self._db_manager.get_all_db_names():
            app_db = self._db_manager.get_db(name)
            if app_db is None:
                continue
            try:
                # one always-sampled trace per (db, pass): the incremental
                # backup inherits the same checkpoint→upload breakdown as
                # the admin backup_db path
                with start_span("backup_manager.backup", always=True,
                                db=name):
                    self._backup_one(name, app_db)
                ok += 1
                Stats.get().incr("backup_manager.backups_ok")
            except Exception:
                Stats.get().incr("backup_manager.backups_failed")
                log.exception("incremental backup failed for %s", name)
        return ok

    def _backup_one(self, name: str, app_db) -> None:
        meta = None
        if self._archive_wal:
            # Install the purge sink BEFORE the checkpoint upload:
            # a long upload overlaps live writes, and any WAL
            # segment the engine purges during it must hit the
            # archive or PITR into that range is lost forever.
            # (One shared archiver per DB: its mutex serializes
            # the purge-time sink against this pass's shipping.)
            arch = self._archiver(name, app_db.db)
            if app_db.db.options.wal_archive_sink is None:
                app_db.db.options.wal_archive_sink = arch.sink
            meta = {"wal_prefix": arch.prefix}
        backup_mod.backup_db(
            app_db.db, self._store, f"{self._prefix}/{name}",
            parallelism=self._parallelism, incremental=True,
            meta=meta,
        )
        if self._archive_wal:
            with start_span("backup.wal_archive"):
                self._archiver(name, app_db.db).archive_live(app_db.db)

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self.backup_all_dbs()
