"""CDC admin service: change-data-capture via OBSERVER replicas.

Reference: cdc_admin/ (cdc_admin.thrift, cdc_admin_handler.{h,cpp},
cdc_application_db.cpp:15-41) — an OBSERVER is a replica that replicates
but never counts toward ACKs (replicator.thrift:63); its custom
``DbWrapper.handle_replicate_response`` publishes updates (e.g. to a
message queue) instead of persisting them. RPCs: addObserver,
removeObserver, checkObserver, getSequenceNumber.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Callable, Deque, Iterator, List, Optional, Tuple

from ..replication.db_wrapper import DbWrapper
from ..replication.replicator import Replicator
from ..replication.wire import ReplicaRole
from ..rpc.errors import RpcApplicationError
from ..storage.records import decode_batch

log = logging.getLogger(__name__)

OBSERVER_ALREADY_EXISTS = "OBSERVER_ALREADY_EXISTS"
OBSERVER_NOT_FOUND = "OBSERVER_NOT_FOUND"

# publish(db_name, start_seq, raw_batch_bytes, timestamp_ms)
Publisher = Callable[[str, int, bytes, Optional[int]], None]


class CdcDbWrapper(DbWrapper):
    """Observer-side wrapper: publishes instead of persisting
    (cdc_application_db.cpp:15-41). Tracks the applied seq in memory."""

    def __init__(self, db_name: str, start_seq: int, publisher: Publisher):
        self.db_name = db_name
        self._seq = start_seq
        self._publisher = publisher
        self._lock = threading.Lock()
        self.published_count = 0
        self.last_published_ms: Optional[int] = None

    def write_to_leader(self, batch) -> int:
        raise RpcApplicationError("NOT_LEADER", "observers do not accept writes")

    def get_updates_from_leader(self, since_seq: int) -> Iterator[Tuple[int, bytes]]:
        return iter(())  # observers never serve downstream replicas

    def latest_sequence_number(self) -> int:
        with self._lock:
            return self._seq

    def handle_replicate_response(self, raw_data: bytes, timestamp_ms) -> None:
        batch = decode_batch(raw_data)
        with self._lock:
            start_seq = self._seq + 1
        # Publish BEFORE advancing the applied seq: a publisher failure
        # leaves _seq unchanged so the pull loop re-fetches and re-publishes
        # the batch (at-least-once, never silent loss).
        self._publisher(self.db_name, start_seq, bytes(raw_data), timestamp_ms)
        with self._lock:
            self._seq += batch.count()
            self.published_count += 1
            self.last_published_ms = int(time.time() * 1000)


class MemoryPublisher:
    """Default publisher: in-memory ring buffer (a MockKafka analog for
    tests and checkObserver introspection; production plugs a queue
    producer in)."""

    def __init__(self, capacity: int = 1024):
        self.buffer: Deque[Tuple[str, int, bytes, Optional[int]]] = (
            collections.deque(maxlen=capacity)
        )
        self._lock = threading.Lock()

    def __call__(self, db_name: str, start_seq: int, raw: bytes, ts) -> None:
        with self._lock:
            self.buffer.append((db_name, start_seq, raw, ts))

    def drain(self) -> List[Tuple[str, int, bytes, Optional[int]]]:
        with self._lock:
            out = list(self.buffer)
            self.buffer.clear()
            return out


class CdcAdminHandler:
    """The CdcAdmin RPC service (cdc_admin.thrift:1-105)."""

    def __init__(
        self,
        replicator: Replicator,
        publisher: Optional[Publisher] = None,
    ):
        self.replicator = replicator
        self.publisher = publisher or MemoryPublisher()
        self._observers: dict = {}
        self._lock = threading.Lock()

    async def handle_add_observer(
        self,
        db_name: str = "",
        upstream_ip: str = "",
        upstream_port: int = 0,
        start_seq: Optional[int] = None,
    ) -> dict:
        """addObserver: start an OBSERVER replica of ``db_name`` pulling
        from upstream. ``start_seq`` None means "from the upstream's current
        position" (probed via a non-blocking replicate call)."""
        if not upstream_ip:
            raise RpcApplicationError("INVALID_UPSTREAM", "upstream required")
        # Reserve before the awaits so a concurrent duplicate gets the typed
        # error instead of a raw add_db ValueError.
        with self._lock:
            if db_name in self._observers:
                raise RpcApplicationError(OBSERVER_ALREADY_EXISTS, db_name)
            self._observers[db_name] = None  # reservation
        try:
            return await self._do_add_observer(
                db_name, upstream_ip, upstream_port, start_seq
            )
        except BaseException:
            with self._lock:
                if self._observers.get(db_name) is None:
                    self._observers.pop(db_name, None)
            raise

    async def _do_add_observer(
        self, db_name: str, upstream_ip: str, upstream_port: int,
        start_seq: Optional[int],
    ) -> dict:
        if start_seq is None:
            pool = self.replicator._pool
            client = await pool.get_client(upstream_ip, upstream_port)
            probe = await client.call(
                "replicate",
                {"db_name": db_name, "seq_no": 1 << 62, "max_wait_ms": 0,
                 "role": ReplicaRole.OBSERVER.value},
            )
            start_seq = int(probe.get("latest_seq", 0))
        wrapper = CdcDbWrapper(db_name, start_seq, self.publisher)
        rdb = self.replicator.add_db(
            db_name, wrapper, ReplicaRole.OBSERVER,
            upstream_addr=(upstream_ip, upstream_port),
        )
        with self._lock:
            self._observers[db_name] = (wrapper, rdb)
        return {"start_seq": start_seq}

    async def handle_remove_observer(self, db_name: str = "") -> dict:
        with self._lock:
            entry = self._observers.get(db_name)
            if entry is None:  # absent or still-starting reservation
                raise RpcApplicationError(OBSERVER_NOT_FOUND, db_name)
            del self._observers[db_name]
        self.replicator.remove_db(db_name)
        return {}

    async def handle_check_observer(self, db_name: str = "") -> dict:
        with self._lock:
            entry = self._observers.get(db_name)
        if entry is None:
            raise RpcApplicationError(OBSERVER_NOT_FOUND, db_name)
        wrapper, rdb = entry
        return {
            "seq_num": wrapper.latest_sequence_number(),
            "published_count": wrapper.published_count,
            "last_published_ms": wrapper.last_published_ms,
            "upstream": list(rdb.upstream_addr or ()),
        }

    async def handle_get_sequence_number(self, db_name: str = "") -> dict:
        with self._lock:
            entry = self._observers.get(db_name)
        if entry is None:
            raise RpcApplicationError(OBSERVER_NOT_FOUND, db_name)
        return {"seq_num": entry[0].latest_sequence_number()}

    def close(self) -> None:
        with self._lock:
            names = [n for n, e in self._observers.items() if e is not None]
            self._observers.clear()
        for name in names:
            try:
                self.replicator.remove_db(name)
            except KeyError:
                pass

