"""rocksplicator_tpu — a TPU-native framework for building large-scale sharded,
replicated, LSM-backed stateful services.

Re-imagines pinterest/rocksplicator (C++/Java/RocksDB/Helix) as a TPU-first
system:

- ``storage``     : LSM storage engine (WAL + memtable + TSST files) with a
                    native C++ hot path (reference L0: vendored rocksdb).
- ``replication`` : per-shard leader/follower chained replication with
                    async / semi-sync / sync ack modes (reference
                    rocksdb_replicator/).
- ``admin``       : admin data plane — backup/restore/ingest/compact RPCs
                    (reference rocksdb_admin/).
- ``cluster``     : native control plane — coordination service, state
                    machines, shard-map generation (reference
                    cluster_management/ Java+Helix, rebuilt without a JVM).
- ``tpu``         : the new part — compaction / SST bulk-ingest hot path
                    offloaded to TPU via JAX/Pallas kernels (k-way merge,
                    bloom construction, block encoding), sharded over a
                    ``jax.sharding.Mesh``.
- ``rpc``         : typed async RPC with zero-copy binary payloads
                    (reference: fbthrift header protocol).
- ``utils``       : stats, flags, timers, watchers, rate limiters, object
                    store (reference common/).
- ``models`` / ``ops`` / ``parallel``: the JAX-facing surface — the
  compaction "model", its kernels, and mesh-sharding helpers.
"""

__version__ = "0.1.0"

# Arm the lock-order watchdog from the environment BEFORE any package
# module constructs a lock (module-level locks are created at their
# module's import, which necessarily follows this one). Zero-cost when
# RSTPU_LOCKWATCH is unset: nothing is imported beyond the tiny module
# and nothing is patched. Chaos-harness child processes inherit the env
# and arm themselves through this same line.
import os as _os

if _os.environ.get("RSTPU_LOCKWATCH"):
    from .testing import lockwatch as _lockwatch

    _lockwatch.maybe_install()
