"""The stateless compaction worker.

A worker owns no shard state: it scans the job ledger, claims one job,
downloads the immutable input SSTs from the object store (verifying
each sha256 against the job manifest), runs the same merge pipeline the
engine would have run locally — ``direct_merge_runs_to_files``, which
routes large inputs through the round-17 bounded-memory streaming merge
under ``RSTPU_COMPACT_MEM_BUDGET`` and small ones through the in-RAM
subcompacting path — uploads the outputs with fresh checksums, and
posts a result manifest. Byte-identical to the local path by
construction: both sides call the identical merge code with the
identical parameters from the job record.

Liveness is a heartbeat node the worker re-stamps while merging; the
publishing leader reaps the claim when the heartbeat goes stale, which
republishes the job for the next worker (or times out into local
fallback). A worker crash therefore leaks nothing but garbage objects,
which the leader's cleanup sweeps by job-id prefix.

The merge backend defaults to the native CPU pipeline; set
``RSTPU_COMPACT_WORKER_BACKEND=tpu`` to use the vmapped TPU backend —
one accelerator worker host then naturally serves many shards'
compactions, which is the silicon story this tier exists for.

``tools/compaction_worker.py`` is the CLI shell around this module.
"""

from __future__ import annotations

import logging
import os
import shutil
import socket
import threading
import time
import uuid
from typing import List, Optional, Tuple

from ..storage.merge import MERGE_OPERATORS
from ..storage.sst import SSTReader, SSTWriter
from ..testing import failpoints as fp
from ..utils.objectstore import build_object_store
from ..utils.stats import Stats, tagged
from .jobs import CompactionJob, JobResult, file_checksum
from .queue import CompactionJobQueue

log = logging.getLogger(__name__)


class ChecksumMismatch(Exception):
    pass


def _build_backend(name: Optional[str]):
    """Resolve the merge backend. "tpu" gates on an importable jax —
    the worker container may be CPU-only, in which case it degrades to
    the native CPU pipeline rather than refusing jobs."""
    name = (name or os.environ.get("RSTPU_COMPACT_WORKER_BACKEND")
            or "cpu").lower()
    if name == "tpu":
        try:
            from ..tpu.backend import TpuCompactionBackend

            return TpuCompactionBackend()
        except Exception:
            log.warning("TPU backend unavailable; worker using CPU merge")
    from ..storage.native_compaction import NativeCompactionBackend

    return NativeCompactionBackend()


def merge_job_to_files(job: CompactionJob, input_paths: List[str],
                       out_dir: str, backend=None
                       ) -> List[Tuple[str, str]]:
    """Run the job's merge over already-fetched local input SSTs.
    Returns [(local_path, sha256)] in output order. Engine-free twin of
    ``DB._write_merged``: same direct pipeline, same tuple-path
    fallback, parameters from the job record instead of DBOptions."""
    backend = backend if backend is not None else _build_backend(None)
    merge_op = None
    if job.merge_operator:
        op_cls = MERGE_OPERATORS.get(job.merge_operator)
        if op_cls is None:
            raise ValueError(f"unknown merge operator {job.merge_operator}")
        merge_op = op_cls()
    readers = [SSTReader(p) for p in input_paths]
    allocated: List[str] = []

    def path_factory() -> str:
        path = os.path.join(out_dir,
                            f"{job.job_id}-{len(allocated):06d}.sst")
        allocated.append(path)
        return path

    outputs = None
    direct = getattr(backend, "merge_runs_to_files", None)
    if direct is not None:
        kwargs = {}
        if getattr(backend, "supports_subcompactions", False):
            kwargs["max_subcompactions"] = 1
            kwargs["io_budget"] = None
        if getattr(backend, "supports_memory_budget", False):
            kwargs["memory_budget_bytes"] = job.memory_budget_bytes
        try:
            outputs = direct(
                readers, merge_op, job.drop_tombstones, path_factory,
                job.block_bytes, job.compression, job.bits_per_key,
                job.target_file_bytes, **kwargs)
        except Exception:
            log.exception("worker direct merge failed; using tuple path")
            outputs = None
    if outputs is None:
        stream = backend.merge_runs(
            [r.iterate() for r in readers], merge_op, job.drop_tombstones)
        paths: List[str] = []
        writer: Optional[SSTWriter] = None
        written = 0
        for key, seq, vtype, value in stream:
            if writer is None:
                path = path_factory()
                paths.append(path)
                writer = SSTWriter(path, job.block_bytes, job.compression,
                                   job.bits_per_key)
                written = 0
            writer.add(key, seq, vtype, value)
            written += len(key) + len(value)
            if written >= job.target_file_bytes:
                writer.finish()
                writer = None
        if writer is not None:
            writer.finish()
        outputs = [(p, {}) for p in paths]
    return [(path, file_checksum(path)) for path, _props in outputs]


class CompactionWorker:
    """Claim → fetch → merge → upload → result, one job at a time."""

    def __init__(self, coord, workdir: str, worker_id: Optional[str] = None,
                 backend=None, poll_interval: float = 0.2,
                 heartbeat_interval: float = 1.0):
        self._coord = coord
        self._queue = CompactionJobQueue(coord)
        self._workdir = workdir
        self.worker_id = worker_id or \
            f"{socket.gethostname()}-{uuid.uuid4().hex[:8]}"
        self._backend = backend
        self._poll_interval = poll_interval
        self._heartbeat_interval = heartbeat_interval
        self.jobs_done = 0
        self.jobs_failed = 0

    # -- loop ----------------------------------------------------------

    def run_once(self) -> bool:
        """Claim and process at most one job; True when one was taken."""
        for db in self._queue.list_open_jobs():
            try:
                job = self._queue.claim(db, self.worker_id)
            except Exception:
                log.exception("claim failed for %s", db)
                continue
            if job is None:
                continue  # duplicate claim loses; scan on
            self._process(job)
            return True
        return False

    def serve_forever(self, stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                if not self.run_once():
                    stop.wait(self._poll_interval)
            except Exception:
                log.exception("worker loop error")
                stop.wait(self._poll_interval)

    # -- one job -------------------------------------------------------

    def _process(self, job: CompactionJob) -> None:
        db = job.db_name
        stop_hb = threading.Event()
        hb = threading.Thread(
            target=self._heartbeat_loop, args=(db, stop_hb),
            name=f"compact-hb-{db}", daemon=True)
        hb.start()
        job_dir = os.path.join(self._workdir, job.job_id)
        try:
            os.makedirs(job_dir, exist_ok=True)
            store = build_object_store(job.store_uri)
            input_paths = []
            for inp in job.inputs:
                # data plane: bytes enter the worker. A checksum
                # mismatch here means the store lied — fail the job,
                # the leader falls back to the local merge.
                fp.hit("compact.remote.fetch")
                local = os.path.join(job_dir, inp["name"])
                store.get_object(inp["key"], local)
                got = file_checksum(local)
                if got != inp["checksum"]:
                    raise ChecksumMismatch(
                        f"{inp['name']}: fetched {got[:12]} != "
                        f"manifest {inp['checksum'][:12]}")
                input_paths.append(local)
            out_dir = os.path.join(job_dir, "out")
            os.makedirs(out_dir, exist_ok=True)
            merged = merge_job_to_files(
                job, input_paths, out_dir, backend=self._backend)
            outputs = []
            for path, checksum in merged:
                # data plane: bytes leave the worker whole-file; the
                # leader re-verifies this sha256 before install
                fp.hit("compact.remote.upload")
                name = os.path.basename(path)
                key = f"compactions/{db}/{job.job_id}/out/{name}"
                store.put_object(path, key)
                outputs.append({
                    "name": name, "key": key, "checksum": checksum,
                    "bytes": os.path.getsize(path),
                })
            self._queue.post_result(JobResult(
                job_id=job.job_id, db_name=db, epoch=job.epoch,
                worker_id=self.worker_id, status="done", outputs=outputs,
                finished_ms=int(time.time() * 1000)))
            self.jobs_done += 1
            Stats.get().incr(tagged("compaction.remote.worker_done",
                                    worker=self.worker_id))
        except Exception as e:
            self.jobs_failed += 1
            log.exception("job %s failed on %s", job.job_id, self.worker_id)
            try:
                self._queue.post_result(JobResult(
                    job_id=job.job_id, db_name=db, epoch=job.epoch,
                    worker_id=self.worker_id, status="failed",
                    error=f"{type(e).__name__}: {e}",
                    finished_ms=int(time.time() * 1000)))
            except Exception:
                # can't even post: the heartbeat stops below, so the
                # leader reaps on expiry — same terminal state as a kill
                log.debug("failed-result post failed", exc_info=True)
        finally:
            stop_hb.set()
            hb.join(timeout=5.0)
            shutil.rmtree(job_dir, ignore_errors=True)

    def _heartbeat_loop(self, db: str, stop: threading.Event) -> None:
        while not stop.wait(self._heartbeat_interval):
            try:
                self._queue.heartbeat(db)
            except Exception:
                # a wedged coordinator just makes us look dead; the
                # leader reaps and republishes — safe, merely wasteful
                log.debug("heartbeat failed for %s", db, exc_info=True)
