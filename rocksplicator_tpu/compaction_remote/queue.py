"""The durable compaction job ledger, coordinator-backed.

Layout (all under one base, default ``/compactions``)::

    /compactions/<db>            job node — value: CompactionJob JSON
    /compactions/<db>/claim      ephemeral — value: worker_id
    /compactions/<db>/heartbeat  worker-stamped ms wall clock
    /compactions/<db>/result     JobResult JSON
    /compactions_summary         cluster-lifetime counters (best-effort)

The job node doubles as the one-job-per-db lock: ``create`` is the
atomic publish, and a second publish while one is in flight hits
NODE_EXISTS → :class:`JobInFlightError` (the same create-as-lock the
shard-move ledger uses for one-mover-per-partition). The claim node is
ephemeral and created with ``create`` too, so exactly one worker wins a
job — the loser's create raises NODE_EXISTS — and a killed worker's
claim evaporates with its session. Reaping a dead worker's claim
(leader-side, on heartbeat expiry) deletes only the claim/heartbeat/
result children and leaves the job node, which IS the republish: the
job reappears in every worker's ``list_open_jobs`` scan.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Dict, List, Optional, Tuple

from ..rpc.errors import RpcApplicationError
from ..testing import failpoints as fp
from ..utils.stats import Stats, tagged
from .jobs import CompactionJob, JobResult

log = logging.getLogger(__name__)

NO_NODE = "NO_NODE"
NODE_EXISTS = "NODE_EXISTS"
BAD_VERSION = "BAD_VERSION"

BASE_PATH = "/compactions"
SUMMARY_PATH = "/compactions_summary"


class JobInFlightError(Exception):
    """A job for this db is already published (one-job-per-db lock)."""


def _now_ms() -> int:
    return int(time.time() * 1000)


class CompactionJobQueue:
    """Leader- and worker-side operations on the job ledger. Thin and
    stateless by design: every method round-trips the coordinator, so a
    queue object can be rebuilt from nothing after any crash."""

    def __init__(self, coord, base: str = BASE_PATH,
                 summary: str = SUMMARY_PATH):
        self._coord = coord
        self._base = base.rstrip("/") or BASE_PATH
        self._summary = summary

    # -- paths ---------------------------------------------------------

    def _job(self, db: str) -> str:
        return f"{self._base}/{db}"

    # -- leader side ---------------------------------------------------

    def publish(self, job: CompactionJob) -> None:
        """Atomically publish ``job``; raises :class:`JobInFlightError`
        when one is already open for the db."""
        # control plane: leader hands the pick to the tier. A fault here
        # is absorbed by maybe_offload's local fallback.
        fp.hit("compact.remote.publish")
        self._coord.ensure(self._base)
        for attempt in (0, 1):
            try:
                self._coord.create(self._job(job.db_name), job.encode())
                break
            except RpcApplicationError as e:
                if e.code != NODE_EXISTS:
                    raise
                # the coordinator auto-creates missing parents, so a
                # dead worker's late heartbeat/result put can resurrect
                # the job path as an EMPTY husk after a sweep. A husk
                # (no decodable job value) is garbage, not a lock —
                # reclaim it and retry once; a real job stays a lock.
                if attempt == 0 and self.get_job(job.db_name) is None:
                    self._coord.delete_if_exists(
                        self._job(job.db_name), recursive=True)
                    continue
                raise JobInFlightError(job.db_name) from e
        self.bump_summary("published")
        Stats.get().incr(
            tagged("compaction.remote.published", db=job.db_name))

    def get_job(self, db: str) -> Optional[CompactionJob]:
        raw = self._coord.get_or_none(self._job(db))
        if raw is None:
            return None
        try:
            return CompactionJob.decode(raw)
        except (ValueError, TypeError, UnicodeDecodeError):
            log.warning("undecodable job node for %s", db)
            return None

    def get_result(self, db: str) -> Optional[JobResult]:
        raw = self._coord.get_or_none(f"{self._job(db)}/result")
        if raw is None:
            return None
        try:
            return JobResult.decode(raw)
        except (ValueError, TypeError, UnicodeDecodeError):
            log.warning("undecodable result node for %s", db)
            return None

    def claim_holder(self, db: str) -> Optional[str]:
        raw = self._coord.get_or_none(f"{self._job(db)}/claim")
        return bytes(raw).decode("utf-8", "replace") if raw is not None \
            else None

    def heartbeat_age_ms(self, db: str) -> Optional[int]:
        """ms since the claiming worker's last heartbeat; None when no
        heartbeat has landed yet."""
        raw = self._coord.get_or_none(f"{self._job(db)}/heartbeat")
        if raw is None:
            return None
        try:
            return max(0, _now_ms() - int(bytes(raw).decode()))
        except ValueError:
            return None

    def reap_claim(self, db: str) -> None:
        """Leader-side: evict a dead worker's claim. The job node stays,
        so the very next worker scan re-offers the job — this IS the
        republish after heartbeat expiry."""
        for child in ("claim", "heartbeat", "result"):
            self._coord.delete_if_exists(f"{self._job(db)}/{child}")
        self.bump_summary("reaped")
        Stats.get().incr(tagged("compaction.remote.reaped", db=db))

    def remove(self, db: str) -> None:
        """Retire the ledger entry (install done, fenced, or fallback)."""
        self._coord.delete_if_exists(self._job(db), recursive=True)

    # -- worker side ---------------------------------------------------

    def list_open_jobs(self) -> List[str]:
        """db names with a published, unclaimed job."""
        open_jobs = []
        for db in self._coord.list(self._base):
            if self._coord.get_or_none(f"{self._base}/{db}/claim") is None:
                open_jobs.append(db)
        return open_jobs

    def claim(self, db: str, worker_id: str) -> Optional[CompactionJob]:
        """Atomically claim the job for ``db``; None when another worker
        won (duplicate claim loses on NODE_EXISTS) or the job vanished."""
        # data plane handoff: a duplicate claim must lose, never corrupt
        fp.hit("compact.remote.claim")
        job = self.get_job(db)
        if job is None:
            return None
        try:
            self._coord.create(
                f"{self._job(db)}/claim", worker_id.encode("utf-8"),
                ephemeral=True)
        except RpcApplicationError as e:
            if e.code in (NODE_EXISTS, NO_NODE):
                return None  # lost the race, or job retired under us
            raise
        try:
            self.heartbeat(db)
        except Exception:
            # the claim is already held — abandoning it here would wedge
            # the job until the leader reaps. The worker's heartbeat
            # loop stamps liveness momentarily; a worker that dies first
            # is reaped on the no-heartbeat timeout.
            log.debug("claim-time heartbeat failed for %s", db,
                      exc_info=True)
        self.bump_summary("claimed")
        Stats.get().incr(tagged("compaction.remote.claimed", db=db))
        return job

    def heartbeat(self, db: str) -> None:
        """Stamp worker liveness; the leader reaps the claim when this
        goes stale (worker died mid-job)."""
        fp.hit("compact.remote.heartbeat")
        self._coord.put(f"{self._job(db)}/heartbeat",
                        str(_now_ms()).encode())

    def post_result(self, result: JobResult) -> None:
        self._coord.put(f"{self._job(result.db_name)}/result",
                        result.encode())

    # -- observability -------------------------------------------------

    def bump_summary(self, key: str) -> None:
        """Best-effort read-modify-write on the cluster-lifetime
        counters — same lost-update tolerance as the move ledger's
        moves_summary: the counters are operator telemetry, not
        correctness state."""
        try:
            raw = self._coord.get_or_none(self._summary)
            counters: Dict[str, int] = {}
            if raw:
                try:
                    counters = json.loads(bytes(raw).decode())
                except (ValueError, UnicodeDecodeError):
                    counters = {}
            counters[key] = int(counters.get(key, 0)) + 1
            self._coord.put(self._summary,
                            json.dumps(counters, sort_keys=True).encode())
        except Exception:
            log.debug("compactions_summary bump failed", exc_info=True)

    def read_summary(self) -> Dict[str, int]:
        raw = self._coord.get_or_none(self._summary)
        if not raw:
            return {}
        try:
            return {k: int(v)
                    for k, v in json.loads(bytes(raw).decode()).items()}
        except (ValueError, UnicodeDecodeError, AttributeError):
            return {}

    def active_jobs(self) -> Dict[str, dict]:
        """Per-db live job state for /cluster_stats: phase, worker,
        heartbeat age, epoch. One ledger scan, read-only."""
        out: Dict[str, dict] = {}
        for db in self._coord.list(self._base):
            job = self.get_job(db)
            if job is None:
                continue
            holder = self.claim_holder(db)
            result = self.get_result(db)
            if result is not None:
                phase = "done" if result.status == "done" else "failed"
            elif holder is not None:
                phase = "claimed"
            else:
                phase = "published"
            out[db] = {
                "job_id": job.job_id,
                "epoch": job.epoch,
                "phase": phase,
                "worker": holder,
                "heartbeat_age_ms": self.heartbeat_age_ms(db),
                "input_bytes": job.input_bytes,
            }
        return out
