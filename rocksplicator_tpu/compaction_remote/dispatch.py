"""Remote dispatch policy — which picks offload, and the patience knobs.

All env-tunable (README Tuning table), defaults chosen so the tier is
strictly opt-in and never blocks serving:

- ``RSTPU_COMPACT_REMOTE``            enable ("1"/"true"/"on")
- ``RSTPU_COMPACT_REMOTE_FLOOR``      min input bytes to offload (8 MiB);
  below the floor the local merge is cheaper than two object-store trips
- ``RSTPU_COMPACT_REMOTE_DEADLINE``   whole-job deadline seconds (120)
- ``RSTPU_COMPACT_REMOTE_CLAIM_WAIT`` seconds to wait for any worker to
  claim before falling back locally (5)
- ``RSTPU_COMPACT_REMOTE_HB_TIMEOUT`` heartbeat staleness that declares
  a claiming worker dead → claim reaped, job republished (10)
- ``RSTPU_COMPACT_COORD``             coordinator endpoint host:port the
  worker CLI connects to
- ``RSTPU_COMPACT_REMOTE_STORE``      object store URI for job transfer
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Tuple

_TRUTHY = ("1", "true", "on", "yes")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


@dataclass
class RemoteDispatchPolicy:
    enabled: bool = False
    size_floor_bytes: int = 8 << 20
    deadline_s: float = 120.0
    claim_wait_s: float = 5.0
    heartbeat_timeout_s: float = 10.0
    poll_interval_s: float = 0.05

    @classmethod
    def from_env(cls) -> "RemoteDispatchPolicy":
        return cls(
            enabled=os.environ.get(
                "RSTPU_COMPACT_REMOTE", "").lower() in _TRUTHY,
            size_floor_bytes=_env_int("RSTPU_COMPACT_REMOTE_FLOOR", 8 << 20),
            deadline_s=_env_float("RSTPU_COMPACT_REMOTE_DEADLINE", 120.0),
            claim_wait_s=_env_float("RSTPU_COMPACT_REMOTE_CLAIM_WAIT", 5.0),
            heartbeat_timeout_s=_env_float(
                "RSTPU_COMPACT_REMOTE_HB_TIMEOUT", 10.0),
        )


def coord_endpoint_from_env() -> Optional[Tuple[str, int]]:
    """Parse ``RSTPU_COMPACT_COORD`` ("host:port") for the worker CLI."""
    raw = os.environ.get("RSTPU_COMPACT_COORD", "").strip()
    if not raw or ":" not in raw:
        return None
    host, _, port = raw.rpartition(":")
    try:
        return host, int(port)
    except ValueError:
        return None


def store_uri_from_env() -> Optional[str]:
    return os.environ.get("RSTPU_COMPACT_REMOTE_STORE") or None


def attach_from_env(ledger_name: str, engine, epoch_provider):
    """Serving-node wiring (Replicator.add_db): attach a
    :class:`RemoteCompactionManager` to a shard's engine when the
    environment opts in — ``RSTPU_COMPACT_REMOTE`` truthy AND both
    ``RSTPU_COMPACT_COORD`` and ``RSTPU_COMPACT_REMOTE_STORE`` set.
    Returns the manager (orphan jobs already recovered, hook installed)
    or None. ``ledger_name`` must be unique per REPLICA, not per shard
    — every replica runs its own background compaction, and two
    replicas sharing a ledger key would fight over the one-job lock and
    sweep each other's live jobs. The manager owns the coordinator
    client it opens here; ``detach`` closes it."""
    import logging

    policy = RemoteDispatchPolicy.from_env()
    if not policy.enabled:
        return None
    endpoint = coord_endpoint_from_env()
    store_uri = store_uri_from_env()
    if endpoint is None or store_uri is None:
        logging.getLogger(__name__).warning(
            "RSTPU_COMPACT_REMOTE set but RSTPU_COMPACT_COORD / "
            "RSTPU_COMPACT_REMOTE_STORE missing; remote compaction "
            "stays off for %s", ledger_name)
        return None
    from ..cluster.coordinator import CoordinatorClient
    from .install import RemoteCompactionManager

    client = CoordinatorClient(*endpoint)
    try:
        mgr = RemoteCompactionManager(
            ledger_name, engine, client, store_uri, policy=policy,
            epoch_provider=epoch_provider)
        # recover-then-serve: sweep any orphaned job a crashed
        # predecessor of this replica left in the ledger
        mgr.recover()
    except Exception:
        client.close()
        raise
    mgr.owned_coord = client
    engine.set_remote_compactor(mgr)
    return mgr


def detach(engine, mgr) -> None:
    """Undo :func:`attach_from_env`: unhook the engine and close the
    coordinator client the attach opened. Safe on a None manager."""
    if mgr is None:
        return
    try:
        engine.set_remote_compactor(None)
    except Exception:
        pass
    client = getattr(mgr, "owned_coord", None)
    if client is not None:
        try:
            client.close()
        except Exception:
            pass
