"""Disaggregated compaction worker tier (round 18).

Leaders publish compaction jobs into a durable coordinator-backed
ledger (``/compactions/<db>``); stateless workers claim exactly one job
at a time, fetch the immutable input SSTs from the object store, run
the round-17 bounded-memory streaming merge, and upload output SSTs
plus a checksummed result manifest. The publishing leader verifies
checksums and installs the new generation atomically through the
engine's existing ``plan_full_compaction`` / ``install_full_compaction``
seams — rejecting any result whose job epoch is stale, so a deposed
leader's in-flight job can never install (the round-11 fencing rule
extended to compaction). Serving correctness never depends on the tier
being up: if no worker claims within the claim window, a worker dies
mid-job (heartbeat expiry), a checksum mismatches, or the deadline
passes, the pick falls back to the unchanged local compaction path.

Module map:

- :mod:`.jobs`     — job / result codecs + sha256 file manifests
- :mod:`.queue`    — the coordinator ledger (publish/claim/heartbeat/result)
- :mod:`.worker`   — the stateless merge worker (``tools/compaction_worker``)
- :mod:`.install`  — leader-side publish → await → verify → fenced install
- :mod:`.dispatch` — env-knob dispatch policy (``RSTPU_COMPACT_REMOTE``)
"""

from .dispatch import RemoteDispatchPolicy
from .install import RemoteCompactionManager
from .jobs import CompactionJob, JobResult, file_checksum
from .queue import CompactionJobQueue, JobInFlightError
from .worker import CompactionWorker

__all__ = [
    "CompactionJob",
    "CompactionJobQueue",
    "CompactionWorker",
    "JobInFlightError",
    "JobResult",
    "RemoteCompactionManager",
    "RemoteDispatchPolicy",
    "file_checksum",
]
