"""Compaction job / result codecs.

A job is the complete, self-contained description of one full
compaction: the immutable input SSTs (object-store keys + sha256
manifests), the merge parameters the publishing engine would have used
locally, and the publishing leader's epoch. A worker needs nothing
else — no engine, no manifest, no WAL — which is what makes the tier
stateless. Results carry per-file sha256 checksums so the leader can
verify every byte before the generation installs.

JSON encoding mirrors :class:`~..cluster.shard_move.MoveRecord`: the
records live as coordinator node values and must survive leader
restarts and version skew (unknown fields are dropped on decode).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import asdict, dataclass, field
from typing import List, Optional


def file_checksum(path: str) -> str:
    """sha256 hex digest of a file, streamed in 1 MiB chunks — input and
    output SSTs cross the object store whole, so a whole-file digest
    (not the engine's per-block polynomial checksum) is the transfer
    integrity seal."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            h.update(chunk)
    return h.hexdigest()


def _decode_fields(cls, raw: bytes):
    data = json.loads(bytes(raw).decode("utf-8"))
    fields = {f for f in cls.__dataclass_fields__}
    return cls(**{k: v for k, v in data.items() if k in fields})


@dataclass
class CompactionJob:
    """One published full compaction. ``inputs`` entries are dicts of
    ``{"name", "key", "checksum", "bytes"}`` — SST file name in the
    source DB, object-store key, sha256, and size."""

    job_id: str
    db_name: str
    epoch: int
    store_uri: str
    inputs: List[dict] = field(default_factory=list)
    bottom: int = 0
    drop_tombstones: bool = True
    merge_operator: Optional[str] = None
    block_bytes: int = 32 * 1024
    compression: int = 1
    bits_per_key: int = 10
    target_file_bytes: int = 64 * 1024 * 1024
    memory_budget_bytes: int = 0
    deadline_ms: int = 0
    published_ms: int = 0

    def encode(self) -> bytes:
        return json.dumps(asdict(self), sort_keys=True).encode("utf-8")

    @classmethod
    def decode(cls, raw: bytes) -> "CompactionJob":
        return _decode_fields(cls, raw)

    @property
    def input_bytes(self) -> int:
        return sum(int(i.get("bytes", 0)) for i in self.inputs)


@dataclass
class JobResult:
    """A worker's completion manifest. ``outputs`` entries are dicts of
    ``{"name", "key", "checksum", "bytes"}``; an empty list with
    ``status == "done"`` means the merge compacted everything away
    (all-tombstoned), which installs as an empty generation."""

    job_id: str
    db_name: str
    epoch: int
    worker_id: str
    status: str = "done"  # "done" | "failed"
    error: Optional[str] = None
    outputs: List[dict] = field(default_factory=list)
    finished_ms: int = 0

    def encode(self) -> bytes:
        return json.dumps(asdict(self), sort_keys=True).encode("utf-8")

    @classmethod
    def decode(cls, raw: bytes) -> "JobResult":
        return _decode_fields(cls, raw)
