"""Leader-side remote dispatch: publish → await → verify → fenced install.

``RemoteCompactionManager.maybe_offload`` is the engine's hook point
(``DB.set_remote_compactor``). It runs INSIDE the background compaction
thread, between the scheduler's pick and the local compaction dispatch,
and returns a tri-state the loop acts on:

- ``"installed"`` — the worker's generation installed atomically; the
  pick is satisfied, local compaction must not run.
- ``"declined"``  — the tier didn't handle it (disabled, below the size
  floor, nothing to compact, no claim, worker death past the deadline,
  checksum mismatch, any publish/transfer fault). The plan's mutex is
  released and the UNCHANGED local path runs — this is the automatic
  fallback, so serving correctness never depends on the tier.
- ``"fenced"``    — the job's epoch went stale while in flight: this
  leader was deposed. The result is discarded AND no local fallback
  runs — a deposed leader must not compact either; the loop surfaces
  the fencing error to manual waiters and re-picks (by which point the
  deposed node has resynced or stopped serving).

The epoch gate is the round-11 fencing rule extended to compaction.
Jobs are stamped with the leader's epoch at publish; at install time
the CURRENT epoch is re-read and compared by :func:`_epoch_is_current`
— a module-level function precisely so the chaos harness's
``--break-guard remote_install`` tooth can patch it out and prove the
deposed-leader install is otherwise caught.

Locking (narrowed in round 19): the remote round trip runs off a
MUTEX-FREE snapshot (``engine.snapshot_full_compaction``) — the
shard's compaction mutex is won only for the final verify+install
(``engine.begin_full_install``, which revalidates the snapshot's
inputs are still live), so local picks never wait behind a slow
worker. Crash safety: a leader killed mid-job leaves only a ledger
entry plus garbage objects — ``recover()`` (called on reopen, before
serving) sweeps both; nothing can install because the install-time
mutex+revalidation gate is process-local state. Re-install after a
leader restart is therefore idempotent by construction: the restarted
leader sweeps the old job and re-plans from its reopened manifest.
"""

from __future__ import annotations

import logging
import time
import uuid
from typing import List, Optional

from ..testing import failpoints as fp
from ..utils.objectstore import build_object_store
from ..utils.stats import Stats, tagged
from .dispatch import RemoteDispatchPolicy
from .jobs import CompactionJob, file_checksum
from .queue import CompactionJobQueue, JobInFlightError

log = logging.getLogger(__name__)

# the scheduler's pressure-driven background picks; manual full
# compactions keep the local compact_range path (they carry futures and
# want synchronous completion semantics)
OFFLOADABLE_KINDS = ("l0", "level")


class FencedInstallError(Exception):
    """The publishing leader's epoch went stale mid-job; the result was
    discarded and no compaction (remote or local) ran for this pick."""


def _epoch_is_current(job_epoch: int, current_epoch: int) -> bool:
    """The fencing gate: a result may install only when no higher epoch
    has been minted since the job was published. Module-level and
    patchable on purpose — the ``remote_install`` chaos tooth breaks
    exactly this predicate to prove the harness catches a deposed
    leader's install."""
    return int(current_epoch) <= int(job_epoch)


class RemoteCompactionManager:
    """One per served DB on the leader. Thread-compat with the engine's
    single background compaction thread: maybe_offload is only ever
    called from there, one pick at a time."""

    def __init__(self, db_name: str, db, coord, store_uri: str,
                 policy: Optional[RemoteDispatchPolicy] = None,
                 epoch_provider=None):
        self.db_name = db_name
        self._db = db
        self._queue = CompactionJobQueue(coord)
        self._store_uri = store_uri
        self._store = build_object_store(store_uri)
        self.policy = policy or RemoteDispatchPolicy.from_env()
        self._epoch = epoch_provider or (lambda: 0)
        # in-process counters mirrored to Stats; cluster-lifetime ones
        # live in the ledger's summary node
        self.installed = 0
        self.failed_over = 0
        self.fenced = 0
        self.republished = 0

    # -- the engine hook ----------------------------------------------

    def maybe_offload(self, pick) -> str:
        if not self.policy.enabled:
            return "declined"
        if getattr(pick, "kind", None) not in OFFLOADABLE_KINDS:
            return "declined"
        # Snapshot WITHOUT the compaction mutex (round 19): the leader
        # holds the shard's mutex only for the final verify+install, so
        # local L0 picks and manual compact_range are never serialized
        # behind a slow worker's whole publish→claim→merge→download
        # round trip. The snapshot is revalidated under the mutex at
        # install time (engine.begin_full_install); a concurrent local
        # compaction that consumed an input makes the remote result
        # STALE — it is discarded, the local outcome stands. A GC'd
        # input mid-upload surfaces as an IO error here and falls back
        # locally; correctness never depends on the race.
        plan = self._db.snapshot_full_compaction()
        if plan is None:
            return "declined"
        job_id = uuid.uuid4().hex[:16]
        # install_full_compaction consumes the mutex won by
        # begin_full_install even when it raises; ``consumed`` tracks
        # whether the install phase owns it (no mutex is held anywhere
        # else anymore)
        consumed = {"plan": False}
        try:
            input_bytes = sum(r.file_size for r in plan["runs"])
            if input_bytes < self.policy.size_floor_bytes:
                return "declined"
            job = self._publish(plan, job_id, input_bytes)
            outcome = self._await_and_install(plan, job, consumed)
        except FencedInstallError as e:
            log.warning("%s: %s", self.db_name, e)
            self._sweep_job(job_id)
            self.fenced += 1
            self._queue.bump_summary("fenced")
            Stats.get().incr(
                tagged("compaction.remote.fenced", db=self.db_name))
            return "fenced"
        except Exception:
            log.exception("%s: remote compaction failed over to local",
                          self.db_name)
            self._sweep_job(job_id)
            if not consumed["plan"]:
                self._note_failover()
                return "declined"
            # the swap died inside install_full_compaction itself — the
            # pick was half-applied territory; surface to the bg loop
            raise
        if outcome != "installed":
            self._sweep_job(job_id)
            self._note_failover()
            return "declined"
        return "installed"

    # -- phases --------------------------------------------------------

    def _publish(self, plan: dict, job_id: str,
                 input_bytes: int) -> CompactionJob:
        opts = self._db.options
        inputs = []
        for name, reader in zip(plan["inputs"], plan["runs"]):
            path = f"{self._db.path}/{name}"
            key = f"compactions/{self.db_name}/{job_id}/in/{name}"
            self._store.put_object(path, key)
            inputs.append({
                "name": name, "key": key,
                "checksum": file_checksum(path),
                "bytes": reader.file_size,
            })
        merge_op = opts.merge_operator
        job = CompactionJob(
            job_id=job_id, db_name=self.db_name, epoch=int(self._epoch()),
            store_uri=self._store_uri, inputs=inputs,
            bottom=plan["bottom"], drop_tombstones=plan["drop_tombstones"],
            merge_operator=getattr(merge_op, "name", None),
            block_bytes=opts.block_bytes, compression=opts.compression,
            bits_per_key=opts.bits_per_key,
            target_file_bytes=opts.target_file_bytes,
            memory_budget_bytes=opts.compaction_memory_budget_bytes,
            deadline_ms=int(self.policy.deadline_s * 1000),
            published_ms=int(time.time() * 1000),
        )
        try:
            self._queue.publish(job)
        except JobInFlightError:
            # a ghost entry from a crashed predecessor on this db —
            # sweep it (nothing can install it: no plan is held) and
            # fall back locally this round
            log.warning("%s: stale job ledger entry; sweeping", self.db_name)
            self.recover()
            raise
        return job

    def _await_and_install(self, plan: dict, job: CompactionJob,
                           consumed: dict) -> str:
        deadline = time.monotonic() + self.policy.deadline_s
        claim_deadline = time.monotonic() + self.policy.claim_wait_s
        claim_seen_at = None
        while True:
            result = self._queue.get_result(job.db_name)
            if result is not None and result.job_id == job.job_id:
                if result.status != "done":
                    log.warning("%s: worker %s failed job %s: %s",
                                self.db_name, result.worker_id,
                                result.job_id, result.error)
                    return "failed"
                return self._install(plan, job, result, consumed)
            now = time.monotonic()
            if now >= deadline:
                return "deadline"
            holder = self._queue.claim_holder(job.db_name)
            if holder is None:
                claim_seen_at = None
                if now >= claim_deadline:
                    return "unclaimed"
            else:
                if claim_seen_at is None:
                    claim_seen_at = now
                age = self._queue.heartbeat_age_ms(job.db_name)
                if age is None:
                    # claimed but no heartbeat node ever landed — count
                    # staleness from when we first saw the claim, else a
                    # worker killed pre-first-heartbeat never gets reaped
                    age = (now - claim_seen_at) * 1000
                if age > self.policy.heartbeat_timeout_s * 1000:
                    # worker died mid-job: evict the claim; the job node
                    # stays published = republished for the next worker
                    log.warning("%s: reaping dead worker %s (hb %dms)",
                                self.db_name, holder, age)
                    self._queue.reap_claim(job.db_name)
                    self.republished += 1
                    self._queue.bump_summary("republished")
                    claim_deadline = now + self.policy.claim_wait_s
            time.sleep(self.policy.poll_interval_s)

    def _install(self, plan: dict, job: CompactionJob, result,
                 consumed: dict) -> str:
        # fencing FIRST: a deposed leader must not even download, let
        # alone install — and must not run the local fallback either
        if not _epoch_is_current(job.epoch, int(self._epoch())):
            raise FencedInstallError(
                f"job epoch {job.epoch} stale "
                f"(current {int(self._epoch())}) — result discarded")
        local_names: List[str] = []
        try:
            for out in result.outputs:
                name, path = self._db.allocate_sst()
                # track before verifying so a mismatching download is
                # itself swept by the except below
                local_names.append(name)
                self._store.get_object(out["key"], path)
                got = file_checksum(path)
                if got != out["checksum"]:
                    raise IOError(
                        f"{out['name']}: downloaded {got[:12]} != "
                        f"result manifest {out['checksum'][:12]}")
            # the last handoff: everything verified, generation swaps in
            fp.hit("compact.remote.install")
        except Exception:
            # outputs never joined the manifest — sweep them and let the
            # caller fall back locally (no mutex held yet)
            self._db._discard_outputs(local_names)
            raise
        # verified generation on disk: only NOW win the compaction
        # mutex, revalidating the snapshot's inputs are still live —
        # the whole remote round trip above ran mutex-free (round 19)
        if not self._db.begin_full_install(plan):
            log.info("%s: snapshot went stale during remote merge "
                     "(local compaction won); discarding job %s",
                     self.db_name, job.job_id)
            self._db._discard_outputs(local_names)
            Stats.get().incr(
                tagged("compaction.remote.stale", db=self.db_name))
            return "stale"
        consumed["plan"] = True
        self._db.install_full_compaction(
            plan, files=local_names, remote=True)
        self.installed += 1
        self._queue.bump_summary("installed")
        Stats.get().incr(
            tagged("compaction.remote.installed", db=self.db_name))
        self._sweep_job(job.job_id)
        return "installed"

    # -- hygiene -------------------------------------------------------

    def _note_failover(self) -> None:
        self.failed_over += 1
        self._queue.bump_summary("failed_over")
        Stats.get().incr(
            tagged("compaction.remote.failed_over", db=self.db_name))

    def _sweep_job(self, job_id: str) -> None:
        """Retire the ledger entry and every transfer object for this
        job. Idempotent; safe on partially-published jobs."""
        for attempt in (0, 1):
            try:
                self._queue.remove(self.db_name)
                break
            except Exception:
                # a worker racing us can create a claim/result child
                # between the delete's enumerate and apply — one retry
                # wins because the parent job node is already doomed
                log.debug("ledger sweep attempt %d failed", attempt,
                          exc_info=True)
                time.sleep(0.05)
        try:
            prefix = f"compactions/{self.db_name}/{job_id}/"
            for key in self._store.list_objects(prefix):
                self._store.delete_object(key)
        except Exception:
            log.debug("object sweep failed", exc_info=True)

    def recover(self) -> None:
        """Leader (re)start: sweep any in-flight job this db published
        before a crash. No plan survives a process death (the compaction
        mutex is process-local), so the entry can never install — it
        only blocks the next publish. Reopen state is exactly
        pre-compaction; the next pick re-plans from scratch."""
        job = self._queue.get_job(self.db_name)
        if job is not None:
            log.info("%s: sweeping orphaned compaction job %s",
                     self.db_name, job.job_id)
            self._sweep_job(job.job_id)
            self._queue.bump_summary("recovered")

    # -- observability -------------------------------------------------

    def counters(self) -> dict:
        return {
            "installed": self.installed,
            "failed_over": self.failed_over,
            "fenced": self.fenced,
            "republished": self.republished,
        }
