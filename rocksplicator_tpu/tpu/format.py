"""Vectorized array→SST sink for the TPU pipeline.

The kernel emits struct-of-array lanes; turning them into SST files by
materializing Python tuples and re-serializing per entry would dominate the
end-to-end time. For uniform-width rows (the counter workload and most
fixed-schema KV), the block bytes assemble as ONE numpy matrix fill — no
per-entry Python — and the TPU-built bloom bitmap writes straight into the
file (byte-identical format, so readers can't tell).
"""

from __future__ import annotations

import zlib
from typing import Dict, List, Optional

import numpy as np

from ..storage.bloom import BloomFilter
from ..storage.planar import (decode_planar_block, encode_planar_block,
                              plane_words, planar_props)
from ..storage import rlz
from ..storage.sst import (BLOCK_PLANAR, BLOCK_PLANAR_RLZ,
                           BLOCK_PLANAR_ZLIB, COMPRESSION_RLZ,
                           COMPRESSION_ZLIB,
                           ENTRY_FIXED_OVERHEAD, SSTWriter)
from ..utils.checksum import poly_checksum_words

_ENTRY_FIXED_OVERHEAD = ENTRY_FIXED_OVERHEAD


def uniform_widths(arrays: Dict[str, np.ndarray], count: int):
    """(key_len, val_len) if all live rows share widths, else None."""
    if count == 0:
        return None
    kl = arrays["key_len"][:count]
    vl = arrays["val_len"][:count]
    k0, v0 = int(kl[0]), int(vl[0])
    if (kl == k0).all() and (vl == v0).all() and 0 < k0 <= 24:
        return k0, v0
    return None


def encode_uniform_block(arrays: Dict[str, np.ndarray], start: int, end: int,
                         klen: int, vlen: int) -> bytes:
    """Vectorized entry packing for rows [start, end) with fixed widths."""
    n = end - start
    stride = _ENTRY_FIXED_OVERHEAD + klen + vlen
    out = np.zeros((n, stride), dtype=np.uint8)
    pos = 0
    out[:, pos:pos + 4] = (
        np.full(n, klen, dtype="<u4").view(np.uint8).reshape(n, 4))
    pos += 4
    key_bytes = (
        np.ascontiguousarray(arrays["key_words_be"][start:end].astype(">u4"))
        .view(np.uint8).reshape(n, 24)
    )
    out[:, pos:pos + klen] = key_bytes[:, :klen]
    pos += klen
    seqs = (
        arrays["seq_hi"][start:end].astype(np.uint64) << np.uint64(32)
    ) | arrays["seq_lo"][start:end].astype(np.uint64)
    out[:, pos:pos + 8] = seqs.astype("<u8").view(np.uint8).reshape(n, 8)
    pos += 8
    out[:, pos] = arrays["vtype"][start:end].astype(np.uint8)
    pos += 1
    out[:, pos:pos + 4] = (
        np.full(n, vlen, dtype="<u4").view(np.uint8).reshape(n, 4))
    pos += 4
    if vlen:
        val_bytes = (
            np.ascontiguousarray(arrays["val_words"][start:end].astype("<u4"))
            .view(np.uint8).reshape(n, -1)
        )
        out[:, pos:pos + vlen] = val_bytes[:, :vlen]
    return out.tobytes()


def read_sst_arrays(reader) -> Optional[Dict[str, np.ndarray]]:
    """Vectorized SOURCE: decode a sink-written uniform-stride TSST file
    straight into kernel lanes (no per-entry Python). Returns the arrays
    dict (+ implicit count = rows) or None when the file lacks the uniform
    property (flush-written / foreign files use the tuple path)."""
    if reader.props.get("planar"):
        return _read_planar_arrays(reader)
    from ..ops.kv_format import UnsupportedBatch

    # Validate BEFORE reading the whole file: a file the array path will
    # reject must not pay a full pread+decompress only to be read again
    # by the tuple fallback.
    widths = reader.props.get("uniform")
    if widths:
        klen, vlen = int(widths[0]), int(widths[1])
        if not (0 < klen <= 24) or vlen < 0:
            return None  # foreign/crafted prop — tuple path validates
        blocks = [reader._read_block(i, fill_cache=False)
                  for i in range(len(reader._index))]
    else:
        # No sink prop (flush-written / foreign file): INFER the uniform
        # stride from block 0 so first-level compactions of flush output
        # still decode array-to-array. Probe only block 0 before
        # committing to the full read; the per-row width checks in the
        # shared row decode validate the inference (non-uniform files
        # fail them and take the tuple path).
        if not reader.num_entries or not reader._index:
            return None
        b0 = reader._read_block(0, fill_cache=False)
        inferred = _infer_uniform_widths(b0)
        if inferred is None:
            return None
        klen, vlen = inferred
        blocks = [b0] + [
            reader._read_block(i, fill_cache=False)
            for i in range(1, len(reader._index))
        ]
    raw = b"".join(blocks)
    try:
        lanes = _decode_uniform_rows(raw, klen, vlen)
    except UnsupportedBatch:
        return None  # misaligned/non-uniform — tuple path handles it
    # ingestion-time global seqno overrides per-entry seqs, same as the
    # reader's _effective_seq
    if reader.global_seqno is not None:
        n = len(lanes["seq_lo"])
        lanes["seq_lo"] = np.full(
            n, reader.global_seqno & 0xFFFFFFFF, dtype=np.uint32)
        lanes["seq_hi"] = np.full(
            n, reader.global_seqno >> 32, dtype=np.uint32)
    return lanes


class SstBlockLaneSource:
    """Block-granular lane decoder over ONE streamable TSST file — the
    SOURCE side of the bounded-memory chunked merge
    (storage/stream_merge.py). Where :func:`read_sst_arrays`
    materializes the whole file, this decodes an arbitrary block range
    on demand so a compaction's working set stays a fixed window per
    input run regardless of file size.

    Block reads probe the decoded-block LRU but never fill it
    (``fill_cache=False`` — the bulk-scan convention): a large streaming
    compaction must not evict hot serving blocks.

    ``probe`` returns None for files the lane representation can't
    stream (non-uniform rows, foreign layouts); a block that later
    violates the probed layout raises UnsupportedBatch and the caller
    falls back to the non-streaming path."""

    def __init__(self, reader, kind: str, klen: int, vlen: int):
        self.reader = reader
        self.kind = kind  # "planar" | "uniform"
        self.klen = klen
        self.vlen = vlen  # non-delete value width
        self.num_blocks = len(reader._index)
        self.num_entries = int(reader.num_entries)

    @classmethod
    def probe(cls, reader) -> Optional["SstBlockLaneSource"]:
        props = reader.props
        if not reader.num_entries or not reader._index:
            return None
        p = props.get("planar")
        if p:
            try:
                klen, vlen = int(p[0]), int(p[1])
            except (TypeError, ValueError, IndexError, KeyError):
                return None
            if not (0 < klen <= 24) or vlen < 0:
                return None
            return cls(reader, "planar", klen, vlen)
        widths = props.get("uniform")
        if widths:
            try:
                klen, vlen = int(widths[0]), int(widths[1])
            except (TypeError, ValueError, IndexError):
                return None
            if not (0 < klen <= 24) or vlen < 0:
                return None
            return cls(reader, "uniform", klen, vlen)
        # No sink prop (flush-written / foreign): infer the uniform
        # stride from block 0 via the SAME helper read_sst_arrays uses —
        # the per-block width checks in decode_blocks validate the
        # inference on every later block.
        b0 = reader._read_block(0, fill_cache=False)
        inferred = _infer_uniform_widths(b0)
        if inferred is None:
            return None
        return cls(reader, "uniform", *inferred)

    def decode_blocks(self, b0: int, b1: int) -> Dict[str, np.ndarray]:
        """Lane arrays for blocks [b0, b1). Raises UnsupportedBatch when
        a block violates the probed layout (caller declines streaming)."""
        from ..ops.kv_format import UnsupportedBatch

        if self.kind == "planar":
            try:
                parts = [
                    decode_planar_block(
                        self.reader._read_block(i, fill_cache=False))
                    for i in range(b0, b1)
                ]
            except Exception as e:
                raise UnsupportedBatch(f"planar stream decode: {e}")
            lanes = {f: np.concatenate([p[f] for p in parts])
                     for f in parts[0]}
            kl = lanes["key_len"]
            if len(kl) and not (kl == self.klen).all():
                raise UnsupportedBatch("planar stream: klen drift")
            vl = lanes["val_len"][lanes["vtype"] != 2]
            if len(vl) and not (vl == self.vlen).all():
                raise UnsupportedBatch("planar stream: vlen drift")
        else:
            raw = b"".join(
                self.reader._read_block(i, fill_cache=False)
                for i in range(b0, b1))
            lanes = _decode_uniform_rows(raw, self.klen, self.vlen)
        seqno = self.reader.global_seqno
        if seqno is not None:
            n = len(lanes["seq_lo"])
            lanes["seq_lo"] = np.full(
                n, seqno & 0xFFFFFFFF, dtype=np.uint32)
            lanes["seq_hi"] = np.full(n, seqno >> 32, dtype=np.uint32)
        return lanes


def _infer_uniform_widths(b0: bytes):
    """(klen, vlen) of a uniform-stride file inferred from its first
    block (no sink prop: flush-written / foreign files), or None when
    block 0 can't carry a uniform stride. Shared by read_sst_arrays and
    SstBlockLaneSource.probe; the per-row checks in
    _decode_uniform_rows validate the inference on every block."""
    if len(b0) < _ENTRY_FIXED_OVERHEAD:
        return None
    klen = int.from_bytes(b0[:4], "little")
    if not (0 < klen <= 24) or len(b0) < _ENTRY_FIXED_OVERHEAD + klen:
        return None
    # first entry's vlen field sits after klen|key|seq|vtype
    vlen = int.from_bytes(b0[klen + 13:klen + 17], "little")
    if len(b0) % (_ENTRY_FIXED_OVERHEAD + klen + vlen):
        return None
    return klen, vlen


def _decode_uniform_rows(raw: bytes, klen: int,
                         vlen: int) -> Dict[str, np.ndarray]:
    """Uniform-stride row bytes → lane arrays (the row-matrix half of
    read_sst_arrays, shared with the block-range streaming source).
    Raises UnsupportedBatch on per-row width drift."""
    from ..ops.kv_format import UnsupportedBatch

    stride = _ENTRY_FIXED_OVERHEAD + klen + vlen
    if len(raw) % stride:
        raise UnsupportedBatch("uniform stream: stride drift")
    n = len(raw) // stride
    mat = np.frombuffer(raw, dtype=np.uint8).reshape(n, stride)
    pos = 0
    klens = mat[:, pos:pos + 4].copy().view("<u4").reshape(n)
    pos += 4
    key_bytes = mat[:, pos:pos + klen]
    pos += klen
    seqs = mat[:, pos:pos + 8].copy().view("<u8").reshape(n)
    pos += 8
    vtypes = mat[:, pos].astype(np.uint32)
    pos += 1
    vlens = mat[:, pos:pos + 4].copy().view("<u4").reshape(n)
    pos += 4
    val_bytes = mat[:, pos:pos + vlen]
    if not (klens == klen).all() or not (vlens == vlen).all():
        raise UnsupportedBatch("uniform stream: row width drift")
    key_buf = np.zeros((n, 24), dtype=np.uint8)
    key_buf[:, :klen] = key_bytes
    vw = max(2, (vlen + 3) // 4)
    val_buf = np.zeros((n, vw * 4), dtype=np.uint8)
    if vlen:
        val_buf[:, :vlen] = val_bytes
    return {
        "key_words_be": key_buf.view(">u4").astype(np.uint32).reshape(n, 6),
        "key_words_le": key_buf.view("<u4").reshape(n, 6).copy(),
        "key_len": klens.astype(np.uint32),
        "seq_hi": (seqs >> np.uint64(32)).astype(np.uint32),
        "seq_lo": (seqs & np.uint64(0xFFFFFFFF)).astype(np.uint32),
        "vtype": vtypes,
        "val_words": val_buf.view("<u4").reshape(n, vw).copy(),
        "val_len": vlens.astype(np.uint32),
    }


def planar_stride(klen: int, vlen: int) -> int:
    """Approximate PLANAR bytes per entry (seq32 layout: key + seq_lo +
    vtype + value) — block/file sizing only, shared by every sink."""
    return klen + vlen + 9


def planar_widths(arrays: Dict[str, np.ndarray], count: int):
    """(klen, vlen) for the PLANAR sink. Laxer than uniform_widths:
    DELETE rows carry no value in the planar layout (val_len derives from
    vtype on read), so kept tombstones coexist with fixed-width values."""
    if count == 0:
        return None
    kl = arrays["key_len"][:count]
    k0 = int(kl[0])
    if not ((kl == k0).all() and 0 < k0 <= 24):
        return None
    vt = arrays["vtype"][:count]
    vl = arrays["val_len"][:count]
    non_del = vl[vt != 2]
    v0 = int(non_del[0]) if len(non_del) else 0
    if len(non_del) and not (non_del == v0).all():
        return None
    if not (vl[vt == 2] == 0).all():
        return None
    # Header bound (u16 vlen): wider values take the entry-stream sink.
    # The round-2 crash was this check missing — every uniform workload
    # with values >= 256 B died in the header packer (VERDICT r2 #1).
    from ..storage.planar import PLANAR_MAX_VLEN
    if v0 > PLANAR_MAX_VLEN:
        return None
    return k0, v0


def _write_planar(
    arrays: Dict[str, np.ndarray], count: int, path: str,
    bloom_words: Optional[np.ndarray], block_entries: int,
    compression: int, bits_per_key: int, klen: int, vlen: int,
    device_words: Optional[np.ndarray],
    device_checksums: Optional[np.ndarray],
) -> Optional[dict]:
    """PLANAR sink body: per-block plane bytes + word-domain checksums."""
    seq32 = bool((arrays["seq_hi"][:count] == 0).all())
    full_words = plane_words(block_entries, klen, vlen, seq32)
    writer = SSTWriter(path, compression=compression,
                       bits_per_key=bits_per_key)
    try:
        key_bytes = (
            np.ascontiguousarray(
                arrays["key_words_be"][:count].astype(">u4"))
            .view(np.uint8).reshape(count, 24)[:, :klen]
        )
        seqs = (
            arrays["seq_hi"][:count].astype(np.uint64) << np.uint64(32)
        ) | arrays["seq_lo"][:count].astype(np.uint64)
        from ..storage.planar import (PLANAR_HEADER, PLANAR_FLAG_SEQ32,
                                      pack_planar_header)

        chks: List[int] = []
        nblocks = (count + block_entries - 1) // block_entries
        for bi, start in enumerate(range(0, count, block_entries)):
            end = min(start + block_entries, count)
            full = end - start == block_entries
            if device_words is not None and full and bi < len(device_words):
                words = np.ascontiguousarray(
                    device_words[bi], dtype="<u4")
                raw = pack_planar_header(
                    block_entries, klen, vlen,
                    PLANAR_FLAG_SEQ32 if seq32 else 0,
                ) + words.tobytes()
                if device_checksums is not None and bi < len(
                        device_checksums):
                    chks.append(int(device_checksums[bi]))
                else:
                    chks.append(poly_checksum_words(words, full_words))
            else:
                raw = encode_planar_block(
                    arrays, start, end, klen, vlen, seq32)
                words = np.frombuffer(
                    raw, dtype="<u4", offset=PLANAR_HEADER.size)
                chks.append(poly_checksum_words(words, full_words))
            codec = BLOCK_PLANAR
            payload = raw
            if compression == COMPRESSION_ZLIB:
                z = zlib.compress(raw, 1)
                if len(z) < len(raw):
                    codec, payload = BLOCK_PLANAR_ZLIB, z
            elif compression == COMPRESSION_RLZ:
                z = rlz.compress(raw)
                if len(z) < len(raw):
                    codec, payload = BLOCK_PLANAR_RLZ, z
            writer.add_encoded_block(
                payload,
                last_key=key_bytes[end - 1].tobytes(),
                num_entries=end - start,
                keys=[],
                min_key=key_bytes[start].tobytes(),
                max_key=key_bytes[end - 1].tobytes(),
                min_seq=int(seqs[start:end].min()),
                max_seq=int(seqs[start:end].max()),
                compressed=False,
                codec=codec,
            )
        if bloom_words is not None:
            bloom = BloomFilter(
                len(bloom_words), np.asarray(bloom_words, dtype=np.uint32)
            )
        else:
            bloom = BloomFilter.build(
                [key_bytes[i].tobytes() for i in range(count)], bits_per_key
            )
        extra_props = {
            "num_keys": int(count),
            "planar": planar_props(klen, vlen, seq32),
            "block_chk": {
                "algo": "poly1w",
                "block_words": int(full_words),
                "values": chks,
            },
        }
        return writer.finish(precomputed_bloom=bloom,
                             extra_props=extra_props)
    except BaseException:
        writer.abandon()
        raise


def _read_planar_arrays(reader) -> Optional[Dict[str, np.ndarray]]:
    """PLANAR source path: per-block plane decode (views + reshapes),
    lanes concatenated across blocks."""
    try:
        parts = [
            decode_planar_block(reader._read_block(i, fill_cache=False))
            for i in range(len(reader._index))
        ]
    except Exception:
        return None  # foreign/corrupt planar props — tuple path validates
    if not parts:
        return None
    lanes = {
        f: np.concatenate([p[f] for p in parts])
        for f in parts[0]
    }
    if reader.global_seqno is not None:
        n = len(lanes["seq_lo"])
        lanes["seq_lo"] = np.full(
            n, reader.global_seqno & 0xFFFFFFFF, dtype=np.uint32)
        lanes["seq_hi"] = np.full(
            n, reader.global_seqno >> 32, dtype=np.uint32)
    return lanes


def write_sst_from_arrays(
    arrays: Dict[str, np.ndarray],
    count: int,
    path: str,
    bloom_words: Optional[np.ndarray] = None,
    block_entries: int = 1024,
    compression: int = COMPRESSION_ZLIB,
    bits_per_key: int = 10,
    device_rows: Optional[np.ndarray] = None,
    device_checksums: Optional[np.ndarray] = None,
    planar: bool = False,
    device_words: Optional[np.ndarray] = None,
) -> Optional[dict]:
    """Write kernel-output arrays as a TSST file without per-entry Python.
    Returns the props dict, or None when rows aren't uniform-width (caller
    falls back to the tuple path).

    ``device_rows``/``device_checksums``: the on-device block encoder's
    output (ops/block_encode.py) — the (count, stride) byte matrix is
    written as-is (no host re-encoding) and the per-block checksums land
    in the "block_chk" prop, which readers verify on every block read.

    ``planar=True`` writes PLANAR blocks (storage/planar.py): u32 planes
    in kernel lane order — smaller files and no byte interleaving on
    either side. ``device_words`` optionally carries the device planar
    encoder's (nblocks, words) matrix for full blocks (the tail block is
    host-packed: its plane lengths differ from the fixed device shape)."""
    if planar:
        widths = planar_widths(arrays, count)
        if widths is None:
            return None
        return _write_planar(
            arrays, count, path, bloom_words, block_entries, compression,
            bits_per_key, widths[0], widths[1], device_words,
            device_checksums)
    widths = uniform_widths(arrays, count)
    if widths is None:
        return None
    klen, vlen = widths
    stride = _ENTRY_FIXED_OVERHEAD + klen + vlen
    if device_rows is not None and device_rows.shape != (count, stride):
        return None  # shape mismatch — let the host path handle it
    writer = SSTWriter(path, compression=compression,
                       bits_per_key=bits_per_key)
    try:
        key_bytes = (
            np.ascontiguousarray(
                arrays["key_words_be"][:count].astype(">u4"))
            .view(np.uint8).reshape(count, 24)[:, :klen]
        )
        seqs = (
            arrays["seq_hi"][:count].astype(np.uint64) << np.uint64(32)
        ) | arrays["seq_lo"][:count].astype(np.uint64)
        for start in range(0, count, block_entries):
            end = min(start + block_entries, count)
            if device_rows is not None:
                raw = device_rows[start:end].tobytes()
            else:
                raw = encode_uniform_block(arrays, start, end, klen, vlen)
            codec = compression
            if codec == COMPRESSION_ZLIB:
                payload = zlib.compress(raw, 1)
            elif codec == COMPRESSION_RLZ:
                payload = rlz.compress(raw)
            else:
                payload = raw
            if len(payload) >= len(raw):
                codec, payload = 0, raw
            writer.add_encoded_block(
                payload,
                last_key=key_bytes[end - 1].tobytes(),
                num_entries=end - start,
                keys=[],  # bloom comes prebuilt; keys list unused
                min_key=key_bytes[start].tobytes(),
                max_key=key_bytes[end - 1].tobytes(),
                min_seq=int(seqs[start:end].min()),
                max_seq=int(seqs[start:end].max()),
                compressed=False,
                codec=codec,
            )
        bloom = None
        if bloom_words is not None:
            bloom = BloomFilter(
                len(bloom_words), np.asarray(bloom_words, dtype=np.uint32)
            )
        else:
            bloom = BloomFilter.build(
                [key_bytes[i].tobytes() for i in range(count)], bits_per_key
            )
        # kernel output has one entry per key; the uniform prop lets the
        # vectorized SOURCE reader decode this file array-to-array
        extra_props = {"num_keys": int(count),
                       "uniform": [int(klen), int(vlen)]}
        if device_checksums is not None:
            extra_props["block_chk"] = {
                "algo": "poly1",
                "block_bytes": block_entries * stride,
                "values": [int(c) for c in device_checksums],
            }
        return writer.finish(
            precomputed_bloom=bloom,
            extra_props=extra_props,
        )
    except BaseException:
        writer.abandon()
        raise
