"""Chunked hierarchical TPU merges for batches beyond one kernel launch.

Correctness rests on the engine's run invariant (the same one the mesh
block axis uses): for any key, two input runs' entries occupy disjoint,
ordered sequence ranges (L0 files partition by flush order; deeper levels
are key-disjoint; ingested files carry one global seqno). Under that
invariant LSM resolution is associative:

- a chunk of ONE run holds a contiguous newest-first slice of each key's
  stack, so folding it yields either a resolved base (shadowing the rest)
  or a partial-merge summary strictly newer than the remainder;
- merging two run summaries composes the same way (newest base shadows).

Pipeline: fold each run's chunks bottom-up, then seq-sort and greedily
group summaries into fixed-shape launches, with tombstones kept until the
final pass. Intermediate results stay as packed numpy lanes — no Python
tuples until the caller unpacks the final output.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Tuple

import numpy as np

from ..ops.compaction_kernel import (MergeKind, deployment_sort_backend,
                                     merge_resolve_kernel)
from ..ops.kv_format import KVBatch

log = logging.getLogger(__name__)

from ..ops.kv_format import LANE_FIELDS as FIELDS  # noqa: E402 (canonical home)
# kernel INPUT lanes: LE key words are byteswap-derived on device, so they
# are carried between passes (FIELDS — outputs include them for the sinks)
# but never shipped into a launch
INPUT_FIELDS = tuple(f for f in FIELDS if f != "key_words_le")


def run_kernel_arrays(
    batch_arrays: dict, n_valid: int, merge_kind: MergeKind,
    drop_tombstones: bool, pad_to: Optional[int] = None,
    uniform_klen: bool = False, seq32: bool = False,
    key_words: Optional[int] = None, to_host: bool = True,
) -> Tuple[Optional[dict], int]:
    """THE kernel invocation wrapper (shared by the chunked tree and the
    backend's direct file sink): one launch over packed arrays; returns
    (output arrays trimmed to count, count) or (None, 0) on kernel-flagged
    fallback. ``pad_to`` fixes the launch shape so callers reuse one
    compiled kernel. ``to_host=False`` keeps the trimmed outputs as
    DEVICE arrays — the chunked tree feeds them straight into the next
    launch, so intermediate passes never round-trip through host numpy
    (only the count/fallback scalars sync)."""
    import jax.numpy as jnp

    n_rows = batch_arrays["key_len"].shape[0]
    if pad_to is not None and n_rows < pad_to:
        pad = pad_to - n_rows
        # jnp.pad keeps device-resident inputs on device; numpy inputs
        # land there with the launch anyway
        batch_arrays = {
            f: jnp.pad(batch_arrays[f],
                       [(0, pad)] + [(0, 0)] * (batch_arrays[f].ndim - 1))
            for f in FIELDS
        }
        n_rows = pad_to
    valid = np.zeros(n_rows, dtype=bool)
    valid[:n_valid] = True
    kw = (key_words if key_words is not None
          else batch_arrays["key_words_be"].shape[1])
    out = merge_resolve_kernel(
        *(jnp.asarray(batch_arrays[f]) for f in INPUT_FIELDS),
        jnp.asarray(valid),
        merge_kind=merge_kind, drop_tombstones=drop_tombstones,
        uniform_klen=uniform_klen, seq32=seq32, key_words=kw,
        sort_backend=deployment_sort_backend(),
    )
    if bool(out["needs_cpu_fallback"]):
        return None, 0
    count = int(out["count"])
    if to_host:
        return {f: np.asarray(out[f])[:count] for f in FIELDS}, count
    return {f: out[f][:count] for f in FIELDS}, count


def _concat(parts: List[dict]) -> Tuple[dict, int]:
    import jax.numpy as jnp

    # jnp: device-resident parts concatenate on device (host parts join
    # them there — that is where the next launch reads them)
    merged = {f: jnp.concatenate([p[f] for p in parts]) for f in FIELDS}
    return merged, merged["key_len"].shape[0]


def _batch_to_arrays(batch: KVBatch) -> Tuple[dict, int]:
    n = batch.num_valid()
    return {f: getattr(batch, f)[:n] for f in FIELDS}, n


def _fold_groups(
    parts: List[Tuple[dict, int]], merge_kind: MergeKind,
    launch_entries: int,
) -> Optional[List[Tuple[dict, int]]]:
    """One greedy pass: group consecutive parts up to the launch size and
    fold each group (tombstones kept — not the final pass)."""
    next_level: List[Tuple[dict, int]] = []
    group: List[dict] = []
    group_n = 0

    def flush() -> bool:
        nonlocal group, group_n
        if not group:
            return True
        merged, total = _concat(group)
        out = run_kernel_arrays(merged, total, merge_kind, False,
                                pad_to=launch_entries, to_host=False)
        if out[0] is None:
            return False
        next_level.append(out)
        group, group_n = [], 0
        return True

    for part, pn in parts:
        if group and group_n + pn > launch_entries:
            if not flush():
                return None
        group.append(part)
        group_n += pn
    if not flush():
        return None
    return next_level


def chunked_merge(
    run_batches: List[KVBatch],
    merge_kind: MergeKind,
    drop_tombstones: bool,
    chunk_entries: int,
    launch_entries: int,
) -> Optional[Tuple[dict, int]]:
    """Merge packed per-run batches hierarchically. Returns (final output
    arrays, count), or None when the kernel demands CPU fallback."""
    chunk_entries = min(chunk_entries, launch_entries)
    # 1) per-run: multi-chunk runs reduce to one summary; single-chunk
    #    runs pass through raw (already sorted per the run contract — a
    #    dedup fold would be a wasted full-size launch)
    summaries: List[Tuple[dict, int]] = []
    for batch in run_batches:
        arrays, n = _batch_to_arrays(batch)
        pieces: List[Tuple[dict, int]] = [
            ({f: arrays[f][i:i + chunk_entries] for f in FIELDS},
             min(chunk_entries, n - i))
            for i in range(0, n, chunk_entries)
        ] or [(arrays, 0)]
        while len(pieces) > 1:
            folded = _fold_groups(pieces, merge_kind, launch_entries)
            if folded is None:
                return None
            if len(folded) >= len(pieces):
                return None  # cannot reduce further
            pieces = folded
        summaries.append(pieces[0])

    # 2) merge run summaries hierarchically; the final pass applies the
    #    real tombstone policy. Grouping folds CONSECUTIVE summaries,
    #    which is only associativity-safe for ADJACENT seq intervals —
    #    engine run lists arrive level-ordered ([L0 old..new, L1, ...]),
    #    NOT seq-ordered, so sort summaries by max seq first (runs occupy
    #    globally disjoint seq intervals in this engine).
    def _max_seq(part_n) -> int:
        part, n = part_n
        if n == 0:
            return 0
        hi_lane, lo_lane = part["seq_hi"][:n], part["seq_lo"][:n]
        if isinstance(hi_lane, np.ndarray):
            # host part (single-chunk pass-through): pure numpy, no H2D
            hi64 = hi_lane.astype(np.uint64) << np.uint64(32)
            return int((hi64 | lo_lane.astype(np.uint64)).max())
        # device part (from _fold_groups): scalar reductions + readbacks
        # only — never pull the lanes to host
        import jax.numpy as jnp

        hi = int(jnp.max(hi_lane))
        lo_at = int(jnp.max(jnp.where(
            hi_lane == hi, lo_lane, jnp.uint32(0))))
        return (hi << 32) | lo_at

    summaries.sort(key=_max_seq)
    while True:
        total = sum(n for _p, n in summaries)
        if total <= launch_entries:
            merged, _n = _concat([p for p, _ in summaries])
            return run_kernel_arrays(merged, total, merge_kind,
                                     drop_tombstones, pad_to=launch_entries)
        folded = _fold_groups(summaries, merge_kind, launch_entries)
        if folded is None or len(folded) >= len(summaries):
            return None  # too many distinct keys to converge
        summaries = folded
