"""Compaction backends: TPU kernel and vectorized-numpy CPU baseline.

``TpuCompactionBackend`` implements the storage engine's CompactionBackend
seam with the ops/compaction_kernel pipeline; anything the fixed-shape
representation can't express (long keys, wide values, custom merge
operators) falls back to the CPU heap-merge, mirroring the north star's
"fall back to CPU on kernel inapplicability".

``NumpyCompactionBackend`` is the honest vectorized CPU baseline the bench
compares against (np.lexsort + reduceat segment folds — the best a CPU
does without hand-written SIMD).
"""

from __future__ import annotations

import logging
import os
from typing import Iterable, Iterator, List, Optional, Tuple

import numpy as np

from ..storage.compaction import CompactionBackend, CpuCompactionBackend, Entry
from ..storage.merge import MergeOperator, UInt64AddOperator
from ..ops.compaction_kernel import (MergeKind, deployment_sort_backend,
                                     merge_resolve_kernel)
from ..ops.kv_format import (KVBatch, UnsupportedBatch, fast_flags,
                             pack_entries, unpack_entries)

log = logging.getLogger(__name__)

_PUT, _DELETE, _MERGE = 1, 2, 3

# Boundary between the single-shot kernel and the hierarchical chunked
# merge (tpu/chunked.py): batches up to this size launch once; larger ones
# fold per-run chunks then summaries at this fixed launch shape.
MAX_TPU_ENTRIES = 1 << 22


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _arrays_from_entries(entries: List[Entry]) -> Optional[dict]:
    """Entry tuples → valid-prefix lane arrays (tuple-source fallback)."""
    if not entries:
        return None
    from .chunked import _batch_to_arrays

    return _batch_to_arrays(pack_entries(entries))[0]


class TpuCompactionBackend(CompactionBackend):
    name = "tpu"
    supports_subcompactions = True
    supports_memory_budget = True

    def __init__(self, fallback: Optional[CompactionBackend] = None):
        # default fallback is the VECTORIZED cpu path: on hosts where the
        # accelerator is absent/wedged, the framework's compaction
        # throughput is the lexsort+reduceat numpy pipeline (itself
        # falling back to the streaming heap-merge for batches the lane
        # representation can't express)
        self._fallback = fallback or NumpyCompactionBackend()
        import jax  # deferred so CPU-only deployments never touch jax

        self._jax = jax

    def merge_runs(
        self,
        runs: List[Iterable[Entry]],
        merge_op: Optional[MergeOperator],
        drop_tombstones: bool,
    ) -> Iterator[Entry]:
        if merge_op is not None and not isinstance(merge_op, UInt64AddOperator):
            # custom operators run arbitrary Python — CPU path
            return self._fallback.merge_runs(runs, merge_op, drop_tombstones)
        run_lists: List[List[Entry]] = [list(run) for run in runs]
        total = sum(len(r) for r in run_lists)
        if total == 0:
            return iter(())
        if merge_op is not None and any(
            vtype != _DELETE and len(value) != 8
            for run in run_lists for _k, _s, vtype, value in run
        ):
            # uint64-add fold semantics require 8-byte values (a lone
            # non-8-byte PUT must stay verbatim; the fold would rewrite
            # it to the parsed-as-zero operand sum) — stream path
            return self._fallback.merge_runs(
                run_lists, merge_op, drop_tombstones)

        def cpu():
            entries = [e for run in run_lists for e in run]
            return self._fallback.merge_runs(
                [sorted(entries, key=lambda e: (e[0], -e[1]))],
                merge_op, drop_tombstones,
            )

        if total > MAX_TPU_ENTRIES:
            # hierarchical chunked merge: per-run folding then summary
            # merging, each launch at one fixed shape (tpu/chunked.py)
            result = self._chunked(run_lists, merge_op, drop_tombstones)
            if result is None:
                return cpu()
            return iter(result)
        entries = [e for run in run_lists for e in run]
        try:
            batch = pack_entries(entries, capacity=_next_pow2(total))
        except UnsupportedBatch as e:
            log.debug("TPU compaction fallback: %s", e)
            return cpu()
        if merge_op is None and bool((batch.vtype == _MERGE).any()):
            # MERGE records without an operator: the reference preserves the
            # unresolved operand chain — only the CPU path can express that.
            # (Checked on the packed vtype lane — a numpy any(), not a
            # Python walk of up to 4M tuples.)
            return cpu()
        result = self._run_batch(batch, merge_op, drop_tombstones)
        if result is None:  # kernel flagged limb-overflow risk
            return cpu()
        return iter(result)

    def _chunked(self, runs, merge_op, drop_tombstones) -> Optional[List[Entry]]:
        from .chunked import chunked_merge
        from ..ops.compaction_kernel import MergeKind as MK

        kind = (
            MK.UINT64_ADD if isinstance(merge_op, UInt64AddOperator)
            else MK.NONE
        )
        try:
            run_batches = [pack_entries(run) for run in runs]
        except UnsupportedBatch as e:
            log.debug("TPU chunked fallback: %s", e)
            return None
        if kind is MK.NONE and any(
            bool((b.vtype[: b.num_valid()] == _MERGE).any())
            for b in run_batches
        ):
            return None
        result = chunked_merge(
            run_batches, kind, drop_tombstones,
            chunk_entries=MAX_TPU_ENTRIES // 4,
            launch_entries=MAX_TPU_ENTRIES,
        )
        if result is None:
            return None
        arrays, count = result
        return unpack_entries(
            arrays["key_words_be"], arrays["key_len"], arrays["seq_hi"],
            arrays["seq_lo"], arrays["vtype"], arrays["val_words"],
            arrays["val_len"], count,
        )

    def merge_runs_to_files(
        self,
        runs: List,
        merge_op: Optional[MergeOperator],
        drop_tombstones: bool,
        path_factory,
        block_bytes: int,
        compression: int,
        bits_per_key: int,
        target_file_bytes: int,
        max_subcompactions: int = 1,
        io_budget=None,
        mem_tracker=None,
        memory_budget_bytes: int = 0,
    ) -> Optional[List[Tuple[str, dict]]]:
        """Merge + write output SSTs with the vectorized array sink and
        kernel-built blooms, splitting at ``target_file_bytes``. Inputs may
        be SSTReader objects — sink-written uniform files decode straight
        to lanes (no per-entry Python on the SOURCE side either) — or
        entry iterables. Returns [(path, props)] — empty list for an
        all-tombstoned result — or None → tuple path.

        Inputs whose projected lane image exceeds the compaction memory
        budget stream through the chunked bounded-memory merge with the
        DEVICE chunk resolver — double-buffered chunks: decode chunk
        N+1 on host while chunk N's lanes transfer back from device
        (the resolve itself still syncs at submit; see TpuChunkResolver)
        (storage/stream_merge.py + compaction_service.TpuChunkResolver).

        ``max_subcompactions > 1``: an in-RAM job splits into disjoint
        key-range slices resolved as ONE padded vmapped device batch
        (tpu/compaction_service.resolve_slices_batched) — k smaller
        bitonic sorts in one launch instead of one pow2(total) sort.
        ``io_budget`` paces the output file writes."""
        from ..ops.bloom_tpu import bloom_build_tpu
        from ..storage.bloom import num_words_for
        from ..storage.stream_merge import maybe_stream_merge
        from .chunked import FIELDS, run_kernel_arrays
        from .compaction_service import TpuChunkResolver
        from .format import (planar_stride, planar_widths, read_sst_arrays,
                             write_sst_from_arrays)

        if merge_op is not None and not isinstance(merge_op, UInt64AddOperator):
            return None
        streamed = maybe_stream_merge(
            runs, merge_op, drop_tombstones, path_factory, block_bytes,
            compression, bits_per_key, target_file_bytes,
            io_budget=io_budget, mem_tracker=mem_tracker,
            memory_budget_bytes=memory_budget_bytes,
            resolver=TpuChunkResolver(),
        )
        if streamed is not None:
            return streamed
        parts: List[dict] = []
        try:
            for run in runs:
                if hasattr(run, "iterate"):  # an SSTReader
                    arr = read_sst_arrays(run)
                    if arr is None:
                        arr = _arrays_from_entries(list(run.iterate()))
                else:
                    arr = _arrays_from_entries(list(run))
                if arr is not None:
                    parts.append(arr)
        except UnsupportedBatch:
            return None
        total = sum(p["key_len"].shape[0] for p in parts)
        if total == 0 or total > MAX_TPU_ENTRIES:
            return None  # chunked/CPU paths return entries, not files (yet)
        # normalize value-lane widths (sources may carry different paddings)
        vw = max(p["val_words"].shape[1] for p in parts)
        for p in parts:
            w = p["val_words"].shape[1]
            if w < vw:
                p["val_words"] = np.pad(p["val_words"], [(0, 0), (0, vw - w)])
        lanes = {
            f: np.concatenate([p[f] for p in parts]) for f in FIELDS
        }
        if merge_op is None and bool((lanes["vtype"] == _MERGE).any()):
            return None
        # Cheap pre-check BEFORE the kernel: the PLANAR sink needs uniform
        # keys and uniform non-delete value widths (kept tombstones are
        # fine — the planar layout derives val_len from vtype, so deletes
        # coexist with fixed-width values, unlike the old row sink).
        kl = lanes["key_len"]
        if total and not (kl == kl[0]).all():
            return None
        is_del = lanes["vtype"] == _DELETE
        vlens = lanes["val_len"]
        non_del_vlens = vlens[~is_del]
        if len(non_del_vlens) and not (non_del_vlens == non_del_vlens[0]).all():
            return None
        # uint64-add fold semantics require 8-byte values: a lone
        # non-8-byte PUT would be rewritten to the (zero) operand sum
        # instead of staying verbatim as the stream path keeps it
        if (merge_op is not None and len(non_del_vlens)
                and not (non_del_vlens == 8).all()):
            return None
        kind = (
            MergeKind.UINT64_ADD if isinstance(merge_op, UInt64AddOperator)
            else MergeKind.NONE
        )
        arrays = count = None
        if max_subcompactions > 1:
            sliced = self._subcompact_arrays(
                parts, lanes, total, kind, drop_tombstones,
                max_subcompactions)
            if sliced is not None:
                arrays, count = sliced
        if arrays is None:
            all_valid = np.ones(total, dtype=bool)
            uniform_klen, seq32, key_words = fast_flags(
                kl, lanes["seq_hi"], all_valid)
            arrays, count = run_kernel_arrays(
                lanes, total, kind, drop_tombstones,
                pad_to=_next_pow2(total),
                uniform_klen=uniform_klen, seq32=seq32,
                key_words=key_words,
            )
        if arrays is None:
            return None
        if count == 0:
            return []  # fully compacted away — nothing to write
        widths = planar_widths(arrays, count)
        if widths is None:
            return None
        klen0, vlen0 = widths
        stride = planar_stride(klen0, vlen0)
        entries_per_file = max(1024, target_file_bytes // max(1, stride))
        block_entries = max(64, block_bytes // max(1, stride))
        outputs: List[Tuple[str, dict]] = []
        for start in range(0, count, entries_per_file):
            end = min(start + entries_per_file, count)
            sub = {f: arrays[f][start:end] for f in arrays}
            sub_valid = np.ones(end - start, dtype=bool)
            num_words = num_words_for(end - start, bits_per_key)
            import jax.numpy as jnp

            bloom = bloom_build_tpu(
                jnp.asarray(sub["key_words_le"]),
                jnp.asarray(sub["key_len"]),
                jnp.asarray(sub_valid), num_words=num_words,
            )
            path = path_factory()
            # PLANAR output: the kernel's struct-of-array lanes ARE the
            # block planes (storage/planar.py) — no byte interleaving on
            # either side, ~29% smaller uncompressed than the row format
            props = write_sst_from_arrays(
                sub, end - start, path,
                bloom_words=np.asarray(bloom),
                block_entries=block_entries,
                compression=compression,
                bits_per_key=bits_per_key,
                planar=True,
            )
            if props is None:  # should not happen after the width checks
                for p, _ in outputs:
                    try:
                        os.remove(p)
                    except OSError:
                        pass
                return None
            outputs.append((path, props))
            if io_budget is not None:
                try:
                    io_budget.throttle(os.path.getsize(path))
                except OSError:
                    pass
        return outputs

    @staticmethod
    def _subcompact_arrays(parts, lanes, total, kind, drop_tombstones,
                           max_subcompactions):
        """Key-range subcompactions on the device: choose boundary keys
        from the runs' key distribution (shared helpers with the CPU
        path), slice every run at them, and resolve ALL slices as one
        padded vmapped batch. Returns (arrays, count) concatenated in
        boundary order — identical logical output to the single-shot
        kernel — or None to take the unsliced path."""
        from ..storage.native_compaction import (_first_row_ge,
                                                 plan_subcompactions,
                                                 slice_parts)
        from .chunked import FIELDS
        from .compaction_service import resolve_slices_batched

        kl = lanes["key_len"]
        klen = int(kl[0]) if len(kl) else 0
        bounds = plan_subcompactions(parts, total, max_subcompactions, klen)
        if not bounds:
            return None
        cuts = [[_first_row_ge(p, b, klen) for b in bounds] for p in parts]
        slices = []
        for si in range(len(bounds) + 1):
            sub = slice_parts(parts, bounds, si, klen, cuts, fields=FIELDS)
            if sub:
                slices.append({
                    f: np.concatenate([p[f] for p in sub]) for f in FIELDS})
        if not slices:
            return None
        per_slice = resolve_slices_batched(slices, kind, drop_tombstones)
        live = [(a, c) for a, c in per_slice if c]
        if not live:
            return {}, 0
        fields = list(live[0][0].keys())
        arrays = {
            f: np.concatenate([np.asarray(a[f]) for a, _c in live])
            for f in fields
        }
        return arrays, int(sum(c for _a, c in live))

    def _run_batch(
        self, batch: KVBatch, merge_op: Optional[MergeOperator],
        drop_tombstones: bool,
    ) -> Optional[List[Entry]]:
        """None means the kernel flagged a condition (limb-overflow risk)
        requiring the CPU path."""
        jnp = self._jax.numpy
        kind = (
            MergeKind.UINT64_ADD if isinstance(merge_op, UInt64AddOperator)
            else MergeKind.NONE
        )
        uniform_klen, seq32, key_words = fast_flags(
            batch.key_len, batch.seq_hi, batch.valid)
        out = merge_resolve_kernel(
            jnp.asarray(batch.key_words_be),
            jnp.asarray(batch.key_len), jnp.asarray(batch.seq_hi),
            jnp.asarray(batch.seq_lo), jnp.asarray(batch.vtype),
            jnp.asarray(batch.val_words), jnp.asarray(batch.val_len),
            jnp.asarray(batch.valid),
            merge_kind=kind, drop_tombstones=drop_tombstones,
            uniform_klen=uniform_klen, seq32=seq32, key_words=key_words,
            sort_backend=deployment_sort_backend(),
        )
        if bool(out["needs_cpu_fallback"]):
            return None
        return unpack_entries(
            np.asarray(out["key_words_be"]), np.asarray(out["key_len"]),
            np.asarray(out["seq_hi"]), np.asarray(out["seq_lo"]),
            np.asarray(out["vtype"]), np.asarray(out["val_words"]),
            np.asarray(out["val_len"]), int(out["count"]),
        )


class NumpyCompactionBackend(CompactionBackend):
    """Vectorized CPU implementation of the same algorithm (lexsort +
    reduceat). uint64add / no-operator semantics only; custom operators
    fall back like the TPU backend."""

    name = "numpy"

    def __init__(self, fallback: Optional[CompactionBackend] = None):
        self._fallback = fallback or CpuCompactionBackend()

    def merge_runs(self, runs, merge_op, drop_tombstones):
        if merge_op is not None and not isinstance(merge_op, UInt64AddOperator):
            return self._fallback.merge_runs(runs, merge_op, drop_tombstones)
        entries = [e for run in runs for e in run]
        if not entries:
            return iter(())

        def cpu():
            return self._fallback.merge_runs(
                [sorted(entries, key=lambda e: (e[0], -e[1]))],
                merge_op, drop_tombstones,
            )

        if merge_op is not None and any(
            vtype != _DELETE and len(value) != 8
            for _k, _s, vtype, value in entries
        ):
            # uint64-add fold semantics require 8-byte values (see
            # TpuCompactionBackend.merge_runs) — stream path
            return cpu()
        try:
            batch = pack_entries(entries)
        except UnsupportedBatch:
            return cpu()
        if merge_op is None and bool((batch.vtype == _MERGE).any()):
            return cpu()
        arrays, count = cpu_merge_resolve(
            batch, uint64_add=merge_op is not None,
            drop_tombstones=drop_tombstones,
        )
        return iter(unpack_entries(*arrays, count))


def cpu_merge_resolve(
    batch: KVBatch, uint64_add: bool, drop_tombstones: bool
) -> Tuple[tuple, int]:
    """Best-available CPU merge-resolve: the native C implementation
    (storage/native cpu_merge_resolve — packed-record sort + linear
    segment resolve) when the library is loaded, else the numpy path.
    Both are element-exact with the TPU kernel; parity is pinned in
    tests/test_native.py."""
    from ..storage.native.binding import get_native

    lib = get_native()
    if lib is None or not getattr(lib, "has_merge_resolve", False):
        return numpy_merge_resolve(batch, uint64_add, drop_tombstones)
    valid_n = batch.num_valid()
    seq = (
        batch.seq_hi[:valid_n].astype(np.uint64) << np.uint64(32)
    ) | batch.seq_lo[:valid_n].astype(np.uint64)
    out_kw, out_klen, out_seq, out_vtype, out_vw, out_vlen, count = (
        lib.merge_resolve(
            batch.key_words_be[:valid_n], batch.key_len[:valid_n], seq,
            batch.vtype[:valid_n], batch.val_words[:valid_n],
            batch.val_len[:valid_n], uint64_add, drop_tombstones,
        )
    )
    out = (
        out_kw[:count], out_klen[:count],
        (out_seq[:count] >> np.uint64(32)).astype(np.uint32),
        (out_seq[:count] & np.uint64(0xFFFFFFFF)).astype(np.uint32),
        out_vtype[:count].astype(batch.vtype.dtype), out_vw[:count],
        out_vlen[:count],
    )
    return out, count


def numpy_merge_resolve(
    batch: KVBatch, uint64_add: bool, drop_tombstones: bool
) -> Tuple[tuple, int]:
    """The kernel's algorithm in numpy (the CPU baseline)."""
    valid_n = batch.num_valid()
    kw = batch.key_words_be[:valid_n]
    klen = batch.key_len[:valid_n]
    seq = (batch.seq_hi[:valid_n].astype(np.uint64) << np.uint64(32)) | batch.seq_lo[
        :valid_n
    ].astype(np.uint64)
    vtype = batch.vtype[:valid_n]
    vw = batch.val_words[:valid_n]
    vlen = batch.val_len[:valid_n]

    # lexsort: last key has highest priority → (key words asc.., len, seq desc)
    order = np.lexsort(
        (~seq, klen) + tuple(kw[:, w] for w in range(kw.shape[1] - 1, -1, -1))
    )
    kw, klen, seq, vtype, vw, vlen = (
        kw[order], klen[order], seq[order], vtype[order], vw[order], vlen[order]
    )
    n = valid_n
    if n == 0:
        return (batch.key_words_be[:0], batch.key_len[:0], batch.seq_hi[:0],
                batch.seq_lo[:0], batch.vtype[:0], batch.val_words[:0],
                batch.val_len[:0]), 0

    new_key = np.ones(n, dtype=bool)
    if n > 1:
        same = np.all(kw[1:] == kw[:-1], axis=1) & (klen[1:] == klen[:-1])
        new_key[1:] = ~same
    bounds = np.flatnonzero(new_key)
    seg_ids = np.cumsum(new_key) - 1
    pos = np.arange(n)

    is_put = vtype == _PUT
    is_del = vtype == _DELETE
    is_merge = vtype == _MERGE
    is_base = is_put | is_del

    first_base_pos = np.minimum.reduceat(np.where(is_base, pos, n), bounds)
    fb = first_base_pos[seg_ids]
    operand_mask = is_merge & (pos < fb)
    has_op = np.maximum.reduceat(operand_mask.astype(np.int8), bounds).astype(bool)
    base_exists = first_base_pos < n
    base_is_put = np.zeros(len(bounds), dtype=bool)
    base_is_put[base_exists] = is_put[first_base_pos[base_exists]]
    base_is_del = np.zeros(len(bounds), dtype=bool)
    base_is_del[base_exists] = is_del[first_base_pos[base_exists]]

    sums = None
    if uint64_add:
        if vw.shape[1] > 1:
            vals = vw[:, 0].astype(np.int64) | (vw[:, 1].astype(np.int64) << 32)
        else:
            vals = vw[:, 0].astype(np.int64)
        # parity with UInt64AddOperator._parse: non-8-byte values parse as 0
        contrib = (operand_mask | (is_base & (pos == fb) & is_put)) & (vlen == 8)
        # the fold itself (wraparound semantics) is the shared
        # storage/merge implementation — single source of truth with the
        # scalar operator
        from ..storage.merge import uint64add_segment_sums

        sums = uint64add_segment_sums(vals, contrib, bounds)

    # representative = first row of each segment
    rep_idx = bounds
    out_kw = kw[rep_idx]
    out_klen = klen[rep_idx]
    out_seq = seq[rep_idx]
    out_vtype = vtype[rep_idx].copy()
    out_vw = vw[rep_idx].copy()
    out_vlen = vlen[rep_idx].copy()

    if uint64_add:
        pure_operands = has_op & ~base_is_put & ~base_is_del
        resolved_put = base_is_put | (has_op & base_is_del)
        fold_mask = resolved_put | pure_operands
        out_vw[fold_mask, 0] = (sums[fold_mask] & 0xFFFFFFFF).astype(np.uint32)
        if out_vw.shape[1] > 1:
            out_vw[fold_mask, 1] = (
                (sums[fold_mask] >> 32) & 0xFFFFFFFF
            ).astype(np.uint32)
        out_vlen[fold_mask] = 8
        out_vtype[resolved_put] = _PUT
        out_vtype[pure_operands] = _PUT if drop_tombstones else _MERGE
        dropped = base_is_del & ~has_op
    else:
        dropped = out_vtype == _DELETE

    keep = ~dropped if drop_tombstones else np.ones(len(bounds), dtype=bool)
    out = (
        out_kw[keep], out_klen[keep],
        (out_seq[keep] >> np.uint64(32)).astype(np.uint32),
        (out_seq[keep] & np.uint64(0xFFFFFFFF)).astype(np.uint32),
        out_vtype[keep], out_vw[keep], out_vlen[keep],
    )
    return out, int(keep.sum())
