"""TpuCompactionService: shard-batched compaction jobs on the device.

North star (BASELINE.json): "a TpuCompactionService is registered by
ApplicationDBManager so that L0→Ln compaction jobs and load_sst ingests
ship their key-value blocks to a TPU sidecar, where kernels run k-way
merge-sort, bloom construction, and block encoding as batched ops over
shards."

Two integration levels:
- ``install_on_options(options)`` — per-DB: plugs a TpuCompactionBackend
  into the engine's CompactionBackend seam (compact_range / L0→L1 jobs).
- ``compact_shard_batch(batches)`` — job-level: many shards' runs compact
  in ONE vmapped kernel launch (the 1000-shard load_sst path), each shard
  padded to a common capacity; returns per-shard merged entries + bloom
  words + counts.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..observability.span import start_span
from ..storage.bloom import num_words_for
from ..storage.engine import DBOptions
from ..ops.bloom_tpu import bloom_build_tpu
from ..ops.compaction_kernel import (MergeKind, deployment_sort_backend,
                                     merge_resolve_kernel)
from ..ops.kv_format import KEY_WORDS, KVBatch, fast_flags, unpack_entries
from .backend import TpuCompactionBackend, _next_pow2

log = logging.getLogger(__name__)


class TpuCompactionService:
    _instance: Optional["TpuCompactionService"] = None
    _instance_lock = threading.Lock()

    def __init__(self, bits_per_key: int = 10, sort_backend: str = None):
        import jax
        import jax.numpy as jnp

        self._jax = jax
        self._jnp = jnp
        self._bits_per_key = bits_per_key
        # deployment knob: run the service's kernels on the lax sort, the
        # VMEM-resident pallas sort, or the fully-fused pallas kernel —
        # whichever the bench shootout crowned on this hardware. None =
        # resolve the sort_backend FLAG per pipeline build, so a runtime
        # FLAGS.set flip reaches the singleton too (the flag value is
        # part of the pipeline cache key).
        self._sort_backend = sort_backend
        self._vmapped_cache: Dict[tuple, object] = {}

    @classmethod
    def instance(cls) -> "TpuCompactionService":
        if cls._instance is None:
            with cls._instance_lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance

    # ------------------------------------------------------------------
    # per-DB integration (engine CompactionBackend seam)
    # ------------------------------------------------------------------

    @staticmethod
    def install_on_options(options: DBOptions) -> DBOptions:
        """Route this DB's compactions through the TPU backend."""
        options.compaction_backend = TpuCompactionBackend()
        return options

    # ------------------------------------------------------------------
    # job-level batched API (the load_sst / compaction-storm path)
    # ------------------------------------------------------------------

    def _pipeline(self, merge_kind: MergeKind, drop_tombstones: bool,
                  num_words: int, uniform_klen: bool = False,
                  seq32: bool = False, key_words: int = KEY_WORDS):
        sort_backend = self._sort_backend or deployment_sort_backend()
        key = (merge_kind, drop_tombstones, num_words, uniform_klen, seq32,
               key_words, sort_backend)
        fn = self._vmapped_cache.get(key)
        if fn is None:
            jax = self._jax

            def one_shard(kwbe, klen, shi, slo, vt, vw, vl, valid):
                out = merge_resolve_kernel(
                    kwbe, klen, shi, slo, vt, vw, vl, valid,
                    merge_kind=merge_kind, drop_tombstones=drop_tombstones,
                    uniform_klen=uniform_klen, seq32=seq32,
                    key_words=key_words, sort_backend=sort_backend,
                )
                out_valid = (
                    jax.lax.iota(jax.numpy.int32, klen.shape[0]) < out["count"]
                )
                bloom = bloom_build_tpu(
                    out["key_words_le"], out["key_len"], out_valid,
                    num_words=num_words,
                )
                out["bloom"] = bloom
                return out

            fn = jax.jit(jax.vmap(one_shard))
            self._vmapped_cache[key] = fn
        return fn

    def compact_shard_batch(
        self,
        batches: Sequence[KVBatch],
        merge_kind: MergeKind = MergeKind.UINT64_ADD,
        drop_tombstones: bool = True,
    ) -> List[dict]:
        """Compact many shards in one launch. Returns, per shard:
        {"entries": [(key, seq, vtype, value)], "bloom_words": np.ndarray,
        "count": int}."""
        if not batches:
            return []
        capacity = _next_pow2(max(b.capacity for b in batches))
        num_words = num_words_for(capacity, self._bits_per_key)
        jnp = self._jnp
        # The job-level trace answers "where does a shard-batch's wall
        # clock go": host stack+H2D staging vs kernel+D2H readback vs
        # host unpack — the split the round-1 profile found dominated by
        # transfer (SURVEY §7), now attributable per job.
        with start_span("tpu.compact_batch", always=True,
                        shards=len(batches), capacity=capacity) as jsp:
            with start_span("tpu.stage"):
                stacked = {
                    name: jnp.asarray(np.stack([
                        _pad_to(getattr(b, name), capacity) for b in batches
                    ]))
                    for name in (
                        "key_words_be", "key_len", "seq_hi",
                        "seq_lo", "vtype", "val_words", "val_len", "valid",
                    )
                }
            flags = [fast_flags(b.key_len, b.seq_hi, b.valid)
                     for b in batches]
            uniform_klen = all(u for u, _, _ in flags)
            seq32 = all(s for _, s, _ in flags)
            key_words = max(k for _, _, k in flags)
            fn = self._pipeline(merge_kind, drop_tombstones, num_words,
                                uniform_klen, seq32, key_words)
            with start_span("tpu.kernel"):
                out = fn(
                    stacked["key_words_be"],
                    stacked["key_len"], stacked["seq_hi"], stacked["seq_lo"],
                    stacked["vtype"], stacked["val_words"],
                    stacked["val_len"], stacked["valid"],
                )
                # np.asarray blocks on the device: readback time lands in
                # the kernel span (dispatch is async; the two are not
                # separable without a device profiler)
                host = {k: np.asarray(v) for k, v in out.items()}
            results = []
            fallbacks = 0
            with start_span("tpu.unpack"):
                for s in range(len(batches)):
                    if bool(host["needs_cpu_fallback"][s]):
                        fallbacks += 1
                        results.append(self._cpu_recompute(
                            batches[s], merge_kind, drop_tombstones,
                            num_words))
                        continue
                    count = int(host["count"][s])
                    entries = unpack_entries(
                        host["key_words_be"][s], host["key_len"][s],
                        host["seq_hi"][s], host["seq_lo"][s],
                        host["vtype"][s], host["val_words"][s],
                        host["val_len"][s], count,
                    )
                    results.append({
                        "entries": entries,
                        "bloom_words": host["bloom"][s],
                        "count": count,
                    })
            if fallbacks:
                jsp.annotate(cpu_fallbacks=fallbacks)
            return results

    def compact_shard_stream(
        self,
        batches: Sequence[KVBatch],
        merge_kind: MergeKind = MergeKind.UINT64_ADD,
        drop_tombstones: bool = True,
        group_size: int = 8,
    ) -> List[dict]:
        """Pipelined variant of compact_shard_batch for big shard counts:
        shards run in fixed-size groups with double-buffered transfers —
        group i+1's H2D upload is issued while group i's kernel runs, and
        group i's D2H readback happens under group i+1's compute
        (device_put and jit dispatch are async; only np.asarray blocks).
        One compiled shape serves every group (the last one is padded
        with empty shards). Addresses the round-1 finding that H2D
        staging cost ~3.7x the kernel (SURVEY §7 front-load item 2)."""
        if not batches:
            return []
        with start_span("tpu.compact_stream", always=True,
                        shards=len(batches), group_size=group_size):
            return self._compact_shard_stream(
                batches, merge_kind, drop_tombstones, group_size)

    def _compact_shard_stream(self, batches, merge_kind, drop_tombstones,
                              group_size):
        jax = self._jax
        capacity = _next_pow2(max(b.capacity for b in batches))
        num_words = num_words_for(capacity, self._bits_per_key)
        flags = [fast_flags(b.key_len, b.seq_hi, b.valid) for b in batches]
        uniform_klen = all(u for u, _, _ in flags)
        seq32 = all(s for _, s, _ in flags)
        key_words = max(k for _, _, k in flags)
        fn = self._pipeline(merge_kind, drop_tombstones, num_words,
                            uniform_klen, seq32, key_words)
        names = (
            "key_words_be", "key_len", "seq_hi",
            "seq_lo", "vtype", "val_words", "val_len", "valid",
        )

        def stage(lo: int) -> Dict[str, object]:
            """Stack one group on host and issue its async H2D."""
            group = list(batches[lo:lo + group_size])
            pad_shards = group_size - len(group)
            stacked = {}
            for name in names:
                arr = np.stack([_pad_to(getattr(b, name), capacity)
                                for b in group])
                if pad_shards:
                    arr = np.pad(
                        arr, [(0, pad_shards)] + [(0, 0)] * (arr.ndim - 1))
                stacked[name] = jax.device_put(arr)
            return stacked

        groups = list(range(0, len(batches), group_size))
        results: List[dict] = []
        pending: List[Tuple[int, dict]] = []  # (group_lo, device outputs)
        dev = stage(groups[0])
        for gi, lo in enumerate(groups):
            out = fn(*(dev[name] for name in names))  # async dispatch
            if gi + 1 < len(groups):
                dev = stage(groups[gi + 1])  # H2D overlaps the kernel
            pending.append((lo, out))
            # drain the PREVIOUS group while this one computes: its
            # np.asarray blocks only on already-finished work
            if len(pending) > 1:
                results.extend(self._drain(
                    *pending.pop(0), batches, merge_kind, drop_tombstones,
                    num_words))
        while pending:
            results.extend(self._drain(
                *pending.pop(0), batches, merge_kind, drop_tombstones,
                num_words))
        return results

    def _drain(self, lo: int, out, batches, merge_kind, drop_tombstones,
               num_words) -> List[dict]:
        """Readback + unpack one group's device outputs."""
        host = {k: np.asarray(v) for k, v in out.items()}
        group = batches[lo:lo + len(host["count"])]
        results = []
        for s in range(min(len(group), len(host["count"]))):
            if bool(host["needs_cpu_fallback"][s]):
                results.append(self._cpu_recompute(
                    group[s], merge_kind, drop_tombstones, num_words))
                continue
            count = int(host["count"][s])
            entries = unpack_entries(
                host["key_words_be"][s], host["key_len"][s],
                host["seq_hi"][s], host["seq_lo"][s], host["vtype"][s],
                host["val_words"][s], host["val_len"][s], count,
            )
            results.append({
                "entries": entries,
                "bloom_words": host["bloom"][s],
                "count": count,
            })
        return results

    def _cpu_recompute(self, batch: KVBatch, merge_kind: MergeKind,
                       drop_tombstones: bool, num_words: int) -> dict:
        """Host recompute for shards the kernel flagged (e.g. one key with
        ≥2^16 operands — beyond the limb-sum range). ``num_words`` is the
        job-wide bloom size so fallback blooms stay interchangeable with
        the TPU-built ones."""
        from ..storage.bloom import BloomFilter
        from ..storage.native.binding import get_native
        from .backend import cpu_merge_resolve

        arrays, count = cpu_merge_resolve(
            batch, uint64_add=merge_kind is MergeKind.UINT64_ADD,
            drop_tombstones=drop_tombstones,
        )
        entries = unpack_entries(*arrays, count)
        bf = BloomFilter(num_words)
        lib = get_native()
        if lib is not None and count:
            # bulk path into the job-pinned words array (build_from_arrays
            # would size its own filter)
            kb = (np.ascontiguousarray(arrays[0][:count].astype(">u4"))
                  .view(np.uint8).reshape(count, -1))
            lens = np.asarray(arrays[1][:count], dtype=np.uint64)
            lens = np.minimum(lens, np.uint64(kb.shape[1]))
            mask = (np.arange(kb.shape[1], dtype=np.uint64)[None, :]
                    < lens[:, None])
            offsets = np.zeros(count + 1, dtype=np.uint64)
            np.cumsum(lens, out=offsets[1:])
            lib.bloom_add_concat(bf.words, kb[mask], offsets, count)
        else:
            for key, _seq, _vt, _val in entries:
                bf.add(key)
        return {"entries": entries, "bloom_words": bf.words, "count": count}


def _pad_to(arr: np.ndarray, capacity: int) -> np.ndarray:
    if arr.shape[0] == capacity:
        return arr
    pad = [(0, capacity - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad)
