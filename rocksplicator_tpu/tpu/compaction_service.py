"""TpuCompactionService: shard-batched compaction jobs on the device.

North star (BASELINE.json): "a TpuCompactionService is registered by
ApplicationDBManager so that L0→Ln compaction jobs and load_sst ingests
ship their key-value blocks to a TPU sidecar, where kernels run k-way
merge-sort, bloom construction, and block encoding as batched ops over
shards."

Two integration levels:
- ``install_on_options(options)`` — per-DB: plugs a TpuCompactionBackend
  into the engine's CompactionBackend seam (compact_range / L0→L1 jobs).
- ``compact_shard_batch(batches)`` — job-level: many shards' runs compact
  in ONE vmapped kernel launch (the 1000-shard load_sst path), each shard
  padded to a common capacity; returns per-shard merged entries + bloom
  words + counts.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..observability.span import start_span
from ..storage.bloom import num_words_for
from ..storage.engine import DBOptions
from ..ops.bloom_tpu import bloom_build_tpu
from ..ops.compaction_kernel import (MergeKind, deployment_sort_backend,
                                     merge_resolve_kernel)
from ..ops.kv_format import KEY_WORDS, KVBatch, fast_flags, unpack_entries
from .backend import TpuCompactionBackend, _next_pow2

log = logging.getLogger(__name__)


class TpuCompactionService:
    _instance: Optional["TpuCompactionService"] = None
    _instance_lock = threading.Lock()

    def __init__(self, bits_per_key: int = 10, sort_backend: str = None):
        import jax
        import jax.numpy as jnp

        self._jax = jax
        self._jnp = jnp
        self._bits_per_key = bits_per_key
        # deployment knob: run the service's kernels on the lax sort, the
        # VMEM-resident pallas sort, or the fully-fused pallas kernel —
        # whichever the bench shootout crowned on this hardware. None =
        # resolve the sort_backend FLAG per pipeline build, so a runtime
        # FLAGS.set flip reaches the singleton too (the flag value is
        # part of the pipeline cache key).
        self._sort_backend = sort_backend
        self._vmapped_cache: Dict[tuple, object] = {}

    @classmethod
    def instance(cls) -> "TpuCompactionService":
        if cls._instance is None:
            with cls._instance_lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance

    # ------------------------------------------------------------------
    # per-DB integration (engine CompactionBackend seam)
    # ------------------------------------------------------------------

    @staticmethod
    def install_on_options(options: DBOptions) -> DBOptions:
        """Route this DB's compactions through the TPU backend."""
        options.compaction_backend = TpuCompactionBackend()
        return options

    # ------------------------------------------------------------------
    # job-level batched API (the load_sst / compaction-storm path)
    # ------------------------------------------------------------------

    def _pipeline(self, merge_kind: MergeKind, drop_tombstones: bool,
                  num_words: int, uniform_klen: bool = False,
                  seq32: bool = False, key_words: int = KEY_WORDS):
        sort_backend = self._sort_backend or deployment_sort_backend()
        key = (merge_kind, drop_tombstones, num_words, uniform_klen, seq32,
               key_words, sort_backend)
        fn = self._vmapped_cache.get(key)
        if fn is None:
            jax = self._jax

            def one_shard(kwbe, klen, shi, slo, vt, vw, vl, valid):
                out = merge_resolve_kernel(
                    kwbe, klen, shi, slo, vt, vw, vl, valid,
                    merge_kind=merge_kind, drop_tombstones=drop_tombstones,
                    uniform_klen=uniform_klen, seq32=seq32,
                    key_words=key_words, sort_backend=sort_backend,
                )
                out_valid = (
                    jax.lax.iota(jax.numpy.int32, klen.shape[0]) < out["count"]
                )
                bloom = bloom_build_tpu(
                    out["key_words_le"], out["key_len"], out_valid,
                    num_words=num_words,
                )
                out["bloom"] = bloom
                return out

            fn = jax.jit(jax.vmap(one_shard))
            self._vmapped_cache[key] = fn
        return fn

    def compact_shard_batch(
        self,
        batches: Sequence[KVBatch],
        merge_kind: MergeKind = MergeKind.UINT64_ADD,
        drop_tombstones: bool = True,
        return_arrays: bool = False,
    ) -> List[dict]:
        """Compact many shards in one launch. Returns, per shard:
        {"entries": [(key, seq, vtype, value)], "bloom_words": np.ndarray,
        "count": int} — or, with ``return_arrays``, {"arrays": lane dict,
        "bloom_words", "count"} with NO per-entry tuple unpacking (the
        array-native sink path: callers feed the lanes straight to
        write_sst_from_arrays)."""
        if not batches:
            return []
        capacity = _next_pow2(max(b.capacity for b in batches))
        num_words = num_words_for(capacity, self._bits_per_key)
        jnp = self._jnp
        # The job-level trace answers "where does a shard-batch's wall
        # clock go": host stack+H2D staging vs kernel+D2H readback vs
        # host unpack — the split the round-1 profile found dominated by
        # transfer (SURVEY §7), now attributable per job.
        with start_span("tpu.compact_batch", always=True,
                        shards=len(batches), capacity=capacity) as jsp:
            with start_span("tpu.stage"):
                stacked = {
                    name: jnp.asarray(np.stack([
                        _pad_to(getattr(b, name), capacity) for b in batches
                    ]))
                    for name in (
                        "key_words_be", "key_len", "seq_hi",
                        "seq_lo", "vtype", "val_words", "val_len", "valid",
                    )
                }
            flags = [fast_flags(b.key_len, b.seq_hi, b.valid)
                     for b in batches]
            uniform_klen = all(u for u, _, _ in flags)
            seq32 = all(s for _, s, _ in flags)
            key_words = max(k for _, _, k in flags)
            fn = self._pipeline(merge_kind, drop_tombstones, num_words,
                                uniform_klen, seq32, key_words)
            with start_span("tpu.kernel"):
                out = fn(
                    stacked["key_words_be"],
                    stacked["key_len"], stacked["seq_hi"], stacked["seq_lo"],
                    stacked["vtype"], stacked["val_words"],
                    stacked["val_len"], stacked["valid"],
                )
                # np.asarray blocks on the device: readback time lands in
                # the kernel span (dispatch is async; the two are not
                # separable without a device profiler)
                host = {k: np.asarray(v) for k, v in out.items()}
            results = []
            fallbacks = 0
            with start_span("tpu.unpack"):
                for s in range(len(batches)):
                    if bool(host["needs_cpu_fallback"][s]):
                        fallbacks += 1
                        results.append(self._cpu_recompute(
                            batches[s], merge_kind, drop_tombstones,
                            num_words, return_arrays=return_arrays))
                        continue
                    results.append(_shard_result(
                        host, s, int(host["count"][s]), return_arrays))
            if fallbacks:
                jsp.annotate(cpu_fallbacks=fallbacks)
            return results

    def compact_shard_stream(
        self,
        batches: Sequence[KVBatch],
        merge_kind: MergeKind = MergeKind.UINT64_ADD,
        drop_tombstones: bool = True,
        group_size: int = 8,
        return_arrays: bool = False,
    ) -> List[dict]:
        """Pipelined variant of compact_shard_batch for big shard counts:
        shards run in fixed-size groups with double-buffered transfers —
        group i+1's H2D upload is issued while group i's kernel runs, and
        group i's D2H readback happens under group i+1's compute
        (device_put and jit dispatch are async; only np.asarray blocks).
        One compiled shape serves every group (the last one is padded
        with empty shards). Addresses the round-1 finding that H2D
        staging cost ~3.7x the kernel (SURVEY §7 front-load item 2)."""
        if not batches:
            return []
        with start_span("tpu.compact_stream", always=True,
                        shards=len(batches), group_size=group_size):
            return self._compact_shard_stream(
                batches, merge_kind, drop_tombstones, group_size,
                return_arrays)

    def _compact_shard_stream(self, batches, merge_kind, drop_tombstones,
                              group_size, return_arrays=False):
        jax = self._jax
        capacity = _next_pow2(max(b.capacity for b in batches))
        num_words = num_words_for(capacity, self._bits_per_key)
        flags = [fast_flags(b.key_len, b.seq_hi, b.valid) for b in batches]
        uniform_klen = all(u for u, _, _ in flags)
        seq32 = all(s for _, s, _ in flags)
        key_words = max(k for _, _, k in flags)
        fn = self._pipeline(merge_kind, drop_tombstones, num_words,
                            uniform_klen, seq32, key_words)
        names = (
            "key_words_be", "key_len", "seq_hi",
            "seq_lo", "vtype", "val_words", "val_len", "valid",
        )

        def stage(lo: int) -> Dict[str, object]:
            """Stack one group on host and issue its async H2D."""
            group = list(batches[lo:lo + group_size])
            pad_shards = group_size - len(group)
            stacked = {}
            for name in names:
                arr = np.stack([_pad_to(getattr(b, name), capacity)
                                for b in group])
                if pad_shards:
                    arr = np.pad(
                        arr, [(0, pad_shards)] + [(0, 0)] * (arr.ndim - 1))
                stacked[name] = jax.device_put(arr)
            return stacked

        groups = list(range(0, len(batches), group_size))
        results: List[dict] = []
        pending: List[Tuple[int, dict]] = []  # (group_lo, device outputs)
        dev = stage(groups[0])
        for gi, lo in enumerate(groups):
            out = fn(*(dev[name] for name in names))  # async dispatch
            if gi + 1 < len(groups):
                dev = stage(groups[gi + 1])  # H2D overlaps the kernel
            pending.append((lo, out))
            # drain the PREVIOUS group while this one computes: its
            # np.asarray blocks only on already-finished work
            if len(pending) > 1:
                results.extend(self._drain(
                    *pending.pop(0), batches, merge_kind, drop_tombstones,
                    num_words, return_arrays))
        while pending:
            results.extend(self._drain(
                *pending.pop(0), batches, merge_kind, drop_tombstones,
                num_words, return_arrays))
        return results

    def _drain(self, lo: int, out, batches, merge_kind, drop_tombstones,
               num_words, return_arrays=False) -> List[dict]:
        """Readback + unpack one group's device outputs."""
        host = {k: np.asarray(v) for k, v in out.items()}
        group = batches[lo:lo + len(host["count"])]
        results = []
        for s in range(min(len(group), len(host["count"]))):
            if bool(host["needs_cpu_fallback"][s]):
                results.append(self._cpu_recompute(
                    group[s], merge_kind, drop_tombstones, num_words,
                    return_arrays=return_arrays))
                continue
            results.append(_shard_result(
                host, s, int(host["count"][s]), return_arrays))
        return results

    def _cpu_recompute(self, batch: KVBatch, merge_kind: MergeKind,
                       drop_tombstones: bool, num_words: int,
                       return_arrays: bool = False) -> dict:
        """Host recompute for shards the kernel flagged (e.g. one key with
        ≥2^16 operands — beyond the limb-sum range). ``num_words`` is the
        job-wide bloom size so fallback blooms stay interchangeable with
        the TPU-built ones."""
        from ..storage.bloom import BloomFilter
        from ..storage.native.binding import get_native
        from .backend import cpu_merge_resolve

        arrays, count = cpu_merge_resolve(
            batch, uint64_add=merge_kind is MergeKind.UINT64_ADD,
            drop_tombstones=drop_tombstones,
        )
        bf = BloomFilter(num_words)
        lib = get_native()
        if lib is not None and count:
            # bulk path into the job-pinned words array (build_from_arrays
            # would size its own filter)
            kb = (np.ascontiguousarray(arrays[0][:count].astype(">u4"))
                  .view(np.uint8).reshape(count, -1))
            lens = np.asarray(arrays[1][:count], dtype=np.uint64)
            lens = np.minimum(lens, np.uint64(kb.shape[1]))
            mask = (np.arange(kb.shape[1], dtype=np.uint64)[None, :]
                    < lens[:, None])
            offsets = np.zeros(count + 1, dtype=np.uint64)
            np.cumsum(lens, out=offsets[1:])
            lib.bloom_add_concat(bf.words, kb[mask], offsets, count)
        else:
            for key, _seq, _vt, _val in unpack_entries(*arrays, count):
                bf.add(key)
        if return_arrays:
            kw_be, klen, seq_hi, seq_lo, vtype, vw, vlen = (
                a[:count] for a in arrays)
            lanes = {
                "key_words_be": kw_be,
                # LE word values are the same key bytes read little-endian
                # — a per-element byteswap of the BE values
                "key_words_le": kw_be.byteswap(),
                "key_len": klen, "seq_hi": seq_hi, "seq_lo": seq_lo,
                "vtype": vtype, "val_words": vw, "val_len": vlen,
            }
            return {"arrays": lanes, "bloom_words": bf.words, "count": count}
        return {"entries": unpack_entries(*arrays, count),
                "bloom_words": bf.words, "count": count}


def _pad_to(arr: np.ndarray, capacity: int) -> np.ndarray:
    if arr.shape[0] == capacity:
        return arr
    pad = [(0, capacity - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad)


# lane names carried through the arrays-native result path (matches
# tpu/chunked.FIELDS; redeclared to avoid importing chunked at call time)
_LANES = (
    "key_words_be", "key_words_le", "key_len", "seq_hi", "seq_lo",
    "vtype", "val_words", "val_len",
)


def _shard_result(host: Dict[str, np.ndarray], s: int, count: int,
                  return_arrays: bool) -> dict:
    """One shard's result from stacked device outputs: lane views (no
    per-entry work) or unpacked tuples."""
    if return_arrays:
        return {
            "arrays": {f: host[f][s][:count] for f in _LANES},
            "bloom_words": host["bloom"][s],
            "count": count,
        }
    return {
        "entries": unpack_entries(
            host["key_words_be"][s], host["key_len"][s],
            host["seq_hi"][s], host["seq_lo"][s],
            host["vtype"][s], host["val_words"][s],
            host["val_len"][s], count,
        ),
        "bloom_words": host["bloom"][s],
        "count": count,
    }


# ---------------------------------------------------------------------------
# key-range subcompactions as one device batch (round 16)
# ---------------------------------------------------------------------------


def resolve_slices_batched(
    slice_lanes: List[Dict[str, np.ndarray]],
    merge_kind: "MergeKind",
    drop_tombstones: bool,
) -> List[Tuple[dict, int]]:
    """ONE compaction's key-range slices resolved as ONE padded vmapped
    device launch — the TPU face of subcompactions: each slice is a
    "shard" of the job, padded to the common pow2 capacity exactly like
    the cross-db batched path, so k smaller sorts ride one launch
    instead of one pow2(total) sort. Returns per-slice
    ``(lane_arrays, count)`` in input order (empty slices come back as
    ``({}, 0)``); slice boundaries are keys, so MERGE operand groups
    are never split across slices by construction."""
    from ..testing import failpoints as fp
    from ..utils.stats import Stats

    out: List[Tuple[dict, int]] = [({}, 0)] * len(slice_lanes)
    batches: List[_LaneBatch] = []
    index: List[int] = []
    for i, lanes in enumerate(slice_lanes):
        if lanes["key_len"].shape[0] == 0:
            continue
        fp.hit("compact.subcompact")
        Stats.get().incr("compaction.subcompactions")
        batches.append(_LaneBatch(lanes))
        index.append(i)
    if batches:
        svc = TpuCompactionService.instance()
        results = svc.compact_shard_batch(
            batches, merge_kind=merge_kind,
            drop_tombstones=drop_tombstones, return_arrays=True)
        for i, res in zip(index, results):
            out[i] = (res["arrays"], int(res["count"]))
    return out


# ---------------------------------------------------------------------------
# cross-DB batched full compaction (the post-load_sst path)
# ---------------------------------------------------------------------------

_PUT, _DELETE, _MERGE = 1, 2, 3

# One shard above this entry count would inflate the whole padded launch
# (every shard pays the max shard's capacity); such shards compact per-db.
MAX_BATCHED_DB_ENTRIES = 1 << 20


class _LaneBatch:
    """Duck-typed KVBatch over pre-read lane arrays — the arrays-native
    input to compact_shard_batch/stream (no per-entry pack loop)."""

    __slots__ = ("key_words_be", "key_words_le", "key_len", "seq_hi",
                 "seq_lo", "vtype", "val_words", "val_len", "valid")

    def __init__(self, lanes: Dict[str, np.ndarray]):
        for f in _LANES:
            setattr(self, f, lanes[f])
        self.valid = np.ones(lanes["key_len"].shape[0], dtype=bool)

    @property
    def capacity(self) -> int:
        return self.key_len.shape[0]


def _db_lanes(plan: dict) -> Optional[Dict[str, np.ndarray]]:
    """A plan's input runs as one concatenated lane dict (planar/uniform
    files decode straight to lanes; row-format files pay one pack). None
    when the lane representation can't express a run."""
    from ..ops.kv_format import UnsupportedBatch
    from .backend import _arrays_from_entries
    from .chunked import FIELDS
    from .format import read_sst_arrays

    parts: List[dict] = []
    try:
        for r in plan["runs"]:
            arr = read_sst_arrays(r)
            if arr is None:
                arr = _arrays_from_entries(list(r.iterate()))
            if arr is not None:
                parts.append(arr)
    except UnsupportedBatch as e:
        log.debug("batched compaction lane read declined: %s", e)
        return None
    if not parts:
        return None
    vw = max(p["val_words"].shape[1] for p in parts)
    for p in parts:
        w = p["val_words"].shape[1]
        if w < vw:
            p["val_words"] = np.pad(p["val_words"], [(0, 0), (0, vw - w)])
    return {f: np.concatenate([p[f] for p in parts]) for f in FIELDS}


def _install_arrays(db, plan: dict, res: dict) -> None:
    """Write one shard's resolved lanes as PLANAR SSTs (vectorized sink,
    kernel-built per-file blooms) and install them; falls back to the
    entry-tuple sink when the planar layout can't express the result."""
    from ..storage.bloom import num_words_for as bloom_words_for
    from .format import planar_stride, planar_widths, write_sst_from_arrays

    arrays, count = res["arrays"], int(res["count"])
    if count == 0:
        db.install_full_compaction(plan, entries=[])
        return
    widths = planar_widths(arrays, count)
    if widths is not None:
        import jax.numpy as jnp

        opts = db.options
        stride = planar_stride(*widths)
        entries_per_file = max(1024, opts.target_file_bytes // max(1, stride))
        block_entries = max(64, opts.block_bytes // max(1, stride))
        names: List[str] = []
        paths: List[str] = []
        ok = True
        for start in range(0, count, entries_per_file):
            end = min(start + entries_per_file, count)
            sub = {f: arrays[f][start:end] for f in arrays}
            # per-file bloom sized from THIS file's count and the DB's own
            # bits_per_key — the job-level bloom is sized by the group's
            # padded max capacity (and the service default bits), so
            # reusing it would write a max-shard-sized bloom into every
            # small shard of a mixed batch
            bloom = np.asarray(bloom_build_tpu(
                jnp.asarray(sub["key_words_le"]),
                jnp.asarray(sub["key_len"]),
                jnp.asarray(np.ones(end - start, dtype=bool)),
                num_words=bloom_words_for(end - start, opts.bits_per_key),
            ))
            name, path = db.allocate_sst()
            props = write_sst_from_arrays(
                sub, end - start, path, bloom_words=bloom,
                block_entries=block_entries, compression=opts.compression,
                bits_per_key=opts.bits_per_key, planar=True,
            )
            if props is None:
                ok = False
                for p in paths:
                    try:
                        os.remove(p)
                    except OSError:
                        pass
                break
            names.append(name)
            paths.append(path)
        if ok:
            db.install_full_compaction(plan, files=names)
            return
    # tuple fallback (non-uniform keys/values)
    entries = unpack_entries(
        arrays["key_words_be"], arrays["key_len"], arrays["seq_hi"],
        arrays["seq_lo"], arrays["vtype"], arrays["val_words"],
        arrays["val_len"], count,
    )
    db.install_full_compaction(plan, entries=entries)


def compact_dbs_batched(dbs, group_size: int = 8, pool=None):
    """Fully compact many DBs' key spaces with batched device launches —
    the cross-shard post-load compaction: N shards' merge-resolve runs as
    vmapped groups over one padded shape instead of N per-db pipelines,
    arrays end to end (runs decode to lanes, the resolved lanes write
    through the PLANAR sink — no per-entry Python on either side). The
    per-db host stages (plan + lane read, then SST write + install) fan
    out over ``pool`` (any Executor) when given; only the device launch
    is centralized.

    Per DB: plan (engine plan_full_compaction: flush + snapshot under the
    compaction mutex), read its runs as lanes, launch the group, install
    each shard's output files (engine install_full_compaction). DBs the
    lane representation can't express (custom merge operators, >24B keys,
    wide values, MERGE records with no operator, oversized shards) are
    declined untouched.

    Returns ``(handled, remaining)``: db names compacted here, and the
    (name, db) pairs the caller must compact per-db (compact_range).
    """
    from ..storage.merge import UInt64AddOperator

    dbs = list(dbs)
    handled: List[str] = []
    remaining: List[tuple] = []
    groups: Dict[tuple, List[tuple]] = {}  # (kind, drop) -> items
    # every un-consumed plan holds its DB's compaction mutex; the finally
    # below releases any leaked by an unexpected raise so the caller's
    # per-db compact_range fallback can never deadlock
    pending: Dict[int, tuple] = {}
    pending_lock = threading.Lock()

    def _track(db, plan):
        with pending_lock:
            pending[id(plan)] = (db, plan)

    def _untrack(plan):
        with pending_lock:
            pending.pop(id(plan), None)

    def _abort(db, plan):
        _untrack(plan)
        db.abort_full_compaction(plan)

    def _pmap(fn, items):
        if pool is None or len(items) <= 1:
            return [fn(it) for it in items]
        return list(pool.map(fn, items))

    def _stage(item):
        """(name, db) → ("handled"|"remaining"|("grouped", key, payload)).

        MUST NOT raise: staging runs through pool.map, and an exception
        there returns control to the caller while sibling _stage tasks
        are still acquiring compaction mutexes — a raced finally-sweep
        could then miss a just-tracked plan and leak its mutex forever.
        Any failure (corrupt SST read, OSError, ...) declines the db to
        the per-db compact_range fallback instead."""
        name, db = item
        merge_op = db.options.merge_operator
        if merge_op is not None and not isinstance(
                merge_op, UInt64AddOperator):
            return ("remaining", name, db, None)
        try:
            plan = db.plan_full_compaction()
        except BaseException:
            log.exception("plan failed for %s; declining to per-db", name)
            return ("remaining", name, db, None)
        if plan is None:
            return ("handled", name, db, None)  # nothing to compact
        _track(db, plan)
        try:
            lanes = _db_lanes(plan)
        except BaseException:
            log.exception(
                "lane read failed for %s; declining to per-db", name)
            _abort(db, plan)
            return ("remaining", name, db, None)
        total = lanes["key_len"].shape[0] if lanes is not None else 0
        if (
            lanes is None
            or total == 0
            or total > MAX_BATCHED_DB_ENTRIES
            # uint64-add fold needs 8-byte values (backend.py parity)
            or (merge_op is not None and bool(
                ((lanes["vtype"] != _DELETE)
                 & (lanes["val_len"] != 8)).any()))
            # MERGE records without an operator: CPU path only
            or (merge_op is None and bool((lanes["vtype"] == _MERGE).any()))
        ):
            _abort(db, plan)
            return ("remaining", name, db, None)
        kind = (
            MergeKind.UINT64_ADD if merge_op is not None else MergeKind.NONE
        )
        key = (kind, plan["drop_tombstones"])
        return ("grouped", name, db, (key, plan, _LaneBatch(lanes)))

    def _install(args):
        name, db, plan, res = args
        _untrack(plan)  # install consumes the plan either way
        try:
            _install_arrays(db, plan, res)
            return ("handled", name, db)
        except BaseException:
            # the mutex was released in install's finally; a per-db
            # retry via compact_range is safe
            log.exception(
                "batched compaction install failed for %s; "
                "will re-compact per-db", name)
            return ("remaining", name, db)

    try:
        with start_span("admin.compact_stage", shards=len(dbs)):
            staged = _pmap(_stage, dbs)
        for verdict, name, db, payload in staged:
            if verdict == "handled":
                handled.append(name)
            elif verdict == "remaining":
                remaining.append((name, db))
            else:
                key, plan, batch = payload
                groups.setdefault(key, []).append((name, db, plan, batch))

        svc = TpuCompactionService.instance()
        for (kind, drop), items in groups.items():
            batches = [b for _n, _d, _p, b in items]
            vw = max(b.val_words.shape[1] for b in batches)
            for b in batches:  # group-uniform value lanes for np.stack
                w = b.val_words.shape[1]
                if w < vw:
                    b.val_words = np.pad(
                        b.val_words, [(0, 0), (0, vw - w)])
            try:
                if len(batches) > group_size:
                    # one compiled (group_size, capacity) shape serves
                    # every group; H2D of group i+1 overlaps group i's
                    # kernel
                    results = svc.compact_shard_stream(
                        batches, merge_kind=kind, drop_tombstones=drop,
                        group_size=group_size, return_arrays=True)
                else:
                    results = svc.compact_shard_batch(
                        batches, merge_kind=kind, drop_tombstones=drop,
                        return_arrays=True)
            except BaseException:
                log.exception(
                    "batched compaction launch failed (%d shards); "
                    "falling back per-db", len(items))
                for name, db, plan, _b in items:
                    _abort(db, plan)
                    remaining.append((name, db))
                continue
            installs = [(name, db, plan, res) for (name, db, plan, _b), res
                        in zip(items, results)]
            with start_span("admin.compact_install", shards=len(installs)):
                installed = _pmap(_install, installs)
            for verdict, name, db in installed:
                if verdict == "handled":
                    handled.append(name)
                else:
                    remaining.append((name, db))
        return handled, remaining
    finally:
        with pending_lock:
            leaked = list(pending.values())
            pending.clear()
        for db, plan in leaked:
            try:
                db.abort_full_compaction(plan)
            except Exception:
                pass


# ---------------------------------------------------------------------------
# streaming bounded-memory compaction: the device chunk resolver
# ---------------------------------------------------------------------------


class TpuChunkResolver:
    """The TPU face of the streaming chunked merge
    (storage/stream_merge.py): each merge chunk launches the
    merge-resolve kernel with ``to_host=False`` so the output lanes stay
    DEVICE-resident at submit; ``collect`` materializes them to host one
    chunk later. The pipeline decodes chunk N+1's windows between
    submit(N) and collect(N), so host decode (70% of a large compaction,
    GIL-bound) overlaps chunk N's DEVICE→HOST transfer — the
    double-buffered chunk shape LUDA (arxiv 2004.03054) uses and the
    silicon bench needs. Honest scope: submit() still synchronizes on
    the kernel itself (``run_kernel_arrays`` reads the
    ``needs_cpu_fallback`` flag and count as Python scalars, forcing
    the launch), so today only the transfer overlaps the next decode;
    overlapping the resolve too needs an async fallback flag — silicon
    follow-on work. Chunks pad to the next pow2 of the window total,
    so steady-state launches reuse one compiled shape."""

    # chunk lanes carry LE key words too (device bloom hashing)
    from .chunked import FIELDS as fields
    pipelined = True  # one chunk stays in flight behind the decode

    def submit(self, parts, lanes, total: int, vw: int, merge_op,
               drop_tombstones: bool):
        from ..storage.merge import UInt64AddOperator
        from ..storage.stream_merge import _StreamDecline
        from .chunked import run_kernel_arrays

        kind = (
            MergeKind.UINT64_ADD
            if isinstance(merge_op, UInt64AddOperator) else MergeKind.NONE
        )
        uniform_klen, seq32, key_words = fast_flags(
            lanes["key_len"], lanes["seq_hi"],
            np.ones(total, dtype=bool))
        arrays, count = run_kernel_arrays(
            lanes, total, kind, drop_tombstones,
            pad_to=_next_pow2(total),
            uniform_klen=uniform_klen, seq32=seq32, key_words=key_words,
            to_host=False,
        )
        if arrays is None:
            # kernel flagged limb-overflow risk: the whole stream
            # declines and the caller's CPU/tuple fallback handles it
            raise _StreamDecline("device kernel flagged cpu fallback")
        return arrays, count

    def collect(self, handle) -> Tuple[dict, int]:
        arrays, count = handle
        return {f: np.asarray(a) for f, a in arrays.items()}, count
