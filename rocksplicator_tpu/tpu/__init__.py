"""TPU offload backend (the new part — BASELINE.json north star).

``TpuCompactionBackend`` plugs into the storage engine's
CompactionBackend seam; ``TpuCompactionService`` batches shard-level
compaction/ingest jobs across a device mesh.
"""

from .backend import TpuCompactionBackend, NumpyCompactionBackend
from .compaction_service import TpuCompactionService

__all__ = [
    "TpuCompactionBackend", "NumpyCompactionBackend", "TpuCompactionService",
]
