"""lockwatch: runtime lock-order watchdog — the dynamic corroboration of
``tools/rstpu_check.py`` pass 1.

Armed via ``RSTPU_LOCKWATCH=1`` (raise on violation) or
``RSTPU_LOCKWATCH=warn`` (count on /stats + log once per edge), checked
at package import so chaos-harness child processes arm themselves from
the inherited environment. When armed, :func:`install` replaces
``threading.Lock``/``threading.RLock`` with tracking wrappers; every
lock constructed AFTERWARDS records

- a per-thread held-set (cleared on release, recursion-counted for
  RLocks), and
- a process-global acquired-while-holding edge set, keyed by the lock's
  CONSTRUCTION SITE (file:line) — the same instance-agnostic identity
  the static pass uses, which is also how live locks map back to the
  static ranks in ``testing/lock_order.py``.

An acquisition violates when (a) its static rank is below a held lock's
rank — the canonical order learned from the static graph — or (b) it
closes a cycle in the dynamically-observed edge graph (covers locks the
static pass cannot see: locals, per-key ObjectLock internals, stdlib).
``Condition.wait``'s release/re-acquire goes through ``_release_save`` /
``_acquire_restore`` and is exempt from order checks, as in every
lock-order sanitizer: the re-acquire after a wait legitimately inverts
the textual order.

Zero-cost when unarmed BY CONSTRUCTION: nothing is patched, every lock
in the process is the stock ``_thread`` primitive, and the only cost
ever paid is this module's import (PERF.md round 12 records the
kill-switch A/B).
"""

from __future__ import annotations

import _thread
import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

__all__ = [
    "LockOrderViolation", "install", "uninstall", "maybe_install",
    "installed", "reset_for_test", "edges",
]

_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock

# repo root for construction-site keys relative to it (matches the
# static pass's repo-relative paths in lock_order.RANKS)
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PKG_DIR)


class LockOrderViolation(AssertionError):
    """Out-of-canonical-order or cycle-closing lock acquisition."""


_installed = False
_mode = "raise"                # "raise" | "warn"
_ranks: Dict[str, Tuple[str, int]] = {}   # site -> (name, rank)
# static PARTIAL order: (before_site, after_site) pairs from the
# transitive closure of the static graph — acquiring `before` while
# holding `after` is a violation; unrelated pairs are unconstrained
_static_order: Set[Tuple[str, str]] = set()
# dynamic edge graph over construction sites; guarded by a RAW lock so
# tracking can never recurse into itself
_graph_lock = _thread.allocate_lock()
_edges: Dict[str, Set[str]] = {}
_warned: Set[Tuple[str, str]] = set()
_tls = threading.local()


def _load_static() -> Tuple[Dict[str, Tuple[str, int]],
                            Set[Tuple[str, str]]]:
    try:
        from .lock_order import ORDER, RANKS
        return dict(RANKS), set(ORDER)
    except Exception:  # generated file absent: dynamic checks only
        return {}, set()


def _held() -> List["_Entry"]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


class _Entry:
    __slots__ = ("lock", "count")

    def __init__(self, lock) -> None:
        self.lock = lock
        self.count = 1


def _site_of_caller() -> str:
    # first frame outside this module = the `threading.Lock()` call site
    f = sys._getframe(1)
    here = __file__
    while f is not None and f.f_code.co_filename == here:
        f = f.f_back
    if f is None:  # pragma: no cover
        return "ext/unknown:0"
    fn = f.f_code.co_filename
    try:
        rel = os.path.relpath(fn, _REPO_ROOT)
    except ValueError:  # different drive (windows); keep absolute
        rel = fn
    if rel.startswith(".."):  # outside the repo (stdlib etc.)
        rel = "ext/" + os.path.basename(fn)
    return f"{rel}:{f.f_lineno}"


def _name_of(site: str) -> str:
    info = _ranks.get(site)
    return info[0] if info else site


def _reaches(src: str, dst: str) -> bool:
    """dst reachable from src in the dynamic edge graph (caller holds
    _graph_lock)."""
    stack, seen = [src], {src}
    while stack:
        n = stack.pop()
        if n == dst:
            return True
        for m in _edges.get(n, ()):
            if m not in seen:
                seen.add(m)
                stack.append(m)
    return False


def _violation(kind: str, new_site: str, held_site: str) -> None:
    msg = (f"lock-order violation ({kind}): acquiring "
           f"{_name_of(new_site)} while holding {_name_of(held_site)} "
           f"(canonical order is the reverse; see "
           f"testing/lock_order.py and tools/rstpu_check.py)")
    if _mode == "warn":
        key = (held_site, new_site)
        with _graph_lock:
            fresh = key not in _warned
            if fresh:
                _warned.add(key)
        if fresh:
            try:
                from ..utils.stats import Stats, tagged

                Stats.get().incr(tagged("lockwatch.violations", kind=kind))
            except Exception:
                pass
            print(f"lockwatch: {msg}", file=sys.stderr)
        return
    raise LockOrderViolation(msg)


def _note_acquire(wlock, *, checked: bool = True) -> None:
    held = _held()
    for e in held:
        if e.lock is wlock:
            e.count += 1          # reentrant RLock: no new ordering fact
            return
    if checked:
        new_site = wlock._site
        for e in held:
            held_site = e.lock._site
            if held_site == new_site:
                continue          # same class+site pair: instances
            if (new_site, held_site) in _static_order:
                # static graph says new comes BEFORE held
                _violation("static-order", new_site, held_site)
            with _graph_lock:
                closes = _reaches(new_site, held_site)
                if not closes:
                    _edges.setdefault(held_site, set()).add(new_site)
            if closes:
                _violation("dynamic-cycle", new_site, held_site)
    held.append(_Entry(wlock))


def _note_release(wlock) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i].lock is wlock:
            held[i].count -= 1
            if held[i].count == 0:
                del held[i]
            return
    # release of a lock acquired before install/by another thread: ignore


class _WatchedLockBase:
    _site: str

    def __init__(self, inner) -> None:
        self._inner = inner
        self._site = _site_of_caller()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            try:
                _note_acquire(self)
            except LockOrderViolation:
                # don't leak the just-acquired inner lock under the
                # raising `with` statement (its __exit__ never runs)
                self._inner.release()
                raise
        return ok

    acquire_lock = acquire  # legacy alias some stdlib code uses

    def release(self) -> None:
        self._inner.release()
        _note_release(self)

    release_lock = release

    def __enter__(self):
        return self.acquire()

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def locked(self) -> bool:
        return self._inner.locked()

    # -- Condition protocol ------------------------------------------------
    # Condition binds these at construction; wait()'s release/re-acquire
    # must keep the held-set truthful but is EXEMPT from order checks.

    def _release_save(self):
        inner_save = getattr(self._inner, "_release_save", None)
        state = inner_save() if inner_save else self._inner.release()
        held = _held()
        entry = None
        for i in range(len(held) - 1, -1, -1):
            if held[i].lock is self:
                entry = held[i]
                del held[i]
                break
        return (state, entry)

    def _acquire_restore(self, saved):
        state, entry = saved
        inner_restore = getattr(self._inner, "_acquire_restore", None)
        if inner_restore:
            inner_restore(state)
        else:
            self._inner.acquire()
        if entry is not None:
            _held().append(entry)
        else:
            _note_acquire(self, checked=False)

    def _is_owned(self) -> bool:
        inner_owned = getattr(self._inner, "_is_owned", None)
        if inner_owned:
            return inner_owned()
        return any(e.lock is self for e in _held())

    def _at_fork_reinit(self) -> None:  # pragma: no cover
        reinit = getattr(self._inner, "_at_fork_reinit", None)
        if reinit:
            reinit()
        _tls.held = []

    def __repr__(self) -> str:
        return f"<lockwatch {self._inner!r} @ {self._site}>"


class _WatchedLock(_WatchedLockBase):
    def __init__(self) -> None:
        super().__init__(_ORIG_LOCK())


class _WatchedRLock(_WatchedLockBase):
    def __init__(self) -> None:
        super().__init__(_ORIG_RLOCK())


def install(mode: str = "raise") -> None:
    """Patch ``threading.Lock``/``RLock`` so every lock constructed from
    now on is order-tracked. Locks that already exist stay stock (they
    keep working; they just aren't watched)."""
    global _installed, _mode, _ranks, _static_order
    if _installed:
        _mode = mode
        return
    _ranks, _static_order = _load_static()
    _mode = mode
    threading.Lock = _WatchedLock
    threading.RLock = _WatchedRLock
    _installed = True


def uninstall() -> None:
    """Restore the stock primitives (already-wrapped locks keep their
    inner lock and keep functioning)."""
    global _installed
    threading.Lock = _ORIG_LOCK
    threading.RLock = _ORIG_RLOCK
    _installed = False


def installed() -> bool:
    return _installed


def maybe_install() -> bool:
    """Arm from the environment (``RSTPU_LOCKWATCH=1`` or ``=warn``);
    called at package import so child processes arm themselves."""
    val = os.environ.get("RSTPU_LOCKWATCH", "")
    if val == "1":
        install("raise")
    elif val == "warn":
        install("warn")
    else:
        return False
    return True


def edges() -> Dict[str, Set[str]]:
    with _graph_lock:
        return {k: set(v) for k, v in _edges.items()}


def reset_for_test() -> None:
    with _graph_lock:
        _edges.clear()
        _warned.clear()
    _tls.held = []
