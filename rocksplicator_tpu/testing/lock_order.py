"""Canonical lock-acquisition order — GENERATED, do not edit.

Regenerate with:
  python -m tools.rstpu_check --emit-lock-order \
      > rocksplicator_tpu/testing/lock_order.py
Verified fresh by `make check` (--check-lock-order).

ORDER is the transitive closure of the static
acquired-while-holding graph (tools/rstpu_check.py pass 1),
keyed by lock construction site: (A, B) present means A is
canonically acquired before B, so a live acquisition of A while
holding B is a violation. RANKS names each known lock and gives
a topological rank for humans reading reports; pairs the static
graph never relates are constrained only by the lockwatch
runtime's dynamic cycle detection.
"""

# construction site (repo-relative file:line) -> (name, rank)
RANKS = {
    "rocksplicator_tpu/replication/ack_window.py:127": ('AckWindow._cond', 0),
    "rocksplicator_tpu/admin/handler.py:161": ('AdminHandler._db_admin_lock', 1),
    "rocksplicator_tpu/admin/ingest_pipeline.py:123": ('BatchCompactor._lock', 2),
    "rocksplicator_tpu/storage/sst.py:99": ('BlockCache._instance_lock', 3),
    "rocksplicator_tpu/storage/sst.py:103": ('BlockCache._lock', 4),
    "rocksplicator_tpu/kafka/network.py:91": ('BrokerHandler._log_lock', 5),
    "rocksplicator_tpu/admin/cdc.py:103": ('CdcAdminHandler._lock', 6),
    "rocksplicator_tpu/admin/cdc.py:42": ('CdcDbWrapper._lock', 7),
    "rocksplicator_tpu/storage/stream_merge.py:127": ('CompactionMemoryBudget._instance_lock', 8),
    "rocksplicator_tpu/storage/stream_merge.py:131": ('CompactionMemoryBudget._lock', 9),
    "rocksplicator_tpu/utils/rate_limiter.py:25": ('ConcurrentRateLimiter._lock', 10),
    "rocksplicator_tpu/cluster/coordinator.py:303": ('CoordinatorServer._snapshot_mutex', 11),
    "rocksplicator_tpu/storage/engine.py:276": ('DB._compaction_mutex', 12),
    "rocksplicator_tpu/utils/dbconfig.py:48": ('DBConfigManager._instance_lock', 13),
    "rocksplicator_tpu/cluster/publishers.py:69": ('DedupPublisher._lock', 14),
    "rocksplicator_tpu/utils/concurrent_map.py:22": ('FastReadMap._write_lock', 15),
    "rocksplicator_tpu/utils/file_watcher.py:44": ('FileWatcher._lock', 16),
    "rocksplicator_tpu/utils/flags.py:34": ('FlagRegistry._lock', 17),
    "rocksplicator_tpu/utils/graceful_shutdown.py:30": ('GracefulShutdownHandler._lock', 18),
    "rocksplicator_tpu/utils/hot_key_detector.py:27": ('HotKeyDetector._lock', 19),
    "rocksplicator_tpu/admin/ingest_pipeline.py:51": ('IngestGate._lock', 20),
    "rocksplicator_tpu/storage/compaction_scheduler.py:118": ('IoBudget._fg_cv', 21),
    "rocksplicator_tpu/storage/compaction_scheduler.py:117": ('IoBudget._fg_lock', 22),
    "rocksplicator_tpu/rpc/ioloop.py:37": ('IoLoop._default_lock', 23),
    "rocksplicator_tpu/replication/iter_cache.py:41": ('IterCache._lock', 24),
    "rocksplicator_tpu/kafka/watcher.py:165": ('KafkaBrokerFileWatcher._lock', 25),
    "rocksplicator_tpu/kafka/watcher.py:191": ('KafkaBrokerFileWatcherManager._lock', 26),
    "rocksplicator_tpu/kafka/wire.py:573": ('KafkaWireBroker._lock', 27),
    "rocksplicator_tpu/kafka/wire.py:861": ('KafkaWireConsumer._lock', 28),
    "rocksplicator_tpu/kafka/wire.py:1090": ('KafkaWireProducer._lock', 29),
    "rocksplicator_tpu/replication/ack_window.py:57": ('MaxNumberBox._cond', 30),
    "rocksplicator_tpu/storage/stream_merge.py:176": ('MemTracker._lock', 31),
    "rocksplicator_tpu/admin/cdc.py:79": ('MemoryPublisher._lock', 32),
    "rocksplicator_tpu/kafka/broker.py:49": ('MockKafkaCluster._cond', 33),
    "rocksplicator_tpu/utils/file_watcher.py:173": ('MultiFilePoller._lock', 34),
    "rocksplicator_tpu/utils/object_lock.py:18": ('ObjectLock._guard', 35),
    "rocksplicator_tpu/cluster/participant.py:76": ('Participant._publish_lock', 36),
    "rocksplicator_tpu/replication/replicated_db.py:175": ('ReplicatedDB._ack_state_lock', 37),
    "rocksplicator_tpu/replication/replicated_db.py:152": ('ReplicatedDB._epoch_lock', 38),
    "rocksplicator_tpu/replication/replicated_db.py:181": ('ReplicatedDB._expiry_lock', 39),
    "rocksplicator_tpu/replication/replicated_db.py:272": ('ReplicatedDB._write_traces_lock', 40),
    "rocksplicator_tpu/replication/replicator.py:46": ('Replicator._instance_lock', 41),
    "rocksplicator_tpu/utils/retry_policy.py:77": ('RetryBudget._lock', 42),
    "rocksplicator_tpu/utils/s3_stub.py:48": ('S3StubServer.lock', 43),
    "rocksplicator_tpu/observability/collector.py:47": ('SpanCollector._instance_lock', 44),
    "rocksplicator_tpu/utils/ssl_context_manager.py:57": ('SslContextManager._lock', 45),
    "rocksplicator_tpu/utils/stats.py:231": ('Stats._buffers_lock', 46),
    "rocksplicator_tpu/utils/stats.py:240": ('Stats._dump_lock', 47),
    "rocksplicator_tpu/utils/stats.py:212": ('Stats._instance_lock', 48),
    "rocksplicator_tpu/utils/status_server.py:31": ('StatusServer._instance_lock', 49),
    "rocksplicator_tpu/rpc/admission.py:115": ('TenantAdmission._instance_lock', 50),
    "rocksplicator_tpu/rpc/admission.py:125": ('TenantAdmission._lock', 51),
    "rocksplicator_tpu/rpc/admission.py:67": ('TokenBucket._lock', 52),
    "rocksplicator_tpu/tpu/compaction_service.py:41": ('TpuCompactionService._instance_lock', 53),
    "rocksplicator_tpu/storage/archive.py:63": ('WalArchiver._mutex', 54),
    "rocksplicator_tpu/testing/failpoints.py:129": ('_Site.lock', 55),
    "rocksplicator_tpu/utils/stats.py:200": ('_ThreadBuffer.lock', 56),
    "rocksplicator_tpu/kafka/broker.py:204": ('kafka.broker:_clusters_lock', 57),
    "rocksplicator_tpu/storage/native/binding.py:472": ('storage.native.binding:_native_lock', 58),
    "rocksplicator_tpu/testing/failpoints.py:161": ('testing.failpoints:_lock', 59),
    "rocksplicator_tpu/utils/objectstore.py:379": ('utils.objectstore:_store_cache_lock', 60),
    "rocksplicator_tpu/admin/db_manager.py:20": ('ApplicationDBManager._lock', 61),
    "rocksplicator_tpu/cluster/coordinator.py:296": ('CoordinatorServer._lock', 62),
    "rocksplicator_tpu/storage/engine.py:247": ('DB._lock', 63),
    "rocksplicator_tpu/storage/engine.py:283": ('DB._manifest_mutex', 64),
    "rocksplicator_tpu/utils/file_watcher.py:40": ('FileWatcher._instance_lock', 65),
    "rocksplicator_tpu/cluster/participant.py:75": ('Participant._state_lock', 66),
    "rocksplicator_tpu/utils/stats.py:218": ('Stats._lock', 67),
    "rocksplicator_tpu/storage/compaction_scheduler.py:123": ('IoBudget._lock', 68),
    "rocksplicator_tpu/storage/wal.py:68": ('WalWriter._sync_lock', 69),
}

# static partial order: (acquired-first, acquired-second)
ORDER = {
    ("rocksplicator_tpu/admin/handler.py:161", "rocksplicator_tpu/admin/db_manager.py:20"),
    ("rocksplicator_tpu/cluster/coordinator.py:303", "rocksplicator_tpu/cluster/coordinator.py:296"),
    ("rocksplicator_tpu/cluster/participant.py:76", "rocksplicator_tpu/cluster/participant.py:75"),
    ("rocksplicator_tpu/storage/engine.py:247", "rocksplicator_tpu/storage/compaction_scheduler.py:123"),
    ("rocksplicator_tpu/storage/engine.py:247", "rocksplicator_tpu/storage/wal.py:68"),
    ("rocksplicator_tpu/storage/engine.py:276", "rocksplicator_tpu/storage/compaction_scheduler.py:123"),
    ("rocksplicator_tpu/storage/engine.py:276", "rocksplicator_tpu/storage/engine.py:247"),
    ("rocksplicator_tpu/storage/engine.py:276", "rocksplicator_tpu/storage/engine.py:283"),
    ("rocksplicator_tpu/storage/engine.py:276", "rocksplicator_tpu/storage/wal.py:68"),
    ("rocksplicator_tpu/utils/dbconfig.py:48", "rocksplicator_tpu/utils/file_watcher.py:40"),
    ("rocksplicator_tpu/utils/stats.py:240", "rocksplicator_tpu/utils/stats.py:218"),
}
