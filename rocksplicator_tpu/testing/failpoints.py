"""Deterministic failpoint registry for fault injection at I/O/RPC seams.

The reference runs on 4000+ hosts where disks stall, RPCs hang, and
object stores 500 as a matter of course; the recovery paths that absorb
those faults deserve the same regression coverage as the hot paths they
protect. This module gives every seam we own a NAMED site::

    from rocksplicator_tpu.testing import failpoints as fp
    ...
    fp.hit("wal.fsync")          # may raise FailpointError / sleep
    os.fsync(f.fileno())

and lets tests/chaos harnesses arm those sites with DETERMINISTIC
policies — same seed, same schedule, same failure — via API::

    fp.activate("wal.fsync", "fail_nth:3")
    with fp.failpoint("rpc.frame.send", "torn:0.05@seed7"):
        ...

or environment (picked up at import, one spec per site)::

    RSTPU_FAILPOINTS="wal.fsync=fail_nth:3;rpc.frame.send=torn:0.01@seed7"

Policy grammar (``kind[:arg[:arg2]][@seedN][,one_shot]``):

- ``fail_nth:N``      raise on exactly the Nth hit of the site
- ``fail_first:N``    raise on hits 1..N, then pass (retry-path testing)
- ``fail_prob:P``     raise with probability P (per-site seeded RNG)
- ``delay_ms:D[:P]``  sleep D ms on every hit (or with probability P)
- ``torn:P``          torn write: data sites cut the payload at a
                      deterministic offset and fail (``torn_point``)
- ``@seedN``          seed the site's private RNG (default 0 — fully
                      deterministic out of the box)
- ``,one_shot``       deactivate the site after its first trip

Zero-cost when unset: every entry point checks one module-global boolean
and returns — no dict lookup, no lock (measured sub-µs per site; the
write-path A/B is recorded in PERF.md next to tracing's 11.5 µs budget).
Trips are rare by construction, so the trip path can afford stats
(``failpoint.trips site=<name>`` counters on /stats) and a span tag on
the active sampled trace, which is how a chaos run's trace tree shows
*which* injected fault each recovery path absorbed.

Registered sites live in ``testing/failpoint_registry.py`` (one entry
per seam with a one-line fault description); ``SITES`` below derives
from it and ``tools/rstpu_check.py`` lint-gates the registry against
the actual call sites and test coverage.
"""

from __future__ import annotations

import asyncio
import contextlib
import os
import random
import threading
import time
from typing import Dict, Optional, Tuple

__all__ = [
    "FailpointError", "SITES", "activate", "deactivate", "clear",
    "failpoint", "hit", "async_hit", "pending_delay", "torn_point",
    "is_active", "active_sites", "trip_counts", "load_env",
]

# The canonical registered-site list, derived from the checked-in
# registry (testing/failpoint_registry.py) so the two can never drift.
# activate() REJECTS names not on it (a typo'd site would arm silently,
# inject nothing, and let a chaos run or regression test pass
# vacuously); names starting with "t." or "test." are exempt for unit
# tests of the registry itself. Adding a seam = add its
# fp.hit()/torn_point() call AND a registry entry AND a test/chaos
# reference — tools/rstpu_check.py pass 3 enforces all three.
from .failpoint_registry import REGISTRY as _REGISTRY

SITES = frozenset(_REGISTRY)


class FailpointError(OSError):
    """Raised by a tripped fail-class policy. Subclasses OSError so the
    I/O seams' existing transient-error handling (retry, reconnect,
    degrade) engages exactly as it would for a real EIO/ECONNRESET."""


_KINDS = ("fail_nth", "fail_first", "fail_prob", "delay_ms", "torn")


class _Site:
    """One armed site. Own lock + own RNG: determinism must not depend
    on what other sites (or the global ``random``) are doing."""

    __slots__ = ("name", "spec", "kind", "n", "prob", "delay_s",
                 "one_shot", "hits", "trips", "rng", "lock")

    def __init__(self, name: str, spec: str):
        self.name = name
        self.spec = spec
        self.one_shot = False
        self.n = 0
        self.prob: Optional[float] = None
        self.delay_s = 0.0
        seed = 0
        body = spec.strip()
        for flag in body.split(",")[1:]:
            if flag.strip() == "one_shot":
                self.one_shot = True
            else:
                raise ValueError(f"unknown failpoint flag: {flag!r}")
        body = body.split(",", 1)[0]
        if "@seed" in body:
            body, seed_s = body.rsplit("@seed", 1)
            seed = int(seed_s)
        parts = body.split(":")
        self.kind = parts[0]
        if self.kind not in _KINDS:
            raise ValueError(f"unknown failpoint kind: {self.kind!r}")
        if self.kind in ("fail_nth", "fail_first"):
            self.n = int(parts[1])
        elif self.kind == "fail_prob":
            self.prob = float(parts[1])
        elif self.kind == "torn":
            self.prob = float(parts[1]) if len(parts) > 1 else 1.0
        elif self.kind == "delay_ms":
            self.delay_s = float(parts[1]) / 1000.0
            self.prob = float(parts[2]) if len(parts) > 2 else None
        self.rng = random.Random(seed)
        self.hits = 0
        self.trips = 0
        self.lock = threading.Lock()

    def decide(self) -> Tuple[bool, float]:
        """(tripped, delay_seconds). delay 0.0 means fail; >0 means
        sleep. Counts the hit; caller handles one_shot/record/raise."""
        with self.lock:
            self.hits += 1
            if self.kind == "fail_nth":
                tripped = self.hits == self.n
            elif self.kind == "fail_first":
                tripped = self.hits <= self.n
            elif self.kind in ("fail_prob", "torn"):
                tripped = self.rng.random() < (self.prob or 0.0)
            else:  # delay_ms
                tripped = (self.prob is None
                           or self.rng.random() < self.prob)
            if tripped:
                self.trips += 1
        return tripped, (self.delay_s if self.kind == "delay_ms" else 0.0)

    def torn_cut(self, nbytes: int) -> Optional[int]:
        """Deterministic cut offset in [0, nbytes) when tripped."""
        with self.lock:
            self.hits += 1
            if self.rng.random() >= (self.prob or 0.0):
                return None
            self.trips += 1
            return self.rng.randrange(0, max(1, nbytes))


# module-global fast path: the ONLY cost paid by unarmed processes
_ACTIVE = False
_lock = threading.Lock()
_sites: Dict[str, _Site] = {}
# lifetime trip counts survive deactivate() so harnesses can report
# which faults a finished schedule actually exercised
_lifetime_trips: Dict[str, int] = {}


def activate(name: str, spec: str) -> None:
    """Arm ``name`` with a policy spec (see module docstring grammar).
    Unknown site names are rejected — see :data:`SITES`."""
    global _ACTIVE
    if name not in SITES and not name.startswith(("t.", "test.")):
        raise ValueError(
            f"unknown failpoint site: {name!r} (see failpoints.SITES)")
    site = _Site(name, spec)  # parse/validate before taking the lock
    with _lock:
        _sites[name] = site
        _ACTIVE = True


def deactivate(name: str) -> None:
    global _ACTIVE
    with _lock:
        site = _sites.pop(name, None)
        if site is not None and site.trips:
            _lifetime_trips[name] = (
                _lifetime_trips.get(name, 0) + site.trips)
        if not _sites:
            _ACTIVE = False


def clear() -> None:
    """Disarm every site (lifetime trip counts are kept)."""
    global _ACTIVE
    with _lock:
        for name, site in _sites.items():
            if site.trips:
                _lifetime_trips[name] = (
                    _lifetime_trips.get(name, 0) + site.trips)
        _sites.clear()
        _ACTIVE = False


def reset_for_test() -> None:
    clear()
    with _lock:
        _lifetime_trips.clear()


def is_active(name: str) -> bool:
    return name in _sites


def active_sites() -> Dict[str, str]:
    with _lock:
        return {n: s.spec for n, s in _sites.items()}


def trip_counts() -> Dict[str, int]:
    """site -> lifetime trips (armed sites' live counts included)."""
    with _lock:
        out = dict(_lifetime_trips)
        for name, site in _sites.items():
            if site.trips:
                out[name] = out.get(name, 0) + site.trips
        return out


@contextlib.contextmanager
def failpoint(name: str, spec: str):
    """Scoped activation for tests."""
    activate(name, spec)
    try:
        yield
    finally:
        deactivate(name)


def load_env(spec: Optional[str] = None) -> int:
    """Parse ``RSTPU_FAILPOINTS`` (or an explicit spec string); returns
    the number of sites armed. Called once at import."""
    if spec is None:
        spec = os.environ.get("RSTPU_FAILPOINTS", "")
    n = 0
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        name, _, policy = entry.partition("=")
        activate(name.strip(), policy)
        n += 1
    return n


# ---------------------------------------------------------------------------
# seam entry points
# ---------------------------------------------------------------------------


def hit(name: str) -> None:
    """Visit a site. No-op unless armed; a tripped fail policy raises
    :class:`FailpointError`, a tripped delay policy sleeps in place.
    ``torn`` policies respond only to :func:`torn_point` (data sites
    call both; the tear must happen at the data write, not before)."""
    if not _ACTIVE:
        return
    site = _sites.get(name)
    if site is None or site.kind == "torn":
        return
    tripped, delay = site.decide()
    if not tripped:
        return
    _record_trip(site)
    if delay > 0.0:
        time.sleep(delay)
        return
    raise FailpointError(
        f"failpoint {name} tripped ({site.spec}, hit {site.hits})")


async def async_hit(name: str) -> None:
    """``hit`` for coroutine sites: a delay policy awaits instead of
    blocking the event loop (a stuck connect stalls ONE connection, not
    every shard sharing the loop)."""
    if not _ACTIVE:
        return
    site = _sites.get(name)
    if site is None or site.kind == "torn":
        return
    tripped, delay = site.decide()
    if not tripped:
        return
    _record_trip(site)
    if delay > 0.0:
        await asyncio.sleep(delay)
        return
    raise FailpointError(
        f"failpoint {name} tripped ({site.spec}, hit {site.hits})")


def pending_delay(name: str) -> float:
    """``hit`` for sites on an event-loop thread that can reschedule
    themselves: a tripped delay policy RETURNS the delay (seconds) for
    the caller to apply via ``loop.call_later`` instead of sleeping in
    place and stalling every coroutine sharing the loop; fail policies
    raise as usual. Returns 0.0 when untripped."""
    if not _ACTIVE:
        return 0.0
    site = _sites.get(name)
    if site is None or site.kind == "torn":
        return 0.0
    tripped, delay = site.decide()
    if not tripped:
        return 0.0
    _record_trip(site)
    if delay > 0.0:
        return delay
    raise FailpointError(
        f"failpoint {name} tripped ({site.spec}, hit {site.hits})")


def torn_point(name: str, nbytes: int) -> Optional[int]:
    """Data sites: returns a deterministic cut offset in [0, nbytes)
    when a ``torn`` policy trips, else None. The caller writes the
    prefix and raises :class:`FailpointError` — the peer observes a torn
    frame/record, the writer observes a failed write."""
    if not _ACTIVE:
        return None
    site = _sites.get(name)
    if site is None or site.kind != "torn":
        return None
    cut = site.torn_cut(nbytes)
    if cut is None:
        return None
    _record_trip(site)
    return cut


def _record_trip(site: _Site) -> None:
    """Trip-path accounting (rare): /stats counter + one_shot retirement
    + a tag on the active sampled span so chaos trace trees show which
    fault each recovery absorbed. Must never mask the injected fault."""
    if site.one_shot:
        deactivate(site.name)
    try:
        from ..observability.context import _current
        from ..utils.stats import Stats, tagged

        Stats.get().incr(tagged("failpoint.trips", site=site.name))
        span = _current.get()
        if span is not None and span.sampled:
            span.annotate(failpoint=site.name)
    except Exception:
        pass


load_env()
