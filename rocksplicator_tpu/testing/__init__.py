"""Test/chaos instrumentation that ships inside the production package.

``failpoints`` is the deterministic fault-injection registry threaded
through every I/O and RPC seam; it is a strict no-op unless activated via
API or the ``RSTPU_FAILPOINTS`` env var, so production paths pay one
module-global boolean check per site.
"""

from . import failpoints  # noqa: F401
