"""The checked-in failpoint site registry — single source of truth.

Every ``failpoints.hit/async_hit/pending_delay/torn_point`` call site in
the package must name an entry here, every entry must have at least one
call site, and every entry must be referenced by at least one test or
chaos schedule; ``tools/rstpu_check.py`` (pass 3) enforces all three, so
a seam can neither arm silently under a typo'd name nor rot uncovered.
``failpoints.SITES`` derives from this dict, so activate()'s
unknown-site rejection can never drift from the registry.

Adding a seam = add its fp.hit()/torn_point() call, add the entry here,
and reference it from a test or a chaos schedule (make check fails on
any of the three missing).

Value = one line saying what fault the site injects, for humans reading
`rstpu-check --json` output or a chaos schedule.
"""

from __future__ import annotations

REGISTRY = {
    "wal.append": "WAL record append failure / torn tail",
    "wal.fsync": "WAL group-commit fsync failure or stall",
    "wal.roll": "WAL segment roll failure",
    "manifest.persist": "manifest atomic-write failure",
    "sst.fsync": "SST data/footer fsync failure or stall",
    "sst.ingest_footer": "global-seqno footer rewrite failure mid-ingest",
    "engine.ingest": "engine external-file ingest failure",
    "compact.install": "compaction result install failure",
    "compact.dispatch": "batch-compactor dispatch failure",
    # workload-adaptive compaction scheduler (round 16)
    "compact.pick": "scheduler pick failure (compaction loop retries)",
    "compact.subcompact": "key-range subcompaction slice failure",
    "compact.yield": "IO-budget yield delay / failure on a compaction write",
    # streaming bounded-memory merge (round 17): a fault at either seam
    # kills the pipeline mid-stream — every written output is swept and
    # nothing was installed, so reopen is exactly pre-compaction
    "compact.stream.chunk": "streaming merge chunk resolve failure",
    "compact.stream.refill": "streaming merge window refill failure",
    "objectstore.get": "object-store download failure",
    "objectstore.put": "object-store upload failure",
    "s3.request": "S3 request transient failure",
    "hdfs.request": "WebHDFS request transient failure",
    "rpc.connect": "RPC connect failure or stall",
    "rpc.frame.send": "RPC frame send failure / torn frame",
    "rpc.frame.recv": "RPC frame receive failure",
    "repl.pull": "replication pull RPC failure",
    "repl.apply": "follower apply failure",
    # multiplexed per-peer pull sessions (round 22): serve is the
    # server-side session seam (a fault fails the WHOLE mux response —
    # the torn-session shape; per-SECTION faults ride the per-shard
    # serve path's existing seams), apply is the client-side demux seam
    # hit once per non-empty section before its apply is scheduled
    "repl.mux.serve": "mux session serve failure (whole response)",
    "repl.mux.apply": "mux per-section apply handoff failure",
    "repl.read": "bounded-staleness read-path failure at the replica",
    "router.read_pick": "router read host-pick failure",
    "ack.expire": "ack-window expiry timer blip",
    "coordinator.heartbeat": "coordinator session heartbeat failure",
    "coordinator.reap": "coordinator ephemeral-node reap blip",
    "coordinator.wal.append": "coordinator WAL append failure / torn tail",
    "participant.transition": "participant state-transition failure",
    "shardmap.publish": "spectator shard-map publish failure",
    "controller.assign": "controller assignment-pass failure",
    "admin.ingest.engine": "admin ingest fault before engine ingest",
    "admin.ingest.meta": "admin ingest fault between engine and meta",
    # live shard moves (round 15): one seam per step-machine phase —
    # arming fail_nth:1 on any of them IS the "kill the move
    # coordinator at this phase" chaos schedule (the raise unwinds the
    # mover, leaving the durable record for resume/abort)
    "move.record": "shard-move ledger write failure (any phase entry)",
    "move.snapshot": "shard-move snapshot (backup) phase failure",
    "move.restore": "shard-move bulk-ingest (restore) phase failure",
    "move.catchup": "shard-move WAL-tail catch-up phase failure",
    "move.flip": "shard-move epoch-bumped cutover phase failure",
    "move.retire": "shard-move source-retire phase failure",
    # disaggregated compaction tier (round 18): one seam per handoff —
    # arming fail_nth:1 kills the exchange at that boundary. Leader-side
    # faults (publish/install) fall back to the unchanged local merge;
    # worker-side faults (claim/fetch/upload/heartbeat) fail the job or
    # make the worker look dead, so the leader reaps + republishes.
    "compact.remote.publish": "compaction job ledger publish failure",
    "compact.remote.claim": "worker job-claim failure at the ledger",
    "compact.remote.fetch": "worker input-SST fetch failure",
    "compact.remote.upload": "worker output-SST upload failure",
    "compact.remote.install": "leader-side verified-install failure",
    "compact.remote.heartbeat": "worker liveness heartbeat failure",
    # tail armor (round 19): arming these drives the SHED/DEGRADE paths
    # themselves, not INTERNAL errors — a tripped deadline check forces
    # the DEADLINE_EXCEEDED verdict, a tripped admission check forces a
    # RETRY_LATER shed, and a tripped hedge launch falls back to the
    # plain primary chain (hedging is never a correctness dependency)
    "rpc.deadline.check": "server deadline check forces expired verdict",
    "admission.shed": "tenant admission forces a RETRY_LATER shed",
    "router.hedge.fire": "hedged-read backup launch failure",
    # autonomous rebalancer + hot-shard range splits (round 20): the
    # decide/plan/dispatch seams kill the policy loop between sensing
    # and acting (the tick's work is re-derived from durable ledgers on
    # the next tick); split.cutover kills the splitter AT the fenced
    # flip — the recorded cutover phase resumes idempotently, and the
    # chaos harness's split_cutover break-guard tooth lives on the same
    # seam
    "rebalance.decide": "rebalancer hot-spot decision failure",
    # executor-side sibling of repl.read: a delay policy here holds a
    # dispatch-executor slot while sleeping (no CPU), giving benches a
    # deterministic per-read service cost — the hot-shift A/B's
    # structural serving knee
    "repl.read.serve": "engine-side read execution failure / stall",
    "rebalance.plan": "rebalancer move/split planning failure",
    "rebalance.dispatch": "rebalancer actuator dispatch failure",
    "split.cutover": "shard-split fenced cutover phase failure",
    # CDC streaming ingest (round 21): the three consumer seams — a
    # fail_nth at any of them kills the consumer thread mid-batch; a
    # restart must resume from the WAL-riding watermark exactly-once
    # (the batch either committed with its watermark or neither did)
    "kafka.fetch": "CDC consumer fetch-round failure (pre-drain)",
    "kafka.apply": "CDC grouped-commit apply failure (pre-write)",
    "kafka.checkpoint": "CDC watermark fold failure (pre-checkpoint)",
}
