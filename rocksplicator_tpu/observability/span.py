"""Spans + the ``start_span`` context manager (the only tracing API most
code touches).

Design constraints (ISSUE: the read path targets ~10M Get()/s; the
reference made even *stats* optional there):

- the **unsampled** path must be near-free: one contextvar read, one
  ``random.random()`` roll (roots only), one contextvar set/reset. No
  Span object, no dict copies, no collector traffic.
- spans inside an unsampled trace short-circuit on the NOOP sentinel
  without touching the contextvar at all.
- all cost that exists only for sampled spans (id generation, wall-clock
  read, annotation dict, collector record) is paid at ~sample_rate.

Usage::

    with start_span("repl.write", db=name) as sp:
        ...
        sp.annotate(seq=seq)

``always=True`` marks control-plane operations (backup, restore, manual
compaction) that are rare enough to trace unconditionally. ``remote=ctx``
reattaches a wire/executor context captured via
:func:`~.context.wire_context` — the server-side restore half.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from .context import _current, new_id, valid_wire_context


class Span:
    """One finished-or-running span. Mutable annotations; immutable ids."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name",
        "start_ms", "_t0", "duration_ms", "annotations", "error",
    )

    sampled = True

    def __init__(
        self,
        name: str,
        trace_id: str,
        parent_id: Optional[str],
        annotations: Optional[Dict[str, Any]] = None,
    ):
        self.name = name
        self.trace_id = trace_id
        self.span_id = new_id()
        self.parent_id = parent_id
        self.start_ms = time.time() * 1000.0
        self._t0 = time.perf_counter()
        self.duration_ms: Optional[float] = None
        self.annotations = annotations or {}
        self.error: Optional[str] = None

    def annotate(self, **kv: Any) -> None:
        self.annotations.update(kv)

    def to_wire(self) -> Dict[str, Any]:
        """This span as a wire/header context dict — the ONE place the
        wire shape is built (context.wire_context and every injection
        site use it, so shape changes cannot drift per-site)."""
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "sampled": True,
        }

    def finish(self) -> None:
        if self.duration_ms is None:
            self.duration_ms = (time.perf_counter() - self._t0) * 1000.0

    def to_dict(self, process: str) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "process": process,
            "start_ms": round(self.start_ms, 3),
            "duration_ms": round(self.duration_ms or 0.0, 3),
            "annotations": self.annotations,
            "error": self.error,
        }


class _NoopSpan:
    """Sentinel for 'tracing decided OFF for this subtree'. All methods
    are no-ops; shared singleton, never recorded."""

    __slots__ = ()
    sampled = False
    trace_id = ""
    span_id = ""

    def annotate(self, **kv: Any) -> None:
        pass


NOOP_SPAN = _NoopSpan()


class _TailRoot:
    """A head-UNSAMPLED root under tail-keep (round 14): the deferred
    sampling decision. Cheap enough for every root op — one small
    object, one wall-clock read, one perf_counter read; ``sampled`` is
    False so descendants still take the NOOP fast path (a kept tail
    trace is root-only by design — the decision can't be made until the
    duration is known, by which time the children are gone). On exit,
    a root slower than the collector's ``tail_ms`` is retained in the
    tail ring: the 1023/1024 head-unsampled p99 outlier becomes
    inspectable on /traces instead of invisible."""

    __slots__ = ("name", "t0", "tail_ms", "annotations")
    sampled = False
    trace_id = ""
    span_id = ""

    def __init__(self, name: str, tail_ms: float,
                 annotations: Optional[Dict[str, Any]]):
        self.name = name
        self.t0 = time.perf_counter()
        # threshold cached here so the (common) fast exit never touches
        # the collector singleton; wall-clock start is reconstructed at
        # keep time (start = now - duration) — one fewer syscall per
        # unsampled root
        self.tail_ms = tail_ms
        self.annotations = annotations or {}

    def annotate(self, **kv: Any) -> None:
        self.annotations.update(kv)


class start_span:
    """Context manager creating a span under the active one (or a new
    sampled/unsampled root). See module docstring for the fast-path
    contract."""

    __slots__ = ("_name", "_always", "_remote", "_ann", "_span", "_token")

    def __init__(self, name: str, always: bool = False,
                 remote: Optional[dict] = None, **annotations: Any):
        self._name = name
        self._always = always
        self._remote = remote
        self._ann = annotations
        self._span = NOOP_SPAN
        self._token = None

    def __enter__(self):
        remote = self._remote
        if remote is not None and valid_wire_context(remote) and _enabled():
            # (_enabled(): the RSTPU_TRACING=0 kill switch must silence
            # remotely-initiated spans too, or a disabled node would keep
            # recording and re-propagating peers' trace contexts)
            # An explicit remote context wins over any local parent: the
            # caller is continuing a trace that crossed a process (RPC
            # header) or executor boundary — e.g. a follower's apply span
            # joins the LEADER's write trace even while a local pull span
            # is active (replicated_db._apply_updates).
            span = Span(self._name, remote["trace_id"],
                        remote["span_id"], self._ann)
        else:
            parent = _current.get()
            if parent is not None:
                if not parent.sampled:
                    # inside an unsampled trace: nothing to set or reset
                    return NOOP_SPAN
                span = Span(self._name, parent.trace_id, parent.span_id,
                            self._ann)
            else:
                from .collector import SpanCollector

                col = SpanCollector.get()
                if (self._always and col.enabled) or col.sample():
                    span = Span(self._name, new_id(), None, self._ann)
                elif col.enabled and col.tail_ms > 0.0:
                    # head-unsampled ROOT under tail-keep: defer the
                    # decision to __exit__ (duration known). sampled is
                    # False, so descendants still take the NOOP branch.
                    root = _TailRoot(self._name, col.tail_ms, self._ann)
                    self._span = root
                    self._token = _current.set(root)
                    return root
                else:
                    # unsampled ROOT: park the sentinel so descendants
                    # take the cheap branch above instead of re-rolling
                    # sampling
                    self._token = _current.set(NOOP_SPAN)
                    return NOOP_SPAN
        self._span = span
        self._token = _current.set(span)
        return span

    def __exit__(self, exc_type, exc, tb):
        if self._token is not None:
            _current.reset(self._token)
        span = self._span
        if span is NOOP_SPAN:
            return False
        if type(span) is _TailRoot:
            duration_ms = (time.perf_counter() - span.t0) * 1000.0
            # tail_exempt: the operation declared its slowness is BY
            # DESIGN (a parked long-poll serve, a long-poll pull RTT) —
            # keeping those would fill the tail ring with waits and
            # evict the genuine outliers the ring exists for
            if duration_ms >= span.tail_ms \
                    and "tail_exempt" not in span.annotations:
                from .collector import SpanCollector

                col = SpanCollector.get()
                if col.enabled:
                    col.record_tail(
                        span, duration_ms,
                        error=repr(exc) if exc_type is not None else None)
            return False
        if exc_type is not None and span.error is None:
            span.error = repr(exc)
        span.finish()
        from .collector import SpanCollector

        SpanCollector.get().record(span)
        return False


def detached_span(name: str, parent, **annotations: Any):
    """A child span that outlives the creating stack frame — for
    operations whose completion lands on another thread (an ack-window
    waiter resolved by the loop's expiry timer or a follower ack), where
    ``with start_span(...)`` cannot scope the lifetime.

    Returns ``None`` when the parent is unsampled (callers keep the
    usual near-free unsampled path). The CALLER OWNS COMPLETION: every
    resolution path must call ``.finish()`` and hand the span to
    ``SpanCollector.get().record(...)`` — keep exactly one resolution
    funnel, as AckWindow does. This is the only sanctioned way to build
    a Span outside observability/ (rstpu-check span-manual)."""
    if parent is None or not parent.sampled:
        return None
    return Span(name, parent.trace_id, parent.span_id, dict(annotations))


def _enabled() -> bool:
    from .collector import SpanCollector

    return SpanCollector.get().enabled
