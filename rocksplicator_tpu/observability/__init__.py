"""Distributed tracing subsystem (spans, propagation, collection, export).

One trace follows one request across the rpc → replication → storage
layers (and across processes via the RPC frame header); the per-process
:class:`SpanCollector` ring retains recent sampled spans for the status
server's ``/traces`` (JSON) and ``/traces.txt`` (waterfall) endpoints.

The instrument the perf PRs cite: per-phase attribution of the semi-sync
write (leader receive → WAL fsync → follower ACK), the backup/restore
round trip (checkpoint → upload batches → download), and compaction
(plan → merge → install).
"""

from .collector import SpanCollector, render_trace
from .context import TRACE_KEY, current_span, wire_context
from .span import NOOP_SPAN, Span, start_span

__all__ = [
    "NOOP_SPAN",
    "Span",
    "SpanCollector",
    "TRACE_KEY",
    "current_span",
    "render_trace",
    "start_span",
    "wire_context",
]
