"""Trace context: contextvar-carried active span + wire (de)serialization.

The active span rides a :mod:`contextvars` ContextVar, which gives both
propagation models this codebase needs for free:

- **asyncio**: ``asyncio.create_task`` / ``ensure_future`` snapshot the
  creating task's context, so request-handler subtasks inherit the active
  span without plumbing (the fbthrift RequestContext analog);
- **threads**: each thread has its own context, so the leader write path
  (called from arbitrary writer threads) and background flush/compaction
  threads trace independently.

The one seam contextvars do NOT cross is ``loop.run_in_executor`` (asyncio
submits the bare callable). Callers that hop onto the executor capture
:func:`wire_context` on the event-loop side and reattach it via
``start_span(..., remote=ctx)`` executor-side (see admin/handler.py).

Cross-process propagation uses the same dict: a sampled caller injects
``{"trace_id", "span_id", "sampled"}`` into the RPC message's JSON frame
header under the reserved top-level key ``"trace"`` (rpc/client.py), and
the server reattaches it before dispatch (rpc/server.py).
"""

from __future__ import annotations

import contextvars
import random
from typing import Any, Dict, Optional

# Holds the active Span (sampled) or the NOOP sentinel (an unsampled root
# was opened: descendants must not re-roll sampling or they'd emit orphan
# partial traces). None = no tracing decision made yet at this point.
_current: contextvars.ContextVar = contextvars.ContextVar(
    "rstpu_active_span", default=None
)

TRACE_KEY = "trace"  # reserved top-level key in the RPC message header


def new_id() -> str:
    """64-bit random hex id. random.getrandbits is atomic under the GIL
    and ~10x cheaper than os.urandom — these ids are correlation keys,
    not secrets."""
    return f"{random.getrandbits(64):016x}"


def current_span():
    """The active span object, or None. The unsampled sentinel is
    returned as-is (callers check ``.sampled``)."""
    return _current.get()


def wire_context() -> Optional[Dict[str, Any]]:
    """The active SAMPLED context as a wire/header dict, else None.
    This is the injection half of cross-process (and cross-executor)
    propagation."""
    span = _current.get()
    if span is None or not span.sampled:
        return None
    return span.to_wire()


def valid_wire_context(ctx: Any) -> bool:
    """Defensive validation of a peer-supplied trace header: ids must be
    short alphanumeric strings — they end up verbatim in /traces JSON,
    the /traces.txt waterfall, rpcgrep lines, and the bench's
    marker-delimited trace block, so control characters/newlines would
    let a peer forge output lines in all of those sinks."""
    if not isinstance(ctx, dict) or ctx.get("sampled") is not True:
        return False
    tid, sid = ctx.get("trace_id"), ctx.get("span_id")
    return (
        isinstance(tid, str) and isinstance(sid, str)
        and 0 < len(tid) <= 64 and 0 < len(sid) <= 64
        and tid.isalnum() and sid.isalnum()
    )
