"""SpanCollector: per-process ring buffer of finished spans + exporters.

Reference points: RESYSTANCE / "Characterize LSM-tree Compaction
Performance" (PAPERS.md) argue per-phase timing — not aggregate counters —
is what exposes hidden stalls; this is the in-process, sample-gated
equivalent for this stack.

Write path ("lock-free-ish"): finished spans land in a fixed-size ring via
``next(itertools.count())`` (atomic under the GIL) + a slot store — no
lock, no allocation beyond the span's export dict. Memory is bounded by
``capacity``; once the ring wraps, the oldest spans are overwritten and
counted in ``dropped`` (the read side reports it, so a truncated window
is never mistaken for complete coverage).

Head sampling: the sampling decision is made once at the trace ROOT
(``sample()``, default ~1/1024) and inherited by every descendant,
including across process hops (the wire context carries ``sampled``).
``sample_rate=0`` disables tracing; the instrumented hot paths then cost
one contextvar read + one roll per would-be root.

Read path (cold): ``traces()`` groups the ring by trace id,
``to_json_text()`` feeds the status server's ``/traces`` endpoint and
``waterfall_text()`` renders the human ``/traces.txt`` view.
"""

from __future__ import annotations

import itertools
import json
import os
import random
import threading
from typing import Any, Dict, List, Optional

DEFAULT_CAPACITY = 4096
DEFAULT_SAMPLE_RATE = 1.0 / 1024.0
# Tail-keep (round 14): a head-UNSAMPLED root whose duration exceeds
# this is retained anyway — the deferred-decision buffer that makes the
# macro-bench's knee-point p99 outliers inspectable instead of
# 1023/1024 invisible. 0 disables; RSTPU_TRACING=0 still kills all.
DEFAULT_TAIL_MS = 100.0
DEFAULT_TAIL_CAPACITY = 256


class SpanCollector:
    _instance: Optional["SpanCollector"] = None
    _instance_lock = threading.Lock()

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 sample_rate: float = DEFAULT_SAMPLE_RATE):
        self._capacity = max(1, int(capacity))
        self._ring: List[Optional[dict]] = [None] * self._capacity
        self._seq = itertools.count()
        self._recorded = 0  # highest seq observed + 1 (approximate is fine)
        env_rate = os.environ.get("RSTPU_TRACE_SAMPLE_RATE")
        if env_rate is not None:
            # the singleton is constructed lazily inside the first traced
            # hot-path op: a malformed env value must degrade to the
            # default, never raise out of an application write/RPC
            try:
                sample_rate = float(env_rate)
            except ValueError:
                pass
        self.sample_rate = float(sample_rate)
        # tail-keep threshold: env-tunable, malformed values degrade to
        # the default (same stance as the sample-rate env above)
        tail_ms = DEFAULT_TAIL_MS
        env_tail = os.environ.get("RSTPU_TRACE_TAIL_MS")
        if env_tail is not None:
            try:
                tail_ms = float(env_tail)
            except ValueError:
                pass
        self.tail_ms = tail_ms
        # separate small ring for tail-kept roots so head-sampled
        # traffic can never evict the rare slow outlier — the whole
        # point of keeping it
        self._tail_ring: List[Optional[dict]] = [None] * DEFAULT_TAIL_CAPACITY
        self._tail_seq = itertools.count()
        self._tail_recorded = 0
        # global kill switch: RSTPU_TRACING=0 disables EVERYTHING,
        # including always=True control-plane spans — the ops escape
        # hatch when any tracing overhead at all is unwanted
        self.enabled = os.environ.get("RSTPU_TRACING", "1") != "0"
        # joined into every exported span so cross-process traces remain
        # attributable after stitching; services may relabel (e.g.
        # "leader:9091") via configure()
        self.process = f"pid:{os.getpid()}"

    # -- singleton --------------------------------------------------------

    @classmethod
    def get(cls) -> "SpanCollector":
        inst = cls._instance
        if inst is None:
            with cls._instance_lock:
                if cls._instance is None:
                    cls._instance = cls()
                inst = cls._instance
        return inst

    @classmethod
    def reset_for_test(cls) -> None:
        with cls._instance_lock:
            cls._instance = cls()

    # -- config -----------------------------------------------------------

    def configure(self, sample_rate: Optional[float] = None,
                  capacity: Optional[int] = None,
                  process: Optional[str] = None,
                  tail_ms: Optional[float] = None) -> None:
        if sample_rate is not None:
            self.sample_rate = float(sample_rate)
        if tail_ms is not None:
            self.tail_ms = float(tail_ms)
        if process is not None:
            self.process = process
        if capacity is not None and int(capacity) != self._capacity:
            self._capacity = max(1, int(capacity))
            self._ring = [None] * self._capacity
            self._seq = itertools.count()
            self._recorded = 0

    # -- hot write path ---------------------------------------------------

    def sample(self) -> bool:
        rate = self.sample_rate
        return self.enabled and rate > 0.0 and random.random() < rate

    def record(self, span) -> None:
        """Called once per finished SAMPLED span (span.py __exit__)."""
        d = span.to_dict(self.process)
        i = next(self._seq)
        ring = self._ring
        ring[i % len(ring)] = d
        self._recorded = i + 1

    def record_tail(self, root, duration_ms: float,
                    error: Optional[str] = None) -> None:
        """Keep a head-unsampled root that crossed the tail threshold
        (span.py ``_TailRoot`` exit). Ids are minted HERE — only kept
        tails pay for id generation. The span dict carries a
        ``tail_kept`` annotation so /traces readers can tell a deferred
        keep (root-only by construction) from a head-sampled trace."""
        import time

        from .context import new_id

        d = {
            "trace_id": new_id(),
            "span_id": new_id(),
            "parent_id": None,
            "name": root.name,
            "process": self.process,
            # wall-clock start reconstructed at keep time — the fast
            # (discarded) path never pays the time.time() syscall
            "start_ms": round(time.time() * 1000.0 - duration_ms, 3),
            "duration_ms": round(duration_ms, 3),
            "annotations": {**root.annotations, "tail_kept": True},
            "error": error,
        }
        i = next(self._tail_seq)
        ring = self._tail_ring
        ring[i % len(ring)] = d
        self._tail_recorded = i + 1
        try:
            from ..utils.stats import Stats

            Stats.get().incr("trace.tail_kept")
        except Exception:  # pragma: no cover - defensive
            pass

    # -- cold read path ---------------------------------------------------

    @property
    def recorded(self) -> int:
        return self._recorded

    @property
    def dropped(self) -> int:
        """Spans overwritten before they could be read (ring evictions)."""
        return max(0, self._recorded - self._capacity)

    @property
    def tail_kept(self) -> int:
        """Head-unsampled roots retained by the tail path."""
        return self._tail_recorded

    @property
    def tail_dropped(self) -> int:
        return max(0, self._tail_recorded - len(self._tail_ring))

    def snapshot(self) -> List[dict]:
        """All retained spans — head-sampled AND tail-kept — oldest
        first (by wall-clock start)."""
        spans = [d for d in list(self._ring) if d is not None]
        spans.extend(d for d in list(self._tail_ring) if d is not None)
        spans.sort(key=lambda d: d["start_ms"])
        return spans

    def traces(self, trace_id: Optional[str] = None,
               limit: int = 64) -> List[Dict[str, Any]]:
        """Retained spans grouped per trace, newest trace first. Each
        entry: {trace_id, start_ms, duration_ms, span_count, spans}."""
        by_trace: Dict[str, List[dict]] = {}
        for d in self.snapshot():
            by_trace.setdefault(d["trace_id"], []).append(d)
        out = []
        for tid, spans in by_trace.items():
            if trace_id is not None and tid != trace_id:
                continue
            start = min(s["start_ms"] for s in spans)
            end = max(s["start_ms"] + s["duration_ms"] for s in spans)
            out.append({
                "trace_id": tid,
                "start_ms": start,
                "duration_ms": round(end - start, 3),
                "span_count": len(spans),
                "spans": spans,
            })
        out.sort(key=lambda t: t["start_ms"], reverse=True)
        return out[:limit]

    def slowest_trace(self, root_name: str) -> Optional[Dict[str, Any]]:
        """The retained trace whose ROOT span (a span whose parent is not
        in the trace) named ``root_name`` has the largest duration — the
        bench's slowest-shard attribution hook. Returns
        ``{"root": span_dict, "trace": trace_dict}`` or None."""
        best = None
        for tr in self.traces(limit=self._capacity):
            ids = {s["span_id"] for s in tr["spans"]}
            for s in tr["spans"]:
                if s["name"] != root_name or s["parent_id"] in ids:
                    continue
                if best is None or s["duration_ms"] > best["root"]["duration_ms"]:
                    best = {"root": s, "trace": tr}
        return best

    def phase_totals(self, prefix: str) -> Dict[str, Dict[str, float]]:
        """Aggregate retained span durations by name, for names starting
        with ``prefix``: {name: {count, total_ms, max_ms}}. Feeds the
        bench's per-phase JSON breakdown."""
        out: Dict[str, Dict[str, float]] = {}
        for d in self.snapshot():
            name = d["name"]
            if not name.startswith(prefix):
                continue
            agg = out.setdefault(
                name, {"count": 0, "total_ms": 0.0, "max_ms": 0.0})
            agg["count"] += 1
            agg["total_ms"] = round(agg["total_ms"] + d["duration_ms"], 3)
            agg["max_ms"] = max(agg["max_ms"], d["duration_ms"])
        return out

    def to_json_text(self, limit: int = 64) -> str:
        """The ``/traces`` status-server endpoint body."""
        return json.dumps({
            "process": self.process,
            "sample_rate": self.sample_rate,
            "capacity": self._capacity,
            "recorded": self.recorded,
            "dropped": self.dropped,
            "tail_ms": self.tail_ms,
            "tail_kept": self.tail_kept,
            "tail_dropped": self.tail_dropped,
            "traces": self.traces(limit=limit),
        }, indent=1, default=str)

    def waterfall_text(self, trace_id: Optional[str] = None,
                       limit: int = 16) -> str:
        """Human-readable per-trace waterfall (``/traces.txt``)."""
        lines: List[str] = [
            f"# spans recorded={self.recorded} dropped={self.dropped} "
            f"sample_rate={self.sample_rate:g} "
            f"tail_kept={self.tail_kept} tail_ms={self.tail_ms:g} "
            f"process={self.process}",
        ]
        for tr in self.traces(trace_id=trace_id, limit=limit):
            lines.append("")
            lines.append(
                f"trace {tr['trace_id']}  spans={tr['span_count']}  "
                f"total={tr['duration_ms']:.3f} ms"
            )
            lines.extend(render_trace(tr["spans"], tr["start_ms"]))
        return "\n".join(lines) + "\n"


def render_trace(spans: List[dict], t0_ms: Optional[float] = None
                 ) -> List[str]:
    """Indented waterfall lines for one trace's span dicts. Spans whose
    parent is missing from the set (e.g. evicted, or living in another
    process's collector) render as roots — a stitched multi-process trace
    passes the union of every process's spans here."""
    if not spans:
        return []
    if t0_ms is None:
        t0_ms = min(s["start_ms"] for s in spans)
    ids = {s["span_id"] for s in spans}
    children: Dict[Optional[str], List[dict]] = {}
    for s in spans:
        parent = s["parent_id"] if s["parent_id"] in ids else None
        children.setdefault(parent, []).append(s)
    for sibs in children.values():
        sibs.sort(key=lambda s: s["start_ms"])
    lines: List[str] = []

    def walk(span: dict, depth: int) -> None:
        off = span["start_ms"] - t0_ms
        ann = " ".join(
            f"{k}={v}" for k, v in sorted(span["annotations"].items()))
        err = f" ERROR={span['error']}" if span.get("error") else ""
        name = "  " * depth + span["name"]
        lines.append(
            f"  {name:<40} +{off:9.3f} ms  {span['duration_ms']:9.3f} ms"
            f"  [{span['process']}]{(' ' + ann) if ann else ''}{err}"
        )
        for c in children.get(span["span_id"], []):
            walk(c, depth + 1)

    for root in children.get(None, []):
        walk(root, 0)
    return lines
