"""CDC consumer-offset checkpoints that ride the engine WAL.

The exactly-once contract: the (topic, partition, offset) watermark is a
reserved key PUT into the *same* WriteBatch as the records it covers.
One batch = one WAL record = crash-atomic, so after any crash the
durable watermark names exactly the prefix of the partition log whose
effects are present — the consumer reopens, reads the watermark, seeks
to ``offset``, and skips any re-delivered message below it. Dedup is
keyed on the watermark, never on record contents.

Two reserved keys per (topic, partition):

- the **watermark** (``wm``): ``{"offset": next-offset-to-consume,
  "applied": records-applied-total, "ts_ms": last-record-timestamp}`` —
  the checkpoint the consumer resumes from;
- the **applies counter** (``ap``): a plain integer incremented by the
  record count of every apply batch (read-modify-write by the single
  consumer thread, committed atomically with the records). With the
  checkpoint riding the batch the two can never diverge; a checkpoint
  decoupled from its batch (the ``cdc_dedup`` chaos tooth) leaves the
  counter ahead of the watermark after a kill/resume — the witness the
  exactly-once invariant checks, robust even though record applies are
  idempotent upserts.

Keys live under the reserved ``\\x00cdc\\x00`` prefix (the engine's
internal-metadata namespace: range trims — retain_lo/retain_hi — never
drop reserved-prefix keys, so a split child keeps its CDC state).
"""

from __future__ import annotations

import json
from typing import Optional

# keys below \x01 are the engine's reserved metadata namespace; range
# filters (DBOptions.retain_lo/hi) always retain them
CDC_KEY_PREFIX = b"\x00cdc\x00"


def watermark_key(topic: str, partition: int) -> bytes:
    return CDC_KEY_PREFIX + b"wm\x00" + topic.encode("utf-8") + \
        b"\x00%d" % partition


def applies_key(topic: str, partition: int) -> bytes:
    return CDC_KEY_PREFIX + b"ap\x00" + topic.encode("utf-8") + \
        b"\x00%d" % partition


def encode_watermark(offset: int, applied: int, ts_ms: int) -> bytes:
    return json.dumps(
        {"offset": int(offset), "applied": int(applied),
         "ts_ms": int(ts_ms)},
        sort_keys=True).encode("utf-8")


def decode_watermark(raw: Optional[bytes]) -> Optional[dict]:
    """None for a missing/garbled watermark (treated as 'never
    checkpointed' — the consumer falls back to the timestamp seek)."""
    if not raw:
        return None
    try:
        d = json.loads(bytes(raw).decode("utf-8"))
        return {"offset": int(d["offset"]), "applied": int(d["applied"]),
                "ts_ms": int(d.get("ts_ms", 0))}
    except (ValueError, KeyError, TypeError, UnicodeDecodeError):
        return None


def read_watermark(engine_db, topic: str, partition: int
                   ) -> Optional[dict]:
    return decode_watermark(engine_db.get(watermark_key(topic, partition)))


def read_applies(engine_db, topic: str, partition: int) -> int:
    raw = engine_db.get(applies_key(topic, partition))
    if not raw:
        return 0
    try:
        return int(bytes(raw).decode("ascii"))
    except (ValueError, UnicodeDecodeError):
        return 0
