"""KafkaWatcher: replay-then-tail consumption with hooks.

Reference: common/kafka/kafka_watcher.{h,cpp}:42-168,141-350 — owns the
consume thread; first a blocking replay from the configured start
timestamp up to "now" (``ConsumeUpToNow``), then the live tail loop;
virtual hooks let subclasses process messages and observe replay
completion. Also KafkaConsumerPool (bounded consumer reuse) and the
broker-serverset file watcher.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, List, Optional, Sequence

from ..utils.file_watcher import FileWatcher
from ..utils.stats import Stats
from .broker import Consumer, Message

log = logging.getLogger(__name__)


class KafkaWatcher:
    """Consume thread with replay + live phases.

    Subclass (or pass callbacks) to handle messages:
    - ``on_message(msg, is_replay)`` per message;
    - ``on_replay_done()`` once caught up to the start-time watermark.
    """

    def __init__(
        self,
        name: str,
        consumer: Consumer,
        topic: str,
        partitions: Sequence[int],
        start_timestamp_ms: int = 0,
        on_message: Optional[Callable[[Message, bool], None]] = None,
        on_replay_done: Optional[Callable[[], None]] = None,
        poll_timeout_sec: float = 0.2,
    ):
        self.name = name
        self._consumer = consumer
        self._topic = topic
        self._partitions = list(partitions)
        self._start_ts = start_timestamp_ms
        self._on_message = on_message
        self._on_replay_done = on_replay_done
        self._poll_timeout = poll_timeout_sec
        self._stop = threading.Event()
        self.replay_done = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.messages_processed = 0
        self.last_timestamp_ms = 0

    # -- hooks (overridable) ----------------------------------------------

    def handle_message(self, msg: Message, is_replay: bool) -> None:
        if self._on_message:
            self._on_message(msg, is_replay)

    def handle_replay_done(self) -> None:
        if self._on_replay_done:
            self._on_replay_done()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "KafkaWatcher":
        self._consumer.assign(self._topic, self._partitions)
        if self._start_ts > 0:
            self._consumer.seek_to_timestamp(self._start_ts)
        self._thread = threading.Thread(
            target=self._run, name=f"kafka-watcher-{self.name}", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        stats = Stats.get()
        # replay phase: consume up to the high watermarks captured now
        # (ConsumeUpToNow, kafka_watcher.cpp:141-233)
        watermarks = {
            p: self._consumer.high_watermark(p) for p in self._partitions
        }

        def caught_up() -> bool:
            return all(
                self._consumer.position(p) >= watermarks[p]
                for p in self._partitions
            )

        while not self._stop.is_set() and not caught_up():
            msg = self._consumer.consume(self._poll_timeout)
            if msg is None:
                continue
            self._dispatch(msg, is_replay=True, stats=stats)
        if not self._stop.is_set():
            self.replay_done.set()
            try:
                self.handle_replay_done()
            except Exception:
                log.exception("%s: replay-done hook failed", self.name)
        # live tail loop (kafka_watcher.cpp:235-350)
        while not self._stop.is_set():
            msg = self._consumer.consume(self._poll_timeout)
            if msg is None:
                continue
            self._dispatch(msg, is_replay=False, stats=stats)

    def _dispatch(self, msg: Message, is_replay: bool, stats) -> None:
        try:
            self.handle_message(msg, is_replay)
            self.messages_processed += 1
            self.last_timestamp_ms = max(self.last_timestamp_ms, msg.timestamp_ms)
            stats.incr("kafka.messages_consumed")
            if is_replay:
                stats.incr("kafka.messages_replayed")
        except Exception:
            stats.incr("kafka.message_errors")
            log.exception("%s: message handler failed @%s/%d:%d",
                          self.name, msg.topic, msg.partition, msg.offset)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        # Best-effort: a networked consumer's commit RPC can fail when the
        # broker is down — the watcher must still stop cleanly and close
        # its consumer (the reference ignores commit errors on teardown).
        try:
            self._consumer.commit()
        except Exception:
            log.warning("%s: final commit failed (broker down?)", self.name,
                        exc_info=True)
        finally:
            self._consumer.close()


class KafkaConsumerPool:
    """Bounded reusable consumer pool (common/kafka/kafka_consumer_pool)."""

    def __init__(self, size: int, factory: Callable[[], Consumer]):
        self._queue: "queue.Queue[Consumer]" = queue.Queue()
        for _ in range(size):
            self._queue.put(factory())

    def acquire(self, timeout: float = 10.0) -> Consumer:
        return self._queue.get(timeout=timeout)

    def release(self, consumer: Consumer) -> None:
        self._queue.put(consumer)


class KafkaBrokerFileWatcher:
    """Broker serverset file → live broker list
    (common/kafka/kafka_broker_file_watcher): one 'host:port' per line,
    hot-reloaded."""

    def __init__(self, serverset_path: str):
        self._path = serverset_path
        self._lock = threading.Lock()
        self._brokers: List[str] = []
        FileWatcher.instance().add_file(serverset_path, self._on_content)

    def _on_content(self, content: bytes) -> None:
        brokers = [
            line.strip() for line in content.decode("utf-8").splitlines()
            if line.strip() and not line.startswith("#")
        ]
        with self._lock:
            self._brokers = brokers

    @property
    def broker_list(self) -> List[str]:
        with self._lock:
            return list(self._brokers)

    def close(self) -> None:
        FileWatcher.instance().remove_file(self._path, self._on_content)


class KafkaBrokerFileWatcherManager:
    """Singleton dedup of broker-list watchers keyed by serverset path
    (rocksdb_admin/detail/kafka_broker_file_watcher_manager)."""

    _instance: Optional["KafkaBrokerFileWatcherManager"] = None
    _lock = threading.Lock()

    def __init__(self) -> None:
        self._watchers: dict = {}

    @classmethod
    def instance(cls) -> "KafkaBrokerFileWatcherManager":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def get_file_watcher(self, serverset_path: str) -> KafkaBrokerFileWatcher:
        with self._lock:
            w = self._watchers.get(serverset_path)
            if w is None:
                w = KafkaBrokerFileWatcher(serverset_path)
                self._watchers[serverset_path] = w
            return w
