"""QueuePublisher: CDC observer updates → message queue.

Reference: the CDC observer's custom DbWrapper "publishes updates (e.g. to
Kafka) instead of persisting" (cdc_admin, SURVEY §2.2). This is the queue
-producer implementation of the CdcAdminHandler ``Publisher`` callable.
"""

from __future__ import annotations

from ..utils.segment_utils import extract_shard_id
from .broker import MockKafkaCluster, get_cluster


class QueuePublisher:
    def __init__(self, topic: str, cluster: MockKafkaCluster | None = None,
                 num_partitions: int = 16):
        self._cluster = cluster or get_cluster()
        self._topic = topic
        self._num_partitions = num_partitions
        self._cluster.create_topic(topic, num_partitions)

    def __call__(self, db_name: str, start_seq: int, raw: bytes,
                 timestamp_ms) -> None:
        shard = extract_shard_id(db_name)
        partition = shard % self._num_partitions if shard >= 0 else 0
        self._cluster.produce(
            self._topic, partition,
            key=f"{db_name}:{start_seq}".encode(),
            value=raw,
            timestamp_ms=timestamp_ms,
        )
